"""The micro-batch streaming engine + the retrain->redeploy loop.

Covers: offset/commit WAL semantics and crash/restart exactly-once
(the acceptance pin: a kill between sink write and commit-log append
replays the batch under the same id and an idempotent sink dedupes),
watermark/window goldens with late data, backpressure (EWMA rate
adaptation + RetryPolicy/terminal failure), the upgraded
FileStreamSource engine protocol, TrafficCapture <-> TrafficLogSource
round trips, fit_stream incremental training with flip-eligible
exports, and the full end-to-end loop: live fleet -> capture ->
fit_stream -> RetrainLoop -> POST /rollout -> new version serving.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.resilience import ManualClock, RetryPolicy
from mmlspark_tpu.streaming import (
    MemoryStreamSource, StreamingQuery, WindowSpec,
)
from mmlspark_tpu.streaming.traffic import TrafficLogSource
from mmlspark_tpu.serving.capture import TrafficCapture


class RecordingSink:
    """Idempotent-by-batch-id sink with a crash hook: raises AFTER
    recording (the 'sink wrote, commit never landed' crash window)."""

    def __init__(self):
        self.seen = set()
        self.rows_by_batch = {}
        self.calls = []
        self.crash_on = None

    def process(self, bid, df):
        self.calls.append(bid)
        if bid not in self.seen:
            self.seen.add(bid)
            self.rows_by_batch[bid] = df.num_rows
        if self.crash_on == bid:
            self.crash_on = None
            raise RuntimeError("injected crash between sink and commit")


def _rows(n, t0=0.0):
    return [{"x": float(i), "t": t0 + float(i)} for i in range(n)]


class TestEngineBasics:
    def test_batches_flow_and_wal_written(self, tmp_path):
        src = MemoryStreamSource()
        sink = RecordingSink()
        q = StreamingQuery(src, sink, checkpoint_dir=str(tmp_path),
                           name="basic", max_batch_rows=4)
        src.add_rows(_rows(10))
        n = q.process_available()
        assert n == 3                      # 4 + 4 + 2
        assert sink.calls == [1, 2, 3]
        assert sum(sink.rows_by_batch.values()) == 10
        assert q.n_batches == 3 and q.n_rows == 10
        # one offset + one commit file per batch, atomic JSON
        offs = sorted(os.listdir(tmp_path / "offsets"))
        coms = sorted(os.listdir(tmp_path / "commits"))
        assert offs == coms == [f"{i:08d}.json" for i in (1, 2, 3)]
        with open(tmp_path / "commits" / "00000003.json") as f:
            assert json.load(f)["batch_id"] == 3

    def test_transform_applied_before_sink(self):
        src = MemoryStreamSource()
        got = []
        q = StreamingQuery(
            src, lambda bid, df: got.append(df["y"].tolist()),
            transform=lambda df: df.with_column(
                "y", np.asarray(df["x"]) * 2))
        src.add_rows(_rows(3))
        q.process_available()
        assert got == [[0.0, 2.0, 4.0]]

    def test_empty_source_is_idle_not_a_batch(self):
        q = StreamingQuery(MemoryStreamSource(), RecordingSink())
        assert q.process_available() == 0
        assert q.n_batches == 0

    def test_threaded_start_stop(self, tmp_path):
        src = MemoryStreamSource()
        sink = RecordingSink()
        q = StreamingQuery(src, sink, checkpoint_dir=str(tmp_path),
                           trigger_interval_s=0.02, name="threaded")
        q.start()
        src.add_rows(_rows(5))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and q.n_rows < 5:
            time.sleep(0.01)
        q.stop()
        assert q.n_rows == 5
        assert q.state == "terminated"
        assert q.await_termination(1.0)


class TestExactlyOnce:
    """The acceptance pin: crash between sink write and commit append,
    restart from the checkpoint dir, sink saw the batch exactly once."""

    def test_crash_between_sink_and_commit_replays_batch(self, tmp_path):
        ckpt = str(tmp_path / "wal")
        src = MemoryStreamSource()
        src.add_rows(_rows(8))
        sink = RecordingSink()
        q = StreamingQuery(src, sink, checkpoint_dir=ckpt,
                           max_batch_rows=4, name="crash",
                           retry_policy=RetryPolicy(max_attempts=1))
        q.process_available(max_batches=1)       # batch 1 committed
        sink.crash_on = 2
        with pytest.raises(RuntimeError, match="injected crash"):
            q.process_available()
        assert q.state == "failed"
        assert "injected crash" in q.status()["error"]
        # batch 2's offset is logged, its commit is not
        assert os.path.exists(
            os.path.join(ckpt, "offsets", "00000002.json"))
        assert not os.path.exists(
            os.path.join(ckpt, "commits", "00000002.json"))

        # "restart": fresh source re-populated (the durable-source
        # analogue), fresh query on the same checkpoint dir. The
        # sink's dedupe store survives, as a transactional sink's must.
        src2 = MemoryStreamSource()
        src2.add_rows(_rows(8))
        q2 = StreamingQuery(src2, sink, checkpoint_dir=ckpt,
                            max_batch_rows=4, name="crash",
                            retry_policy=RetryPolicy(max_attempts=1))
        q2.process_available()
        # batch 2 was replayed (same id), the sink deduped it, and
        # every row was processed exactly once
        assert q2.n_replayed_batches == 1
        assert sink.calls.count(2) == 2          # offered twice...
        assert sum(sink.rows_by_batch.values()) == 8   # ...counted once
        assert sorted(sink.seen) == [1, 2]

    def test_recovery_reacks_committed_offsets(self, tmp_path):
        """Crash between commit append and source ack: recovery re-acks
        so the source's cursor catches up instead of re-planning
        committed rows as a NEW batch id."""
        ckpt = str(tmp_path / "wal")
        src = MemoryStreamSource()
        src.add_rows(_rows(4))
        q = StreamingQuery(src, RecordingSink(), checkpoint_dir=ckpt,
                           name="reack")
        q.process_available()
        # simulate the torn ack: a fresh source with the same rows but
        # a zeroed cursor (what a durable source's stale journal is)
        src2 = MemoryStreamSource()
        src2.add_rows(_rows(4))
        sink2 = RecordingSink()
        q2 = StreamingQuery(src2, sink2, checkpoint_dir=ckpt,
                            name="reack")
        assert q2.process_available() == 0       # nothing re-planned
        assert sink2.calls == []


class TestWatermarksAndWindows:
    def test_tumbling_window_golden(self, tmp_path):
        clock = ManualClock()
        src = MemoryStreamSource()
        emitted = []

        def sink(bid, df):
            for i in range(df.num_rows):
                emitted.append((float(df["window_start"][i]),
                                float(df["window_end"][i]),
                                int(df["n"][i]), float(df["sx"][i])))

        q = StreamingQuery(
            src, sink, name="win", checkpoint_dir=str(tmp_path),
            event_time_col="t", watermark_delay_s=2.0,
            window=WindowSpec(5.0, aggs={"n": ("count", None),
                                         "sx": ("sum", "x")}),
            clock=clock)
        src.add_rows([{"x": 1.0, "t": 1.0}, {"x": 2.0, "t": 4.0}])
        q.process_available()
        assert emitted == []                     # wm = 2.0: nothing closed
        assert q.watermark == pytest.approx(2.0)
        src.add_rows([{"x": 3.0, "t": 6.0}, {"x": 4.0, "t": 8.5}])
        q.process_available()
        # wm = 6.5: window [0, 5) closes with its two rows
        assert emitted == [(0.0, 5.0, 2, 3.0)]
        assert q.watermark == pytest.approx(6.5)
        # late row (t=3.0 < wm): counted, excluded from state
        src.add_rows([{"x": 100.0, "t": 3.0}])
        q.process_available()
        assert q.n_late_rows == 1
        src.add_rows([{"x": 5.0, "t": 12.5}])
        q.process_available()
        # wm = 10.5: window [5, 10) closes WITHOUT the late 100.0
        assert emitted[-1] == (5.0, 10.0, 2, 7.0)

    def test_sliding_windows_multi_assign(self):
        src = MemoryStreamSource()
        emitted = []

        def sink(bid, df):
            for i in range(df.num_rows):
                emitted.append((float(df["window_start"][i]),
                                int(df["n"][i])))

        q = StreamingQuery(
            src, sink, name="slide", event_time_col="t",
            window=WindowSpec(4.0, slide_s=2.0,
                              aggs={"n": ("count", None)}))
        # t=3 lands in windows [0,4) and [2,6)
        src.add_rows([{"t": 3.0}])
        q.process_available()
        src.add_rows([{"t": 10.0}])              # wm=10: both close
        q.process_available()
        assert (0.0, 1) in emitted and (2.0, 1) in emitted

    def test_watermark_monotone_and_recovered(self, tmp_path):
        ckpt = str(tmp_path / "wal")
        src = MemoryStreamSource()
        q = StreamingQuery(src, RecordingSink(), checkpoint_dir=ckpt,
                           name="wm", event_time_col="t",
                           watermark_delay_s=1.0)
        src.add_rows([{"t": 10.0}])
        q.process_available()
        src.add_rows([{"t": 5.0}])               # regression: wm holds
        q.process_available()
        assert q.watermark == pytest.approx(9.0)
        q2 = StreamingQuery(MemoryStreamSource(), RecordingSink(),
                            checkpoint_dir=ckpt, name="wm",
                            event_time_col="t", watermark_delay_s=1.0)
        assert q2.watermark == pytest.approx(9.0)   # from the commit log

    def test_window_state_survives_restart(self, tmp_path):
        ckpt = str(tmp_path / "wal")
        spec = WindowSpec(10.0, aggs={"n": ("count", None),
                                      "sx": ("sum", "x")})
        src = MemoryStreamSource()
        q = StreamingQuery(src, RecordingSink(), checkpoint_dir=ckpt,
                           name="state", event_time_col="t", window=spec)
        src.add_rows([{"x": 1.0, "t": 1.0}, {"x": 2.0, "t": 3.0}])
        q.process_available()                    # window [0,10) open
        emitted = []

        def sink(bid, df):
            emitted.append((int(df["n"][0]), float(df["sx"][0])))

        # durable-source analogue: the already-committed rows are still
        # at positions the recovery re-ack will skip past
        src2 = MemoryStreamSource()
        src2.add_rows([{"x": 1.0, "t": 1.0}, {"x": 2.0, "t": 3.0}])
        q2 = StreamingQuery(src2, sink,
                            checkpoint_dir=ckpt, name="state",
                            event_time_col="t", window=spec)
        src2.add_rows([{"x": 4.0, "t": 15.0}])   # closes [0,10)
        q2.process_available()
        # the restarted query finalized the window with the PRE-crash
        # partial aggregates restored from the commit log
        assert emitted == [(2, 3.0)]


class TestBackpressure:
    def test_rate_adapts_down_on_slow_sink_and_back_up(self):
        clock = ManualClock()
        src = MemoryStreamSource()
        slow = {"ms": 1000.0}

        def sink(bid, df):
            clock.advance(slow["ms"] / 1000.0)

        q = StreamingQuery(src, sink, name="bp", max_batch_rows=64,
                           min_batch_rows=1, target_batch_ms=100.0,
                           clock=clock)
        for _ in range(6):
            src.add_rows(_rows(64))
            q.process_available()
        assert q.status()["rows_limit"] < 64     # pushed down
        floor = q.status()["rows_limit"]
        slow["ms"] = 1.0                         # sink recovers
        for _ in range(10):
            src.add_rows(_rows(64))
            q.process_available()
        assert q.status()["rows_limit"] > floor  # recovered

    def test_sink_retries_then_succeeds(self):
        src = MemoryStreamSource()
        attempts = []

        def flaky(bid, df):
            attempts.append(bid)
            if len(attempts) < 3:
                raise IOError("transient")

        q = StreamingQuery(
            src, flaky, name="retry",
            retry_policy=RetryPolicy(max_attempts=4, base=0.001,
                                     cap=0.002))
        src.add_rows(_rows(2))
        q.process_available()
        assert attempts == [1, 1, 1]             # same batch, in place
        assert q.n_sink_retries == 2
        assert q.n_batches == 1 and q.state != "failed"

    def test_retries_exhausted_is_terminal(self):
        src = MemoryStreamSource()

        def dead(bid, df):
            raise IOError("sink down")

        q = StreamingQuery(
            src, dead, name="dead",
            retry_policy=RetryPolicy(max_attempts=2, base=0.001,
                                     cap=0.002))
        src.add_rows(_rows(1))
        with pytest.raises(IOError):
            q.process_available()
        assert q.state == "failed"
        assert q.n_sink_failures == 1
        st = q.status()
        assert "sink down" in st["error"]
        # a failed query refuses further driving
        with pytest.raises(Exception):
            q.run_once()


class TestFileSourceEngine:
    def test_plan_read_ack_and_resume(self, tmp_path):
        from mmlspark_tpu.io.streaming import FileStreamSource
        data = tmp_path / "data"
        data.mkdir()
        ckpt = str(tmp_path / "progress.json")
        (data / "a.bin").write_bytes(b"one")
        (data / "b.bin").write_bytes(b"two")
        src = FileStreamSource(str(data), checkpoint_location=ckpt)
        sink = RecordingSink()
        q = StreamingQuery(src, sink, checkpoint_dir=str(tmp_path / "wal"),
                           name="files")
        q.process_available()
        assert sum(sink.rows_by_batch.values()) == 2
        # planned-not-re-planned: an immediate second pass is idle
        assert q.process_available() == 0
        # resume: a fresh source instance + fresh query skip old files
        (data / "c.bin").write_bytes(b"three")
        src2 = FileStreamSource(str(data), checkpoint_location=ckpt)
        sink2 = RecordingSink()
        q2 = StreamingQuery(src2, sink2,
                            checkpoint_dir=str(tmp_path / "wal"),
                            name="files")
        q2.process_available()
        assert sum(sink2.rows_by_batch.values()) == 1
        assert q2.batch_id == 2                  # ids continue past WAL


class TestTrafficCapture:
    class _P:
        def __init__(self, i, payload=None):
            self.rid = f"r{i}"
            self.trace = f"trace{i}"
            self.payload = payload or {"x": [float(i)], "label": i % 2}
            self.reply = b'{"scores": [0.25]}'

    def test_rows_round_trip_with_meta(self, tmp_path):
        cap = TrafficCapture(str(tmp_path))
        cap.offer("v1", [self._P(i) for i in range(5)])
        cap.stop()
        src = TrafficLogSource(str(tmp_path))
        df = src.read(src.plan())
        assert df.num_rows == 5
        assert df["rid"][0] == "r0" and df["trace_id"][2] == "trace2"
        assert set(df["version"]) == {"v1"}
        assert df["x"][3] == [3.0]
        assert df["scores"][0] == [0.25]

    def test_segment_rotation_and_prune(self, tmp_path):
        cap = TrafficCapture(str(tmp_path), max_segment_bytes=256,
                             max_segments=3)
        for i in range(40):
            cap.offer("v1", [self._P(i)])
            cap.flush()
        cap.stop()
        segs = [n for n in os.listdir(tmp_path) if n.endswith(".jsonl")]
        assert 1 <= len(segs) <= 3
        assert cap.n_segments_rotated > 0
        assert cap.n_segments_pruned > 0

    def test_offer_never_blocks_when_writer_behind(self, tmp_path,
                                                   monkeypatch):
        cap = TrafficCapture(str(tmp_path), queue_depth=1)
        monkeypatch.setattr(cap, "_ensure_writer", lambda: None)
        cap.offer("v1", [self._P(0)])
        cap.offer("v1", [self._P(1)])            # queue full -> drop
        assert cap.n_dropped_batches == 1

    def test_batch_sampling(self, tmp_path, monkeypatch):
        cap = TrafficCapture(str(tmp_path), sample_every=2,
                             queue_depth=64)
        monkeypatch.setattr(cap, "_ensure_writer", lambda: None)
        for i in range(6):
            cap.offer("v1", [self._P(i)])
        assert cap._q.qsize() == 3               # every 2nd batch

    def test_torn_tail_not_planned_until_complete(self, tmp_path):
        seg = tmp_path / "segment-000001.jsonl"
        good = json.dumps({"kind": "traffic", "t": 1.0, "rid": "a",
                           "request": {"x": 1}}).encode()
        seg.write_bytes(good + b"\n" + b'{"kind": "traffic", "t"')
        src = TrafficLogSource(str(tmp_path))
        meta = src.plan()
        df = src.read(meta)
        assert df.num_rows == 1                  # the torn tail waits
        src.ack(meta)
        # the tail completes -> it becomes plannable
        with open(seg, "ab") as f:
            f.write(b': 2.0, "rid": "b", "request": {"x": 2}}\n')
        df2 = src.read(src.plan())
        assert df2.num_rows == 1 and df2["rid"][0] == "b"

    def test_cursor_resumes_across_instances(self, tmp_path):
        cap = TrafficCapture(str(tmp_path / "w"))
        cap.offer("v1", [self._P(i) for i in range(4)])
        cap.stop()
        src = TrafficLogSource(str(tmp_path / "w"))
        meta = src.plan(2)
        src.read(meta)
        src.ack(meta)
        src2 = TrafficLogSource(str(tmp_path / "w"))
        df = src2.read(src2.plan())
        assert df.num_rows == 2                  # only the unacked half

    def test_shadow_rows_kind_filtered(self, tmp_path):
        cap = TrafficCapture(str(tmp_path), shadow_rows_per_batch=2)
        df = DataFrame({"x": [1.0, 2.0, 3.0]})
        live = df.with_column("scores", [0.1, 0.2, 0.3])
        shadow = df.with_column("scores", [0.1, 0.9, 0.3])
        cap.offer_shadow("v1", "v2", df, live, shadow)
        cap.stop()
        assert cap.n_shadow_rows == 2            # bounded per batch
        src = TrafficLogSource(str(tmp_path))    # default: traffic only
        meta = src.plan()
        assert meta is not None          # lines plan; kinds filter at read
        assert src.read(meta).num_rows == 0
        src_all = TrafficLogSource(str(tmp_path),
                                   kinds=("traffic", "shadow"),
                                   cursor_path=str(tmp_path / "c2.json"))
        rows = src_all.read(src_all.plan())
        assert rows.num_rows == 2
        assert rows["kind"][0] == "shadow"
        assert rows["live_scores"][1] == 0.2
        assert rows["shadow_scores"][1] == 0.9


class TestServerCapture:
    def test_live_server_captures_committed_rows(self, tmp_path):
        import requests
        from mmlspark_tpu.serving import ServingServer, TrafficCapture
        from mmlspark_tpu.stages import ScaleColumn

        cap = TrafficCapture(str(tmp_path / "cap"))
        with ServingServer(ScaleColumn(input_col="x", output_col="y",
                                       scale=2.0),
                           max_latency_ms=1, max_batch_size=4,
                           capture=cap, slow_trace_ms=None) as srv:
            for i in range(6):
                r = requests.post(
                    srv.address, json={"x": float(i)},
                    headers={"X-Request-Id": f"rid-{i}",
                             "X-Trace-Id": f"trace{i}"}, timeout=5)
                assert r.status_code == 200
            stats = requests.get(
                f"http://{srv.host}:{srv.port}/stats", timeout=5).json()
            assert stats["capture"]["directory"] == cap.directory
            metrics = requests.get(
                f"http://{srv.host}:{srv.port}/metrics",
                timeout=5).text
            assert "serving_capture_rows_total" in metrics
        # server stop flushed the writer
        src = TrafficLogSource(str(tmp_path / "cap"))
        df = src.read(src.plan())
        assert df.num_rows == 6
        assert sorted(df["rid"]) == [f"rid-{i}" for i in range(6)]
        assert all(t.startswith("trace") for t in df["trace_id"])
        assert set(df["version"]) == {"v1"}
        ys = {float(np.asarray(v).reshape(-1)[0]) for v in df["y"]}
        assert ys == {0.0, 2.0, 4.0, 6.0, 8.0, 10.0}

    def test_shadow_output_sampling_rides_capture(self, tmp_path):
        import requests
        from mmlspark_tpu.serving import ServingServer, TrafficCapture
        from mmlspark_tpu.stages import ScaleColumn

        cap = TrafficCapture(str(tmp_path / "cap"))
        with ServingServer(ScaleColumn(input_col="x", output_col="y",
                                       scale=2.0),
                           max_latency_ms=1, max_batch_size=4,
                           capture=cap, slow_trace_ms=None) as srv:
            srv.warmup({"x": 0.0})
            srv.versions.stage(
                model=ScaleColumn(input_col="x", output_col="y",
                                  scale=3.0),
                version="v2", shadow_fraction=1.0, sync=True)
            for i in range(8):
                requests.post(srv.address, json={"x": 1.0}, timeout=5)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and cap.n_shadow_rows == 0:
                time.sleep(0.02)
        assert cap.n_shadow_rows > 0
        src = TrafficLogSource(str(tmp_path / "cap"), kinds=("shadow",),
                               cursor_path=str(tmp_path / "c.json"))
        df = src.read(src.plan())
        assert df.num_rows > 0
        i = 0
        assert df["version"][i] == "v1"
        assert df["staged_version"][i] == "v2"
        # live 2x vs staged 3x on x=1.0: the diff evidence, row-aligned
        assert float(np.asarray(df["live_y"][i]).reshape(-1)[0]) == 2.0
        assert float(np.asarray(df["shadow_y"][i]).reshape(-1)[0]) == 3.0


def _mlp_learner(ckpt_dir):
    from mmlspark_tpu.models.trainer import NNLearner
    return NNLearner(arch={"builder": "mlp", "hidden": [4],
                           "num_outputs": 1},
                     features_col="x", label_col="label",
                     loss="squared_error", optimizer="adam",
                     learning_rate=0.02, batch_size=16,
                     checkpoint_dir=ckpt_dir)


def _seed_traffic(capdir, n, seed=0):
    rng = np.random.default_rng(seed)

    class P:
        def __init__(self, i):
            x = rng.normal(size=2)
            self.rid = f"seed-{seed}-{i}"
            self.trace = f"t{i}"
            self.payload = {"x": x.tolist(), "label": float(x.sum())}
            self.reply = b'{"scores": [0.0]}'

    cap = TrafficCapture(capdir)
    cap.offer("v1", [P(i) for i in range(n)])
    cap.stop()


class TestFitStream:
    def test_trains_and_exports_flip_eligible_checkpoints(self, tmp_path):
        from mmlspark_tpu.io.checkpoint import verify_digest
        capdir = str(tmp_path / "cap")
        _seed_traffic(capdir, 32)
        fit = _mlp_learner(str(tmp_path / "train")).fit_stream(
            TrafficLogSource(capdir),
            export_dir=str(tmp_path / "exp"), export_every_batches=1,
            checkpoint_dir=str(tmp_path / "wal"), max_batch_rows=16)
        fit.query.process_available()
        st = fit.status()["trainer"]
        assert st["n_batches_trained"] >= 1
        assert st["n_rows_trained"] == 32
        assert st["n_exports"] >= 1
        for path in fit.exports:
            ok, detail = verify_digest(path, strict=True)
            assert ok, detail            # every export is flip-eligible
        # the exported model scores
        from mmlspark_tpu.core.stage import PipelineStage
        m = PipelineStage.load(fit.exports[-1])
        out = m.transform(DataFrame({"x": np.zeros((2, 2))}))
        assert out["scores"].shape[0] == 2

    def test_crash_mid_loop_replay_is_skipped_exactly_once(self, tmp_path):
        """The acceptance pin inside the loop: kill the query between
        the trainer-sink write (train + checkpoint) and the commit-log
        append, restart from the same checkpoints, and the replayed
        batch id is detected and skipped — no batch trains twice."""
        capdir = str(tmp_path / "cap")
        _seed_traffic(capdir, 48)
        wal, train = str(tmp_path / "wal"), str(tmp_path / "train")

        def make():
            return _mlp_learner(train).fit_stream(
                TrafficLogSource(capdir),
                export_dir=str(tmp_path / "exp"),
                export_every_batches=1,           # high-water each batch
                checkpoint_dir=wal, max_batch_rows=16,
                retry_policy=RetryPolicy(max_attempts=1))

        fit = make()
        inner = fit.query.sink

        class Crasher:                    # crash AFTER sink-side effects
            def process(self, bid, df):
                inner.process(bid, df)
                if bid == 2:
                    raise RuntimeError("injected kill")

        fit.query.sink = Crasher()
        with pytest.raises(RuntimeError, match="injected kill"):
            fit.query.process_available()
        assert fit.query.state == "failed"
        run1 = inner.status()
        assert run1["last_trained_batch"] == 2    # batch 2 DID train

        fit2 = make()
        fit2.query.process_available()
        st = fit2.status()
        assert st["query"]["n_replayed_batches"] == 1
        assert st["trainer"]["n_replays_skipped"] == 1   # batch 2 skipped
        # exactly-once: every captured row trained exactly once overall
        assert run1["n_rows_trained"] \
            + st["trainer"]["n_rows_trained"] == 48


class TestRetrainRedeployLoop:
    """The headline acceptance: traffic served -> captured -> streamed
    into fit_stream -> flip-eligible export -> RetrainLoop drives
    POST /rollout through the canary -> the fleet serves the retrained
    version with zero downtime and zero dropped/wrong replies."""

    def test_end_to_end_loop(self, tmp_path):
        import requests
        from mmlspark_tpu.core.stage import PipelineStage
        from mmlspark_tpu.models.function import NNFunction
        from mmlspark_tpu.models.nn import NNModel
        from mmlspark_tpu.serving import (
            ServingCoordinator, ServingServer, TrafficCapture)
        from mmlspark_tpu.streaming import RetrainLoop

        # v1: an untrained tiny MLP, persisted + digest-manifested
        fn = NNFunction.init({"builder": "mlp", "hidden": [4],
                              "num_outputs": 1}, (2,), seed=0)
        v1_dir = str(tmp_path / "v1")
        NNModel(model=fn, input_col="x", output_col="scores").save(v1_dir)
        capdir = str(tmp_path / "cap")
        warm = {"x": [0.0, 0.0], "label": 0.0}

        cap = TrafficCapture(capdir)
        workers = []
        coord = ServingCoordinator().start()
        try:
            for i in range(2):
                srv = ServingServer(
                    PipelineStage.load(v1_dir), max_batch_size=4,
                    max_latency_ms=1, model_version="v1",
                    capture=cap if i == 0 else None,
                    slow_trace_ms=None)
                srv.warmup(warm)
                srv.start()
                ServingCoordinator.register_worker(
                    f"http://{coord.host}:{coord.port}",
                    srv.host, srv.port)
                workers.append(srv)

            # -- background traffic for the WHOLE test (zero-downtime
            # evidence): every reply must be a well-formed 200
            rng = np.random.default_rng(7)
            stop = threading.Event()
            results = {"ok": 0, "bad": []}

            def traffic():
                i = 0
                while not stop.is_set():
                    x = rng.normal(size=2)
                    srv = workers[i % 2]
                    try:
                        r = requests.post(
                            srv.address,
                            json={"x": x.tolist(),
                                  "label": float(x.sum())},
                            headers={"X-Request-Id": f"e2e-{i}"},
                            timeout=10)
                        body = r.json()
                        if r.status_code == 200 and "scores" in body:
                            results["ok"] += 1
                        else:
                            results["bad"].append(
                                (i, r.status_code, body))
                    except Exception as e:  # noqa: BLE001
                        results["bad"].append((i, "exc", str(e)))
                    i += 1
                    time.sleep(0.005)

            t = threading.Thread(target=traffic, daemon=True)
            t.start()

            # -- stream captured traffic into the trainer until it has
            # exported at least one flip-eligible checkpoint
            fit = _mlp_learner(str(tmp_path / "train")).fit_stream(
                TrafficLogSource(capdir),
                export_dir=str(tmp_path / "exp"),
                export_every_batches=2,
                checkpoint_dir=str(tmp_path / "wal"),
                max_batch_rows=16)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not fit.exports:
                fit.query.process_available()
                time.sleep(0.05)
            assert fit.exports, "fit_stream never exported a checkpoint"

            # -- the retrain loop pushes it through the canary gates
            loop = RetrainLoop(
                str(tmp_path / "exp"),
                f"http://{coord.host}:{coord.port}",
                warmup_payload=warm,
                poll_interval_s=0.1,
                rollout={"canary": True, "canary_min_requests": 4,
                         "canary_window_s": 3.0,
                         "stage_timeout_s": 60.0}).start()
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline and loop.n_completed == 0 \
                    and loop.n_failed == 0 and loop.n_rolled_back == 0:
                time.sleep(0.1)
            loop.stop()
            stop.set()
            t.join(timeout=10)

            status = loop.status()
            assert loop.n_completed == 1, status
            new_version = status["history"][-1]["version"]
            assert new_version.startswith("r")

            # -- the fleet is coherent on the retrained version and
            # still answering
            versions = set()
            for srv in workers:
                v = requests.get(
                    f"http://{srv.host}:{srv.port}/version",
                    timeout=5).json()
                versions.add(v["active"]["version"])
                assert v["active"]["state"] == "active"
            assert versions == {new_version}
            for srv in workers:
                r = requests.post(srv.address,
                                  json={"x": [0.0, 0.0], "label": 0.0},
                                  timeout=10)
                assert r.status_code == 200 and "scores" in r.json()

            # -- zero downtime, zero dropped, zero wrong replies
            assert results["bad"] == []
            assert results["ok"] > 0
            # the loop's audit trail shows the completed canary rollout
            assert status["history"][-1]["state"] == "completed"
        finally:
            stop.set()
            for srv in workers:
                srv.stop()
            coord.stop()


class TestReviewHardening:
    def test_unlabeled_rows_never_kill_the_retrain_query(self, tmp_path):
        """Real traffic mixes labeled (feedback) and unlabeled (plain
        inference) rows: label-less / None-holed / malformed labels are
        dropped and counted — never a terminal query failure."""
        capdir = str(tmp_path / "cap")
        cap = TrafficCapture(capdir)

        class P:
            def __init__(self, payload):
                self.rid = None
                self.trace = "t"
                self.payload = payload
                self.reply = b'{"scores": [0.0]}'

        # batch 1: NO labels at all; batch 2: mixed junk + good labels
        cap.offer("v1", [P({"x": [0.1, 0.2]}) for _ in range(4)])
        cap.flush()
        mixed = [P({"x": [0.1, 0.2], "label": 1.0}),
                 P({"x": [0.3, 0.4]}),                  # hole -> None
                 P({"x": [0.5, 0.6], "label": "oops"}),
                 P({"x": [0.7, 0.8], "label": 2.0})]
        cap.offer("v1", mixed)
        cap.stop()
        fit = _mlp_learner(str(tmp_path / "train")).fit_stream(
            TrafficLogSource(capdir), max_batch_rows=4,
            checkpoint_dir=str(tmp_path / "wal"))
        fit.query.process_available()
        st = fit.status()
        assert st["query"]["state"] != "failed"
        tr = st["trainer"]
        assert tr["n_rows_trained"] == 2         # only the good labels
        assert tr["n_rows_unlabeled"] == 6
        assert tr["n_batches_trained"] == 1      # all-unlabeled batch skipped

    def test_transient_read_failure_reoffers_instead_of_losing(
            self, tmp_path, monkeypatch):
        """An engine-mode read failing transiently must NOT journal the
        file as consumed: the key re-offers on the next plan (bounded
        by max_read_failures before quarantine)."""
        from mmlspark_tpu.io import streaming as iostreaming
        data = tmp_path / "data"
        data.mkdir()
        (data / "a.bin").write_bytes(b"payload")
        src = iostreaming.FileStreamSource(
            str(data), checkpoint_location=str(tmp_path / "p.json"))
        real_read = iostreaming.read_binary_files
        fail = {"n": 1}

        def flaky(path, **kw):
            if fail["n"] > 0:
                fail["n"] -= 1
                raise OSError("transient NFS blip")
            return real_read(path, **kw)

        monkeypatch.setattr(iostreaming, "read_binary_files", flaky)
        meta = src.plan()
        assert src.read(meta).num_rows == 0      # blip: nothing read
        src.ack(meta)                            # must NOT journal it
        meta2 = src.plan()
        assert meta2 is not None                 # re-offered
        df = src.read(meta2)
        assert df.num_rows == 1 and list(df["bytes"]) == [b"payload"]
        src.ack(meta2)
        assert src.plan() is None                # now consumed for good

    def test_warmup_batches_never_captured(self, tmp_path):
        """Synthetic warmup dispatches must not feed the retrain loop:
        'nothing is journaled' covers the capture journal too."""
        import requests
        from mmlspark_tpu.serving import ServingServer, TrafficCapture
        from mmlspark_tpu.stages import ScaleColumn

        cap = TrafficCapture(str(tmp_path / "cap"))
        with ServingServer(ScaleColumn(input_col="x", output_col="y",
                                       scale=2.0),
                           max_latency_ms=1, max_batch_size=4,
                           capture=cap, slow_trace_ms=None) as srv:
            srv.warmup({"x": 123.0})             # synthetic ladder
            r = requests.post(srv.address, json={"x": 1.0}, timeout=5)
            assert r.status_code == 200
        src = TrafficLogSource(str(tmp_path / "cap"))
        meta = src.plan()
        df = src.read(meta) if meta else DataFrame({})
        assert df.num_rows == 1                  # ONLY the live request
        assert float(np.asarray(df["x"][0]).reshape(-1)[0]) == 1.0

    def test_default_checkpoint_cadence_covers_every_batch(self, tmp_path):
        """Exactly-once must not depend on the export cadence: with the
        default checkpoint_every_batches=1, a crash after ANY committed
        batch warm-starts past it even when exports are sparse."""
        capdir = str(tmp_path / "cap")
        _seed_traffic(capdir, 48)
        wal, train = str(tmp_path / "wal"), str(tmp_path / "train")

        def make():
            return _mlp_learner(train).fit_stream(
                TrafficLogSource(capdir),
                export_dir=str(tmp_path / "exp"),
                export_every_batches=100,        # exports far apart...
                checkpoint_dir=wal, max_batch_rows=16,
                retry_policy=RetryPolicy(max_attempts=1))

        fit = make()
        inner = fit.query.sink

        class Crasher:
            def process(self, bid, df):
                inner.process(bid, df)
                if bid == 2:
                    raise RuntimeError("kill")

        fit.query.sink = Crasher()
        with pytest.raises(RuntimeError):
            fit.query.process_available()
        fit2 = make()
        fit2.query.process_available()
        st = fit2.status()["trainer"]
        # ...but the per-batch train-state checkpoint still made the
        # replayed batch skippable: nothing trained twice
        assert st["n_replays_skipped"] == 1
        assert inner.status()["n_rows_trained"] \
            + st["n_rows_trained"] == 48
