"""The event-loop socket edge: HTTP/1.1 framing, keep-alive lifecycle,
and serving-semantics parity (ISSUE 6).

Four pillars:

* **framing edges** — the state machine must survive exactly the
  byte-stream shapes ``http.server`` never showed it: headers split at
  arbitrary boundaries, oversized header blocks (431), bodies with
  missing/invalid/oversized Content-Length (411/400/413), chunked
  uploads (501), ``Connection: close``, and stray pipelined bytes;
* **connection lifecycle** — keep-alive reuse is the steady state,
  idle and slow-loris connections are reaped on the sweep clock, and a
  graceful drain finishes in-flight keep-alive requests;
* **serving parity** — journal/replay, 429 shedding, deadline
  rejection, and trace-context adoption behave identically behind the
  new edge (the broad suites already run on ``frontend="eventloop"``
  by default; the tests here pin the wire-visible details);
* **satellites** — the ``X-Capture`` force-capture wire hint and
  ``MetricsPusher`` rotating auth headers.

Raw-socket tests talk bytes on purpose: the stdlib client would paper
over the exact framing shapes under test.
"""

import json
import socket
import threading
import time

import numpy as np
import pytest
import requests

from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.core.tracing import (
    Tracer, capture_hint, inject_span_context,
)
from mmlspark_tpu.serving import ServingServer
from mmlspark_tpu.serving.frontend import (
    EventLoopFrontend, build_head, parse_head,
)


class Doubler(Transformer):
    def transform(self, df):
        return df.with_column(
            "y", np.asarray(df["x"], dtype=np.float64) * 2)


class SlowDoubler(Doubler):
    def __init__(self, delay=0.2, **kw):
        super().__init__(**kw)
        self.delay = delay

    def transform(self, df):
        time.sleep(self.delay)
        return super().transform(df)


def _server(model=None, **kw):
    kw.setdefault("max_batch_size", 8)
    kw.setdefault("max_latency_ms", 2)
    return ServingServer(model or Doubler(), **kw).start()


def _connect(srv, timeout=10.0):
    s = socket.create_connection((srv.host, srv.port), timeout=timeout)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def _request_bytes(path="/predict", body=b'{"x": 1.0}', headers=()):
    head = [f"POST {path} HTTP/1.1", "Host: t",
            "Content-Type: application/json",
            f"Content-Length: {len(body)}"]
    head += [f"{k}: {v}" for k, v in headers]
    return ("\r\n".join(head) + "\r\n\r\n").encode() + body


def _read_response(sock):
    """One full response off the socket: (status, headers dict, body)."""
    buf = bytearray()
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError(f"EOF mid-head: {bytes(buf)!r}")
        buf += chunk
    he = buf.index(b"\r\n\r\n")
    head = bytes(buf[:he]).decode("latin-1").split("\r\n")
    status = int(head[0].split()[1])
    hdrs = {}
    for line in head[1:]:
        k, _, v = line.partition(":")
        hdrs[k.strip().lower()] = v.strip()
    clen = int(hdrs.get("content-length", 0))
    body = buf[he + 4:]
    while len(body) < clen:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("EOF mid-body")
        body += chunk
    rest = bytes(body[clen:])
    return status, hdrs, bytes(body[:clen]), rest


# ---------------------------------------------------------------------------
# Framing units
# ---------------------------------------------------------------------------

class TestParseHead:

    def test_basic(self):
        raw = bytearray(b"POST /p HTTP/1.1\r\nHost: h\r\n"
                        b"X-Trace-Id: abc\r\n")
        method, path, version, h = parse_head(raw, len(raw))
        assert (method, path, version) == (b"POST", "/p", b"HTTP/1.1")
        assert h.get("x-trace-id") == "abc"          # case-insensitive
        assert h.get("X-Trace-Id") == "abc"
        assert h.get("missing") is None
        assert h.get("missing", "d") == "d"
        assert "HOST" in h

    def test_value_whitespace_and_empty(self):
        raw = bytearray(b"GET / HTTP/1.1\r\nA:   padded\r\nB:\r\n")
        _, _, _, h = parse_head(raw, len(raw))
        assert h.get("a") == "padded"
        assert h.get("b") == ""

    def test_malformed_request_line_raises(self):
        raw = bytearray(b"NONSENSE\r\nHost: h\r\n")
        with pytest.raises(ValueError):
            parse_head(raw, len(raw))

    def test_build_head_cached_blocks(self):
        h = build_head(200, 10)
        assert h.startswith(b"HTTP/1.1 200 OK\r\n")
        assert b"Content-Length: 10\r\n" in h
        assert b"Date: " in h
        assert h.endswith(b"\r\n\r\n")
        # >1024 bodies leave the interned Content-Length cache
        assert b"Content-Length: 5000\r\n" in build_head(200, 5000)
        assert b"Connection: close\r\n" in build_head(200, 1, close=True)
        assert b"Retry-After: 1\r\n" in build_head(
            429, 1, extra=(("Retry-After", "1"),))


# ---------------------------------------------------------------------------
# Framing edges on the wire
# ---------------------------------------------------------------------------

class TestFramingEdges:

    def test_split_at_every_boundary(self):
        """The whole request dribbled in two fragments, split at EVERY
        byte boundary (headers mid-name, mid-CRLF, body mid-JSON):
        framing must be agnostic to how TCP fragments the stream."""
        with _server() as srv:
            raw = _request_bytes(body=b'{"x": 3.0}')
            sock = _connect(srv)
            try:
                for cut in range(1, len(raw), 7):
                    sock.sendall(raw[:cut])
                    time.sleep(0.001)
                    sock.sendall(raw[cut:])
                    status, _, body, rest = _read_response(sock)
                    assert status == 200
                    assert json.loads(body) == {"y": 6.0}
                    assert rest == b""
            finally:
                sock.close()

    def test_pipelined_requests_served_in_order(self):
        """Two complete requests in ONE send: both answered, in order,
        on the same connection (no read event for the second)."""
        with _server() as srv:
            two = (_request_bytes(body=b'{"x": 1.0}')
                   + _request_bytes(body=b'{"x": 2.0}'))
            sock = _connect(srv)
            try:
                sock.sendall(two)
                status1, _, body1, _ = _read_response(sock)
                status2, _, body2, _ = _read_response(sock)
                assert (status1, status2) == (200, 200)
                assert json.loads(body1) == {"y": 2.0}
                assert json.loads(body2) == {"y": 4.0}
            finally:
                sock.close()

    def test_oversized_headers_rejected_431(self):
        with _server() as srv:
            fe = srv._frontend
            sock = _connect(srv)
            try:
                filler = b"X-Pad: " + b"a" * fe.max_header_bytes
                sock.sendall(b"POST /predict HTTP/1.1\r\n" + filler)
                status, hdrs, _, _ = _read_response(sock)
                assert status == 431
                assert hdrs.get("connection") == "close"
                assert sock.recv(65536) == b""    # server closed
            finally:
                sock.close()
            assert fe.n_parse_errors >= 1

    def test_oversized_headers_in_one_send_rejected_431(self):
        """The whole oversized block — terminator included — landing in
        a single recv must still 431: finding CRLFCRLF does not make an
        over-limit header block admissible."""
        with _server() as srv:
            fe = srv._frontend
            fe.max_header_bytes = 1024
            sock = _connect(srv)
            try:
                sock.sendall(_request_bytes(
                    headers=(("X-Pad", "a" * 4096),)))
                status, hdrs, _, _ = _read_response(sock)
                assert status == 431
                assert hdrs.get("connection") == "close"
                assert sock.recv(65536) == b""    # server closed
            finally:
                sock.close()
            assert fe.n_parse_errors >= 1

    def test_missing_content_length_411(self):
        with _server() as srv:
            sock = _connect(srv)
            try:
                sock.sendall(b"POST /predict HTTP/1.1\r\nHost: t\r\n\r\n")
                status, _, _, _ = _read_response(sock)
                assert status == 411
            finally:
                sock.close()

    def test_invalid_content_length_400(self):
        with _server() as srv:
            sock = _connect(srv)
            try:
                sock.sendall(_request_bytes(
                    headers=()).replace(b"Content-Length: 10",
                                        b"Content-Length: ten"))
                status, _, _, _ = _read_response(sock)
                assert status == 400
            finally:
                sock.close()

    def test_oversized_body_rejected_413(self):
        with _server() as srv:
            srv._frontend.max_body_bytes = 1024
            sock = _connect(srv)
            try:
                head = (b"POST /predict HTTP/1.1\r\nHost: t\r\n"
                        b"Content-Length: 4096\r\n\r\n")
                sock.sendall(head)
                status, _, _, _ = _read_response(sock)
                assert status == 413
            finally:
                sock.close()

    def test_chunked_transfer_encoding_501(self):
        with _server() as srv:
            sock = _connect(srv)
            try:
                sock.sendall(b"POST /predict HTTP/1.1\r\nHost: t\r\n"
                             b"Transfer-Encoding: chunked\r\n\r\n"
                             b"0\r\n\r\n")
                status, _, _, _ = _read_response(sock)
                assert status == 501
            finally:
                sock.close()

    def test_malformed_request_line_400(self):
        with _server() as srv:
            sock = _connect(srv)
            try:
                sock.sendall(b"garbage\r\n\r\n")
                status, _, _, _ = _read_response(sock)
                assert status == 400
            finally:
                sock.close()

    def test_connection_close_honored(self):
        with _server() as srv:
            sock = _connect(srv)
            try:
                sock.sendall(_request_bytes(
                    headers=(("Connection", "close"),)))
                status, hdrs, body, _ = _read_response(sock)
                assert status == 200
                assert json.loads(body) == {"y": 2.0}
                assert hdrs.get("connection") == "close"
                assert sock.recv(65536) == b""
            finally:
                sock.close()

    def test_http10_defaults_to_close(self):
        with _server() as srv:
            sock = _connect(srv)
            try:
                body = b'{"x": 1.0}'
                sock.sendall(b"POST /predict HTTP/1.0\r\nHost: t\r\n"
                             b"Content-Length: %d\r\n\r\n%b"
                             % (len(body), body))
                status, _, rbody, _ = _read_response(sock)
                assert status == 200
                assert json.loads(rbody) == {"y": 2.0}
                assert sock.recv(65536) == b""
            finally:
                sock.close()

    def test_unknown_route_404_keeps_connection(self):
        with _server() as srv:
            sock = _connect(srv)
            try:
                sock.sendall(_request_bytes(path="/nope"))
                status, _, _, _ = _read_response(sock)
                assert status == 404
                # framing intact: the connection survives a 404 and
                # serves the next request
                sock.sendall(_request_bytes())
                status, _, body, _ = _read_response(sock)
                assert status == 200
                assert json.loads(body) == {"y": 2.0}
            finally:
                sock.close()


# ---------------------------------------------------------------------------
# Connection lifecycle
# ---------------------------------------------------------------------------

def wait_until(cond, timeout=8.0, what="condition"):
    t_end = time.monotonic() + timeout
    while time.monotonic() < t_end:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


class TestConnectionLifecycle:

    def test_keepalive_reuse_counters(self):
        with _server() as srv:
            fe = srv._frontend
            sock = _connect(srv)
            try:
                for i in range(20):
                    sock.sendall(_request_bytes(
                        body=json.dumps({"x": float(i)}).encode()))
                    status, _, body, _ = _read_response(sock)
                    assert status == 200
                    assert json.loads(body) == {"y": 2.0 * i}
            finally:
                sock.close()
            assert fe.n_keepalive_reuses >= 19
            stats = fe.stats()
            assert stats["keepalive_reuse_rate"] > 0.9
            assert stats["kind"] == "eventloop"

    def test_idle_connection_reaped(self):
        with _server(idle_timeout=0.3) as srv:
            fe = srv._frontend
            sock = _connect(srv)
            try:
                sock.sendall(_request_bytes())
                status, _, _, _ = _read_response(sock)
                assert status == 200
                # park idle: the sweep must close it from the server
                # side within the idle budget (plus sweep cadence)
                sock.settimeout(5)
                assert sock.recv(65536) == b""
            finally:
                sock.close()
            wait_until(lambda: fe.n_idle_reaped >= 1,
                       what="idle reap counter")

    def test_slow_loris_reaped_mid_request(self):
        """Bytes dribbling in keep the socket non-idle; the reap clock
        for a mid-request stall is the REQUEST's age."""
        with _server(idle_timeout=0.4) as srv:
            fe = srv._frontend
            raw = _request_bytes()
            sock = _connect(srv)
            closed = False
            try:
                sock.settimeout(10)
                t_end = time.monotonic() + 6.0
                try:
                    for i in range(len(raw)):
                        if time.monotonic() > t_end:
                            break
                        sock.sendall(raw[i:i + 1])
                        time.sleep(0.05)
                    # the server must have hung up mid-dribble
                    closed = sock.recv(65536) == b""
                except OSError:
                    closed = True
            finally:
                sock.close()
            assert closed
            assert fe.n_idle_reaped >= 1

    def test_followup_during_inflight_ages_from_reply(self):
        """Bytes of request B arriving while A is still awaiting its
        reply must age from A's reply, not from A's first byte — a
        well-behaved keep-alive client is not a slow loris just because
        the previous dispatch was slow."""
        with _server(model=SlowDoubler(delay=0.5),
                     idle_timeout=0.4) as srv:
            raw_b = _request_bytes(body=b'{"x": 3.0}')
            split = len(raw_b) // 2
            sock = _connect(srv)
            try:
                sock.settimeout(10)
                sock.sendall(_request_bytes(body=b'{"x": 2.0}'))
                time.sleep(0.1)               # A is mid-dispatch
                sock.sendall(raw_b[:split])   # B starts while A awaits
                status, _, body, _ = _read_response(sock)
                assert status == 200
                assert json.loads(body) == {"y": 4.0}
                # sit across a few sweep ticks (but inside B's own idle
                # budget): a stale reap clock would close the socket here
                time.sleep(0.15)
                sock.sendall(raw_b[split:])
                status, _, body, _ = _read_response(sock)
                assert status == 200
                assert json.loads(body) == {"y": 6.0}
            finally:
                sock.close()

    def test_graceful_drain_finishes_inflight_keepalive(self):
        """stop(drain=True) while a keep-alive request is in flight:
        the reply lands on the open connection before the loops die."""
        with ServingServer(SlowDoubler(delay=0.3), max_batch_size=8,
                           max_latency_ms=1) as srv:
            sock = _connect(srv)
            try:
                sock.sendall(_request_bytes(body=b'{"x": 5.0}'))
                time.sleep(0.1)          # request is mid-dispatch
                t = threading.Thread(target=srv.stop,
                                     kwargs={"drain_timeout": 10.0})
                t.start()
                status, _, body, _ = _read_response(sock)
                assert status == 200
                assert json.loads(body) == {"y": 10.0}
                t.join(timeout=10)
                assert not t.is_alive()
            finally:
                sock.close()

    def test_drain_refuses_new_work_503(self):
        with _server() as srv:
            srv._draining.set()
            r = requests.post(srv.address, json={"x": 1.0}, timeout=10)
            assert r.status_code == 503
            assert "Retry-After" in r.headers
            srv._draining.clear()

    @pytest.mark.skipif(not hasattr(socket, "SO_REUSEPORT"),
                        reason="no SO_REUSEPORT on this platform")
    def test_reuseport_acceptors_share_port(self):
        with ServingServer(Doubler(), max_batch_size=8,
                           max_latency_ms=1, acceptors=2,
                           reuse_port=True) as srv:
            assert len(srv._frontend._loops) == 2
            out = set()
            for i in range(16):
                r = requests.post(srv.address, json={"x": float(i)},
                                  timeout=10)
                assert r.status_code == 200
                out.add(r.json()["y"])
            assert out == {2.0 * i for i in range(16)}
            assert srv._frontend.stats()["acceptors"] == 2

    def test_acceptors_without_reuseport_rejected(self):
        with pytest.raises(ValueError, match="reuse_port"):
            EventLoopFrontend(None, acceptors=2, reuse_port=False)


# ---------------------------------------------------------------------------
# Serving-semantics parity behind the new edge
# ---------------------------------------------------------------------------

class TestServingParity:

    def test_journal_replay_on_keepalive_connection(self):
        calls = []

        class Counting(Doubler):
            def transform(self, df):
                calls.append(df.num_rows)
                return super().transform(df)

        with _server(Counting()) as srv:
            sock = _connect(srv)
            try:
                for _ in range(3):   # original + 2 replays, one conn
                    sock.sendall(_request_bytes(
                        body=b'{"x": 4.0}',
                        headers=(("X-Request-Id", "rid-ka-1"),)))
                    status, hdrs, body, _ = _read_response(sock)
                    assert status == 200
                    assert json.loads(body) == {"y": 8.0}
                replayed = hdrs.get("x-replayed")
            finally:
                sock.close()
            assert replayed == "1"
            assert sum(calls) == 1          # one compute, two replays
            assert srv.n_replayed == 2

    def test_shed_429_with_retry_after(self):
        with ServingServer(SlowDoubler(delay=0.5), max_batch_size=1,
                           max_latency_ms=1, max_queue=1,
                           shed_retry_after=0.7) as srv:
            statuses = []

            def hit():
                r = requests.post(srv.address, json={"x": 1.0},
                                  timeout=10)
                statuses.append((r.status_code, r.headers))

            threads = [threading.Thread(target=hit) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            shed = [(s, h) for s, h in statuses if s == 429]
            assert shed, f"expected 429s, got {[s for s, _ in statuses]}"
            assert all(h.get("Retry-After") == "0.7" for _, h in shed)
            assert srv.n_shed == len(shed)

    def test_deadline_rejection(self):
        r_ok = None
        with _server() as srv:
            r = requests.post(srv.address, json={"x": 1.0},
                              headers={"X-Deadline-Ms": "0"}, timeout=10)
            assert r.status_code == 504
            assert srv.n_deadline_expired == 1
            r_ok = requests.post(srv.address, json={"x": 1.0},
                                 headers={"X-Deadline-Ms": "30000"},
                                 timeout=10)
        assert r_ok.status_code == 200

    def test_trace_context_adopted_and_echoed(self):
        with _server(tracer=Tracer(), slow_trace_ms=0.0) as srv:
            r = requests.post(srv.address, json={"x": 1.0},
                              headers={"X-Trace-Id": "edge-trace-1"},
                              timeout=10)
            assert r.status_code == 200
            assert r.headers.get("X-Trace-Id") == "edge-trace-1"
            tr = requests.get(
                f"http://{srv.host}:{srv.port}/trace/edge-trace-1",
                timeout=10).json()
            assert tr["trace_id"] == "edge-trace-1"
            names = {s["name"] for s in _flatten(tr["tree"])}
            assert "request" in names and "commit" in names

    def test_invalid_json_400_echoes_trace(self):
        with _server() as srv:
            sock = _connect(srv)
            try:
                sock.sendall(_request_bytes(
                    body=b"not json",
                    headers=(("X-Trace-Id", "bad-json-1"),)))
                status, hdrs, _, _ = _read_response(sock)
                assert status == 400
                assert hdrs.get("x-trace-id") == "bad-json-1"
            finally:
                sock.close()

    def test_get_routes_served_by_frontend(self):
        with _server() as srv:
            base = f"http://{srv.host}:{srv.port}"
            assert requests.get(f"{base}/healthz", timeout=10).json() \
                == {"ok": True}
            assert requests.get(f"{base}/readyz", timeout=10).json()[
                "ready"] is True
            stats = requests.get(f"{base}/stats", timeout=10).json()
            assert stats["frontend"]["kind"] == "eventloop"
            metrics = requests.get(f"{base}/metrics", timeout=10).text
            assert "serving_open_connections" in metrics
            assert "serving_keepalive_reuses_total" in metrics


def _flatten(tree):
    out = [tree]
    for c in tree.get("children", ()):
        out.extend(_flatten(c))
    return out


# ---------------------------------------------------------------------------
# Satellites: X-Capture wire hint, MetricsPusher rotating auth
# ---------------------------------------------------------------------------

class TestCaptureHint:

    def test_capture_header_forces_retention(self):
        """slow_trace_ms=None retains errors only — yet an X-Capture: 1
        request's trace is kept end to end."""
        with _server(tracer=Tracer(), slow_trace_ms=None,
                     adaptive_slow_trace=False) as srv:
            r = requests.post(srv.address, json={"x": 1.0},
                              headers={"X-Trace-Id": "forced-1",
                                       "X-Capture": "1"}, timeout=10)
            assert r.status_code == 200
            tr = requests.get(
                f"http://{srv.host}:{srv.port}/trace/forced-1",
                timeout=10).json()
            assert tr["reason"] == "forced"
            # the unforced twin is tail-dropped as usual
            requests.post(srv.address, json={"x": 1.0},
                          headers={"X-Trace-Id": "unforced-1"},
                          timeout=10)
            missing = requests.get(
                f"http://{srv.host}:{srv.port}/trace/unforced-1",
                timeout=10)
            assert missing.status_code == 404

    def test_capture_hint_parsing(self):
        assert capture_hint({"X-Capture": "1"})
        assert not capture_hint({"X-Capture": "0"})
        assert not capture_hint({"X-Capture": "yes"})  # boolean, not knob
        assert not capture_hint({})
        assert not capture_hint(None)

    def test_forced_span_propagates_hint_on_egress(self):
        tracer = Tracer()
        root = tracer.start("request", trace_id="t-forced")
        root.force = True
        child = tracer.start("http_egress", parent=root)
        assert child.force                      # inherits parent's flag
        out = inject_span_context({"A": "b"}, child)
        assert out["X-Capture"] == "1"
        # an unforced span adds nothing
        plain = tracer.start("http_egress",
                             trace_id="t-plain")
        assert "X-Capture" not in inject_span_context({}, plain)
        # a caller-supplied hint wins (never duplicated)
        pre = inject_span_context({"x-capture": "0"}, child)
        assert pre["x-capture"] == "0"
        assert "X-Capture" not in pre


class TestMetricsPusherAuth:

    def _gateway(self):
        """In-process gateway capturing each push's headers."""
        seen = []

        class App:
            def handle_request(self, method, path, headers, body,
                               reply):
                seen.append({k.lower(): v for k, v in headers.items()})
                reply(200, b"{}")
                return True

        fe = EventLoopFrontend(App()).start()
        return fe, seen

    def test_header_provider_reinvoked_per_push(self):
        from mmlspark_tpu.core.telemetry import (
            MetricsPusher, MetricsRegistry)
        fe, seen = self._gateway()
        try:
            tokens = iter(["tok-1", "tok-2", "tok-3"])
            pusher = MetricsPusher(
                f"http://{fe.host}:{fe.port}/push",
                registries=(MetricsRegistry(),),
                interval_s=3600,
                headers={"X-Static": "s"},
                header_provider=lambda: {
                    "Authorization": f"Bearer {next(tokens)}"})
            for _ in range(3):
                pusher.push_now()
            assert [h["authorization"] for h in seen] == \
                ["Bearer tok-1", "Bearer tok-2", "Bearer tok-3"]
            assert all(h["x-static"] == "s" for h in seen)
        finally:
            fe.stop()

    def test_broken_provider_degrades_to_static(self):
        from mmlspark_tpu.core.telemetry import (
            MetricsPusher, MetricsRegistry)
        fe, seen = self._gateway()
        try:
            def boom():
                raise RuntimeError("token refresh down")

            pusher = MetricsPusher(
                f"http://{fe.host}:{fe.port}/push",
                registries=(MetricsRegistry(),),
                interval_s=3600,
                headers={"X-Static": "s"},
                header_provider=boom)
            pusher.push_now()
            assert len(seen) == 1               # push still happened
            assert seen[0]["x-static"] == "s"
            assert "authorization" not in seen[0]
            assert pusher.n_errors >= 1
        finally:
            fe.stop()


# ---------------------------------------------------------------------------
# Socket-edge fairness + per-IP shedding (ISSUE 7 satellites)
# ---------------------------------------------------------------------------

def _read_n_responses(sock, n):
    """Parse ``n`` responses off one socket with a persistent buffer
    (pipelined replies may coalesce into one recv)."""
    buf = bytearray()
    out = []
    while len(out) < n:
        he = buf.find(b"\r\n\r\n")
        if he < 0:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError(f"EOF after {len(out)} responses")
            buf += chunk
            continue
        head = bytes(buf[:he]).decode("latin-1").split("\r\n")
        status = int(head[0].split()[1])
        hdrs = {}
        for line in head[1:]:
            k, _, v = line.partition(":")
            hdrs[k.strip().lower()] = v.strip()
        clen = int(hdrs.get("content-length", 0))
        total = he + 4 + clen
        while len(buf) < total:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("EOF mid-body")
            buf += chunk
        out.append((status, hdrs, bytes(buf[he + 4:total])))
        del buf[:total]
    return out


class TestPerIpConnectionCap:

    def test_over_cap_accept_shed_429_then_slot_freed(self):
        """The third concurrent connection from one peer is refused at
        accept — immediate 429 + close, before any queue slot is spent
        — and closing an admitted connection frees the slot."""
        with _server(max_conns_per_ip=2) as srv:
            fe = srv._frontend
            s1, s2 = _connect(srv), _connect(srv)
            try:
                for s in (s1, s2):        # both admitted conns serve
                    s.sendall(_request_bytes())
                    status, _, _, _ = _read_response(s)
                    assert status == 200
                s3 = _connect(srv)
                try:
                    status, hdrs, body, _ = _read_response(s3)
                    assert status == 429
                    assert hdrs.get("retry-after") == "1"
                    assert b"too many connections" in body
                    assert s3.recv(65536) == b""      # closed
                finally:
                    s3.close()
                assert fe.n_per_ip_rejected == 1
                assert fe.per_ip_high_water == 2
            finally:
                s1.close()
                s2.close()
            # Prove the released slots readmit: poll until a fresh
            # connect serves 200 (the loop processes the closes
            # asynchronously; rejected polls bump the counter too).
            deadline = time.monotonic() + 5
            admitted = False
            while time.monotonic() < deadline and not admitted:
                s4 = _connect(srv)
                try:
                    s4.sendall(_request_bytes())
                    status, _, _, _ = _read_response(s4)
                    admitted = status == 200
                except ConnectionError:
                    pass
                finally:
                    s4.close()
                if not admitted:
                    time.sleep(0.05)
            assert admitted, "closed connections never freed the cap"
            # with slots free again, the counters are visible over HTTP
            base = f"http://{srv.host}:{srv.port}"
            st = requests.get(base + "/stats", timeout=10).json()
            assert st["frontend"]["per_ip_rejected_total"] >= 1
            assert st["frontend"]["per_ip_conns_high_water"] == 2
            text = requests.get(base + "/metrics?scope=server",
                                timeout=10).text
            assert "serving_per_ip_rejected_total" in text
            assert "serving_per_ip_conns_high_water 2" in text

    def test_cap_off_by_default(self):
        with _server() as srv:
            fe = srv._frontend
            assert fe.max_conns_per_ip == 0
            socks = [_connect(srv) for _ in range(8)]
            try:
                for s in socks:
                    s.sendall(_request_bytes())
                    assert _read_response(s)[0] == 200
            finally:
                for s in socks:
                    s.close()
            assert fe.n_per_ip_rejected == 0


class TestPipeliningFairnessCap:

    def test_flooding_pipelined_conn_deferred_but_fully_served(self):
        """A connection flooding N pipelined requests in ONE buffer is
        served completely and in order, but the loop defers its excess
        beyond max_pipelined_per_iter to later iterations (counted by
        serving_pipelining_deferred_total) instead of serving the
        whole buffer in one pass."""
        n = 32
        with _server(max_pipelined_per_iter=2) as srv:
            fe = srv._frontend
            # synchronous control-plane GETs reply inline, so one
            # buffer of them exercises the per-iteration budget
            burst = (b"GET /healthz HTTP/1.1\r\nHost: t\r\n\r\n") * n
            sock = _connect(srv)
            try:
                sock.sendall(burst)
                rsps = _read_n_responses(sock, n)
            finally:
                sock.close()
            assert [status for status, _, _ in rsps] == [200] * n
            assert fe.n_pipelining_deferred >= 1
            st = requests.get(f"http://{srv.host}:{srv.port}/stats",
                              timeout=10).json()
            assert st["frontend"]["pipelining_deferred_total"] >= 1

    def test_cap_zero_disables_deferral(self):
        n = 16
        with _server(max_pipelined_per_iter=0) as srv:
            fe = srv._frontend
            burst = (b"GET /healthz HTTP/1.1\r\nHost: t\r\n\r\n") * n
            sock = _connect(srv)
            try:
                sock.sendall(burst)
                rsps = _read_n_responses(sock, n)
            finally:
                sock.close()
            assert [status for status, _, _ in rsps] == [200] * n
            assert fe.n_pipelining_deferred == 0

    def test_interleaved_conns_all_served_under_cap(self):
        """Two connections pipelining concurrently under a tight cap:
        both finish, both in order (fairness must not starve or
        misdeliver either)."""
        n = 12
        with _server(max_pipelined_per_iter=1) as srv:
            results = {}

            def drive(tag):
                burst = b"".join(
                    _request_bytes(body=json.dumps(
                        {"x": float(i)}).encode())
                    for i in range(n))
                s = _connect(srv)
                try:
                    s.sendall(burst)
                    results[tag] = _read_n_responses(s, n)
                finally:
                    s.close()

            ts = [threading.Thread(target=drive, args=(t,))
                  for t in ("a", "b")]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for tag in ("a", "b"):
                assert [s for s, _, _ in results[tag]] == [200] * n
                assert [json.loads(b) for _, _, b in results[tag]] == \
                    [{"y": 2.0 * i} for i in range(n)]


class TestBatchedReplyFlushing:
    """One encoder commit batch -> one deque extend + one wake per
    loop, replies fanned out to distinct connections in one loop pass
    (the ROADMAP item 5 follow-up)."""

    def test_batched_replies_unit(self):
        """The thread-local scope: posts inside it park per loop and
        flush together; nesting flushes once, at the outermost exit."""
        from mmlspark_tpu.serving.frontend import batched_replies

        class FakeFrontend:
            n_reply_flushes = 0
            n_batched_replies = 0

        class FakeLoop:
            ident = -1            # never the current thread
            frontend = FakeFrontend()

            def __init__(self):
                self._replies = []
                self.wakes = 0

            def wake(self):
                self.wakes += 1

            def flush_replies(self, items):
                self._replies.extend(items)
                self.frontend.n_reply_flushes += 1
                self.frontend.n_batched_replies += len(items)
                self.wake()

        from mmlspark_tpu.serving import frontend as fe_mod
        a, b = FakeLoop(), FakeLoop()
        with batched_replies():
            with batched_replies():         # nested: outer flushes
                fe_mod._Loop.post_reply(a, None, 0, b"h", b"b", False)
            fe_mod._Loop.post_reply(a, None, 1, b"h", b"b", False)
            fe_mod._Loop.post_reply(b, None, 2, b"h", b"b", False)
            assert a.wakes == b.wakes == 0  # parked, not posted
        assert len(a._replies) == 2 and a.wakes == 1
        assert len(b._replies) == 1 and b.wakes == 1
        assert FakeLoop.frontend.n_reply_flushes == 2
        assert FakeLoop.frontend.n_batched_replies == 3
        # outside any scope: straight to the deque + wake (unbatched)
        fe_mod._Loop.post_reply(a, None, 3, b"h", b"b", False)
        assert len(a._replies) == 3 and a.wakes == 2

    def test_commit_batch_flushes_once_across_connections(self):
        """N keep-alive connections whose requests commit in one
        micro-batch: every reply lands correctly, and the flush
        counters show cross-connection coalescing (fewer flushes than
        batched replies)."""
        srv = _server(max_batch_size=8, max_latency_ms=60)
        try:
            srv.warmup({"x": 0.0})
            n = 6
            socks = [_connect(srv) for _ in range(n)]
            # stagger-free burst: all requests queued inside one
            # collection window -> one batch -> one _commit_many
            for i, s in enumerate(socks):
                s.sendall(_request_bytes(
                    body=json.dumps({"x": float(i)}).encode()))
            for i, s in enumerate(socks):
                status, _headers, body, _rest = _read_response(s)
                assert status == 200
                assert json.loads(body)["y"] == 2.0 * i
            fe = srv._frontend
            assert fe.n_batched_replies >= n
            assert 0 < fe.n_reply_flushes < fe.n_batched_replies
            stats = fe.stats()
            assert stats["batched_replies_total"] == \
                fe.n_batched_replies
            assert stats["reply_flush_batches_total"] == \
                fe.n_reply_flushes
            body = requests.get(
                f"http://{srv.host}:{srv.port}/metrics?scope=server",
                timeout=10).text
            assert "serving_reply_flush_batches_total" in body
            assert "serving_batched_replies_total" in body
            for s in socks:
                s.close()
        finally:
            srv.stop()

    def test_threaded_frontend_unaffected(self):
        """The threaded plane has no loops to flush: commits release
        Event waiters exactly as before."""
        srv = _server(frontend="threaded", max_latency_ms=20)
        try:
            srv.warmup({"x": 0.0})
            rs = []
            for i in range(4):
                rs.append(requests.post(
                    srv.address, json={"x": float(i)}, timeout=10))
            assert [r.json()["y"] for r in rs] == \
                [0.0, 2.0, 4.0, 6.0]
        finally:
            srv.stop()
