"""Unified telemetry: registry primitives, exposition, trace ids,
fleet aggregation (ISSUE 3).

Contracts under test:

* **histogram bucket edges** — a sample exactly on an edge lands in
  that ``le`` bucket (Prometheus ``le`` is inclusive), cumulative
  rendering is correct, and the running sum/count/last/max track;
* **concurrency** — N threads hammering one counter child lose no
  increments (the lock-striped hot path is actually locked);
* **exposition golden** — ``render()`` is byte-stable valid Prometheus
  text format;
* **trace propagation** — an inbound ``X-Trace-Id`` is echoed on the
  reply, stamped into journal lines, injected into log records, and
  minted when absent;
* **fleet merge** — the coordinator's merged view sums per-worker
  counters exactly and names the slowest stage across >= 2 workers;
* **overhead** (perf-marked) — counter/histogram hot-path updates stay
  under the 2 us budget that lets telemetry run in production.
"""

import json
import logging
import threading
import time

import numpy as np
import pytest
import requests

from mmlspark_tpu.core.telemetry import (
    DEFAULT_LATENCY_BUCKETS_MS, MetricsRegistry, current_trace_id,
    log_buckets, merge_prometheus, new_trace_id, parse_prometheus,
    trace_context, trace_id_from_headers,
)


# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------

class TestCounter:

    def test_inc_and_value(self):
        r = MetricsRegistry()
        c = r.counter("c_total", "help")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5

    def test_negative_inc_rejected(self):
        c = MetricsRegistry().counter("c_total")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_labels_children_independent(self):
        c = MetricsRegistry().counter("c_total", labels=("k",))
        c.labels("a").inc()
        c.labels("a").inc()
        c.labels("b").inc()
        assert c.labels("a").value == 2
        assert c.labels("b").value == 1

    def test_label_arity_enforced(self):
        c = MetricsRegistry().counter("c_total", labels=("k",))
        with pytest.raises(ValueError):
            c.labels("a", "b")

    def test_set_function_view(self):
        state = {"n": 0}
        c = MetricsRegistry().counter("c_total")
        c.set_function(lambda: state["n"])
        state["n"] = 41
        assert c.value == 41

    def test_concurrent_increments_lose_nothing(self):
        """8 threads x 5000 incs on ONE child: the exact total
        survives (a bare ``+=`` on a float would drop updates under
        bytecode interleaving)."""
        c = MetricsRegistry().counter("c_total")
        child = c.labels()
        n_threads, n_incs = 8, 5000

        def worker():
            for _ in range(n_incs):
                child.inc()

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n_threads * n_incs


class TestGauge:

    def test_set_inc_dec(self):
        g = MetricsRegistry().gauge("g")
        g.set(10)
        g.inc(5)
        g.dec(2)
        assert g.value == 13

    def test_set_function_live_view(self):
        depth = [3]
        g = MetricsRegistry().gauge("g")
        g.set_function(lambda: depth[0])
        assert g.value == 3
        depth[0] = 9
        assert g.value == 9


class TestHistogram:

    def test_bucket_edges_inclusive(self):
        """Prometheus ``le`` semantics: a sample EXACTLY on an edge
        belongs to that bucket; one epsilon above spills to the next."""
        r = MetricsRegistry()
        h = r.histogram("h_ms", buckets=(1.0, 10.0, 100.0))
        for v in (1.0, 10.0, 100.0, 1.0000001, 0.1, 1e9):
            h.observe(v)
        s = h.stats()
        # non-cumulative per-slot counts: [<=1, <=10, <=100, +Inf]
        assert s["buckets"] == [2, 2, 1, 1]
        assert s["count"] == 6
        assert s["max"] == 1e9
        assert s["last"] == 1e9

    def test_render_is_cumulative(self):
        r = MetricsRegistry()
        h = r.histogram("h_ms", buckets=(1.0, 10.0))
        for v in (0.5, 5.0, 50.0):
            h.observe(v)
        text = r.render()
        assert 'h_ms_bucket{le="1"} 1' in text
        assert 'h_ms_bucket{le="10"} 2' in text
        assert 'h_ms_bucket{le="+Inf"} 3' in text
        assert "h_ms_count 3" in text

    def test_unsorted_buckets_rejected(self):
        with pytest.raises(ValueError):
            MetricsRegistry().histogram("h", buckets=(10.0, 1.0))

    def test_time_context_manager_observes_ms(self):
        from mmlspark_tpu.core.resilience import ManualClock
        clock = ManualClock()
        r = MetricsRegistry(clock=clock)
        h = r.histogram("h_ms")
        with h.time():
            clock.advance(0.25)          # 250 ms on the injected clock
        s = h.stats()
        assert s["count"] == 1
        assert abs(s["last"] - 250.0) < 1e-6

    def test_reset(self):
        h = MetricsRegistry().histogram("h_ms")
        h.observe(5.0)
        h.labels().reset()
        assert h.stats()["count"] == 0

    def test_default_buckets_are_log_scale_ms(self):
        assert DEFAULT_LATENCY_BUCKETS_MS[0] == 0.1
        assert DEFAULT_LATENCY_BUCKETS_MS[-1] == 10000.0
        assert list(DEFAULT_LATENCY_BUCKETS_MS) == \
            sorted(DEFAULT_LATENCY_BUCKETS_MS)

    def test_log_buckets_helper(self):
        assert log_buckets(1.0, 100.0) == \
            (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0)
        with pytest.raises(ValueError):
            log_buckets(10.0, 1.0)


class TestRegistry:

    def test_get_or_create_same_family(self):
        r = MetricsRegistry()
        assert r.counter("x_total") is r.counter("x_total")

    def test_kind_mismatch_raises(self):
        r = MetricsRegistry()
        r.counter("x_total")
        with pytest.raises(ValueError):
            r.gauge("x_total")

    def test_label_mismatch_raises(self):
        r = MetricsRegistry()
        r.counter("x_total", labels=("a",))
        with pytest.raises(ValueError):
            r.counter("x_total", labels=("b",))

    def test_histogram_bucket_mismatch_raises(self):
        r = MetricsRegistry()
        r.histogram("h_ms", buckets=(1.0, 10.0))
        with pytest.raises(ValueError):
            r.histogram("h_ms", buckets=(1.0, 10.0, 100.0))
        # same ladder re-registers fine
        assert r.histogram("h_ms", buckets=(1.0, 10.0)) is not None

    def test_reset_preserves_cached_family_references(self):
        """reset() zeroes values in place: a call site holding the
        family (the io/http / trainer caching pattern) keeps feeding
        the SAME exposition afterwards — no orphaned updates."""
        r = MetricsRegistry()
        c = r.counter("c_total")
        c.inc(5)
        h = r.histogram("h_ms")
        h.observe(3.0)
        r.reset()
        assert c.value == 0 and h.stats()["count"] == 0
        c.inc()                              # the cached ref still counts
        assert "c_total 1" in r.render()
        assert r.counter("c_total") is c     # no second family

    def test_invalid_names_rejected(self):
        r = MetricsRegistry()
        with pytest.raises(ValueError):
            r.counter("bad name")
        with pytest.raises(ValueError):
            r.counter("ok_total", labels=("bad-label",))


# ---------------------------------------------------------------------------
# Exposition
# ---------------------------------------------------------------------------

class TestExposition:

    def test_golden(self):
        """Byte-stable golden: the full Prometheus text format for a
        registry with all three kinds, labels, and escaping."""
        r = MetricsRegistry()
        c = r.counter("requests_total", "Total requests.",
                      labels=("path", "status"))
        c.labels("/predict", "200").inc(3)
        c.labels('/we"ird', "500").inc()
        r.gauge("backlog", "Accepted, undispatched.").set(7)
        h = r.histogram("latency_ms", "Request latency.",
                        buckets=(1.0, 2.5))
        h.observe(0.5)
        h.observe(2.5)
        h.observe(99.0)
        assert r.render() == (
            '# HELP backlog Accepted, undispatched.\n'
            '# TYPE backlog gauge\n'
            'backlog 7\n'
            '# HELP latency_ms Request latency.\n'
            '# TYPE latency_ms histogram\n'
            'latency_ms_bucket{le="1"} 1\n'
            'latency_ms_bucket{le="2.5"} 2\n'
            'latency_ms_bucket{le="+Inf"} 3\n'
            'latency_ms_sum 102\n'
            'latency_ms_count 3\n'
            '# HELP requests_total Total requests.\n'
            '# TYPE requests_total counter\n'
            'requests_total{path="/predict",status="200"} 3\n'
            'requests_total{path="/we\\"ird",status="500"} 1\n'
        )

    def test_parse_round_trip(self):
        r = MetricsRegistry()
        r.counter("x_total", labels=("k",)).labels("v").inc(4)
        samples = parse_prometheus(r.render())
        assert ("x_total", (("k", "v"),), 4.0) in samples

    def test_merge_sums_across_scrapes(self):
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.counter("x_total").inc(2)
        r2.counter("x_total").inc(5)
        r2.counter("only_here_total").inc()
        merged = merge_prometheus([r1.render(), r2.render()])
        assert merged[("x_total", ())] == 7.0
        assert merged[("only_here_total", ())] == 1.0

    def test_parse_round_trips_hostile_label_values(self):
        """Values containing '}', quotes, literal backslashes, and
        backslash-n must survive render -> parse exactly (the fleet
        merge depends on it)."""
        for hostile in ('x}y', 'a"b', 'a\\nb', 'a\nb', 'tr{icky},v'):
            r = MetricsRegistry()
            r.counter("x_total", labels=("k",)).labels(hostile).inc()
            samples = parse_prometheus(r.render())
            assert ("x_total", (("k", hostile),), 1.0) in samples, hostile

    def test_render_samples_round_trips_merge(self):
        """parse -> merge -> render_samples -> parse is a fixed point,
        including hostile label values (the /fleet/metrics path)."""
        from mmlspark_tpu.core.telemetry import render_samples
        r = MetricsRegistry()
        r.counter("x_total", labels=("k",)).labels('new\nline').inc(2)
        r.gauge("g").set(1.5)
        merged = merge_prometheus([r.render(), r.render()])
        text = render_samples(merged)
        assert merge_prometheus([text]) == merged


# ---------------------------------------------------------------------------
# Trace ids
# ---------------------------------------------------------------------------

class TestTraceContext:

    def test_bind_and_reset(self):
        assert current_trace_id() is None
        with trace_context("abc") as tid:
            assert tid == "abc"
            assert current_trace_id() == "abc"
            with trace_context() as inner:
                assert current_trace_id() == inner != "abc"
            assert current_trace_id() == "abc"
        assert current_trace_id() is None

    def test_new_ids_unique(self):
        ids = {new_trace_id() for _ in range(1000)}
        assert len(ids) == 1000

    def test_from_headers_adopts_and_sanitizes(self):
        assert trace_id_from_headers({"X-Trace-Id": "keep-me"}) == "keep-me"
        weird = trace_id_from_headers({"X-Trace-Id": ' a"b\\c\nd '})
        assert weird == "abcd"
        minted = trace_id_from_headers({})
        assert minted and minted != trace_id_from_headers(None)

    def test_does_not_cross_threads(self):
        """Contextvars stay thread-local: the staged pipeline must
        re-bind from the work item (which ServingServer does)."""
        seen = []
        with trace_context("outer"):
            t = threading.Thread(
                target=lambda: seen.append(current_trace_id()))
            t.start()
            t.join()
        assert seen == [None]


class TestLogIntegration:

    def _record(self, msg="hello"):
        return logging.LogRecord("mmlspark_tpu.test", logging.INFO,
                                 __file__, 1, msg, (), None)

    def test_json_formatter_carries_trace(self):
        from mmlspark_tpu.core.logs import make_formatter
        fmt = make_formatter("json")
        with trace_context("tid-1"):
            out = json.loads(fmt.format(self._record()))
        assert out["message"] == "hello"
        assert out["trace_id"] == "tid-1"
        assert out["level"] == "INFO"

    def test_plain_formatter_appends_trace_only_when_bound(self):
        from mmlspark_tpu.core.logs import make_formatter
        fmt = make_formatter("plain")
        assert "trace=" not in fmt.format(self._record())
        with trace_context("tid-2"):
            assert fmt.format(self._record()).endswith("trace=tid-2")

    def test_filter_stamps_records(self):
        from mmlspark_tpu.core.logs import _TraceFilter
        rec = self._record()
        with trace_context("tid-3"):
            assert _TraceFilter().filter(rec)
        assert rec.trace_id == "tid-3"

    def test_reconfigure_swaps_format_without_dropping_handler(self):
        """The runtime log-format flip keeps the handler installed
        throughout (records emitted mid-flip are never dropped) and
        round-trips plain -> json -> plain."""
        import os
        from mmlspark_tpu.core import logs
        logs.get_logger("telemetry-test")       # ensure configured
        root = logging.getLogger("mmlspark_tpu")
        n_handlers = len(root.handlers)
        assert n_handlers >= 1
        os.environ["MMLSPARK_TPU_LOGGING_FORMAT"] = "json"
        try:
            logs.reconfigure()
            assert len(root.handlers) == n_handlers
            out = json.loads(root.handlers[0].formatter.format(
                self._record("flip")))
            assert out["message"] == "flip"
        finally:
            del os.environ["MMLSPARK_TPU_LOGGING_FORMAT"]
            logs.reconfigure()
        assert "flip" in root.handlers[0].formatter.format(
            self._record("flip"))
        assert len(root.handlers) == n_handlers


# ---------------------------------------------------------------------------
# StageTimings as a registry view
# ---------------------------------------------------------------------------

class TestStageTimings:

    def test_snapshot_has_max_and_reset(self):
        from mmlspark_tpu.core.profiling import StageTimings
        clock = iter([0.0, 0.010, 1.0, 1.002]).__next__
        t = StageTimings(clock=clock)
        with t.span("s"):
            pass
        with t.span("s"):
            pass
        snap = t.snapshot()["s"]
        assert snap["count"] == 2
        assert snap["max_ms"] == 10.0
        assert snap["last_ms"] == 2.0
        assert snap["total_ms"] == 12.0
        t.reset()
        assert t.snapshot()["s"]["count"] == 0

    def test_shares_registry_with_metrics(self):
        from mmlspark_tpu.core.profiling import StageTimings
        r = MetricsRegistry()
        t = StageTimings(registry=r, metric="spans_ms")
        with t.span("collect"):
            pass
        assert 'spans_ms_count{stage="collect"} 1' in r.render()

    def test_process_vitals(self):
        from mmlspark_tpu.core.profiling import (
            process_rss_bytes, process_uptime_s)
        assert process_uptime_s() > 0
        rss = process_rss_bytes()
        assert rss is None or rss > 1024 * 1024


# ---------------------------------------------------------------------------
# Live server: /metrics + trace end-to-end
# ---------------------------------------------------------------------------

class _Doubler:
    pass


def _doubler():
    from mmlspark_tpu.core.stage import Transformer

    class Doubler(Transformer):
        def transform(self, df):
            return df.with_column(
                "y", np.asarray(df["x"], dtype=np.float64) * 2)

    return Doubler()


class TestServingTelemetry:

    def test_metrics_endpoint_valid_and_consistent(self):
        from mmlspark_tpu.serving import ServingServer
        with ServingServer(_doubler(), max_batch_size=4,
                           max_latency_ms=5) as srv:
            srv.warmup({"x": 0.0})
            for i in range(3):
                requests.post(srv.address, json={"x": float(i)},
                              timeout=10)
            base = srv.address.rsplit("/", 1)[0]
            resp = requests.get(base + "/metrics", timeout=10)
            assert resp.status_code == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            samples = dict(
                ((n, l), v) for n, l, v in parse_prometheus(resp.text))
            stats = requests.get(base + "/stats", timeout=10).json()
            # the registry views and /stats read the same state
            assert samples[("serving_requests_total", ())] == \
                stats["n_requests"]
            assert samples[("serving_recompiles_total", ())] == \
                stats["n_recompiles"]
            assert samples[("serving_batches_total", ())] == \
                stats["n_batches"]
            # per-bucket dispatch histogram covers every warmed bucket
            for b in stats["dispatch_sizes"]:
                assert samples[("serving_dispatch_latency_ms_count",
                                (("bucket", str(b)),))] > 0
            # stage spans appear in BOTH surfaces with equal counts
            for stage, t in stats["stage_timings"].items():
                assert samples[("serving_stage_duration_ms_count",
                                (("stage", stage),))] == t["count"]
            assert samples[("process_uptime_seconds", ())] > 0

    def test_stats_gains_vitals_keeps_existing_keys(self):
        from mmlspark_tpu.serving import ServingServer
        with ServingServer(_doubler(), max_batch_size=4) as srv:
            base = srv.address.rsplit("/", 1)[0]
            stats = requests.get(base + "/stats", timeout=10).json()
        for key in ("pipeline", "bucket_batches", "encoder_threads",
                    "n_batches", "n_requests", "n_recompiles",
                    "dispatch_sizes", "inflight_batches", "queue_depth",
                    "stage_timings", "uptime_s", "rss_bytes"):
            assert key in stats

    def test_trace_id_echoed_and_minted(self):
        from mmlspark_tpu.serving import ServingServer
        with ServingServer(_doubler(), max_batch_size=4,
                           max_latency_ms=5) as srv:
            srv.warmup({"x": 0.0})
            r = requests.post(srv.address, json={"x": 1.0},
                              headers={"X-Trace-Id": "client-trace-7"},
                              timeout=10)
            assert r.headers["X-Trace-Id"] == "client-trace-7"
            assert r.json() == {"y": 2.0}
            r2 = requests.post(srv.address, json={"x": 2.0}, timeout=10)
            assert r2.headers.get("X-Trace-Id")  # minted at ingress

    def test_trace_id_lands_in_journal_lines(self, tmp_path):
        from mmlspark_tpu.serving import ServingServer
        path = str(tmp_path / "journal.jsonl")
        srv = ServingServer(_doubler(), max_batch_size=4,
                            max_latency_ms=5, journal_path=path)
        srv.warmup({"x": 0.0})
        srv.start()
        try:
            r = requests.post(
                srv.address, json={"x": 5.0},
                headers={"X-Trace-Id": "journal-trace",
                         "X-Request-Id": "rid-1"}, timeout=10)
            assert r.status_code == 200
        finally:
            srv.stop()
        recs = [json.loads(l) for l in open(path) if l.strip()]
        mine = [rec for rec in recs if rec["rid"] == "rid-1"]
        assert mine and mine[0]["trace"] == "journal-trace"

    def test_trace_replayed_after_journal_recovery(self, tmp_path):
        from mmlspark_tpu.serving import ServingServer
        path = str(tmp_path / "journal.jsonl")
        srv = ServingServer(_doubler(), max_batch_size=4,
                            max_latency_ms=5, journal_path=path)
        srv.start()
        try:
            requests.post(srv.address, json={"x": 5.0},
                          headers={"X-Trace-Id": "t-orig",
                                   "X-Request-Id": "rid-2"}, timeout=10)
        finally:
            srv.stop()
        srv2 = ServingServer(_doubler(), max_batch_size=4,
                             journal_path=path)
        assert srv2.n_journal_recovered == 1
        assert srv2._journal["rid-2"][3] == "t-orig"


# ---------------------------------------------------------------------------
# Fleet aggregation
# ---------------------------------------------------------------------------

class TestFleetView:

    def _slow_doubler(self, delay_s):
        from mmlspark_tpu.core.stage import Transformer

        class Slow(Transformer):
            def transform(self, df):
                time.sleep(delay_s)
                return df.with_column(
                    "y", np.asarray(df["x"], dtype=np.float64) * 2)

        return Slow()

    def test_merge_over_two_workers(self):
        """The merged fleet view sums per-worker counters exactly,
        identifies the slowest stage, and attributes it to the slow
        worker — the ROADMAP item this subsystem closes."""
        from mmlspark_tpu.serving import ServingCoordinator, ServingServer
        fast = ServingServer(_doubler(), max_batch_size=4,
                             max_latency_ms=2)
        slow = ServingServer(self._slow_doubler(0.05), max_batch_size=8,
                             max_latency_ms=2)
        for s in (fast, slow):
            s.warmup({"x": 0.0})
            s.start()
        coord = ServingCoordinator().start()
        curl = f"http://{coord.host}:{coord.port}"
        try:
            for s in (fast, slow):
                ServingCoordinator.register_worker(curl, s.host, s.port)
            for s in (fast, slow):
                for i in range(2):
                    requests.post(f"http://{s.host}:{s.port}/predict",
                                  json={"x": float(i)}, timeout=10)
            fleet = requests.get(curl + "/fleet", timeout=10).json()
            assert fleet["n_workers"] == 2
            assert fleet["n_responding"] == 2
            stats_f = requests.get(
                f"http://{fast.host}:{fast.port}/stats", timeout=10).json()
            stats_s = requests.get(
                f"http://{slow.host}:{slow.port}/stats", timeout=10).json()
            assert fleet["totals"]["n_requests"] == \
                stats_f["n_requests"] + stats_s["n_requests"]
            assert fleet["totals"]["n_batches"] == \
                stats_f["n_batches"] + stats_s["n_batches"]
            # the slow worker's 50 ms model dominates: dispatch is the
            # fleet's slowest stage and is attributed to that worker
            assert fleet["slowest_stage"]["stage"] == "dispatch"
            assert fleet["slowest_stage"]["worker"] == \
                f"{slow.host}:{slow.port}"
            # widest compiled bucket across the fleet (slow has cap 8)
            assert fleet["widest_bucket"] == 8
            # merged stage timings: counts sum across workers
            merged_dispatch = fleet["stage_timings"]["dispatch"]
            assert merged_dispatch["count"] == \
                stats_f["stage_timings"]["dispatch"]["count"] + \
                stats_s["stage_timings"]["dispatch"]["count"]
            # merged exposition: counters sum exactly
            fm = requests.get(curl + "/fleet/metrics", timeout=10).text
            merged = dict(((n, l), v) for n, l, v in parse_prometheus(fm))
            assert merged[("serving_requests_total", ())] == \
                stats_f["n_requests"] + stats_s["n_requests"]
        finally:
            coord.stop()
            fast.stop()
            slow.stop()

    def test_fleet_metrics_excludes_shared_process_registry(self):
        """Two workers in ONE process share the global REGISTRY: the
        merged fleet exposition must not sum its families once per
        worker (it scrapes ?scope=server), while each worker's own
        /metrics still includes them."""
        from mmlspark_tpu.core.telemetry import REGISTRY
        from mmlspark_tpu.serving import ServingCoordinator, ServingServer
        marker = REGISTRY.counter("test_fleet_dedupe_total")
        marker.labels()        # ensure the family renders
        s1 = ServingServer(_doubler(), max_batch_size=4)
        s2 = ServingServer(_doubler(), max_batch_size=4)
        coord = ServingCoordinator().start()
        curl = f"http://{coord.host}:{coord.port}"
        try:
            s1.start()
            s2.start()
            for s in (s1, s2):
                ServingCoordinator.register_worker(curl, s.host, s.port)
            full = requests.get(
                f"http://{s1.host}:{s1.port}/metrics", timeout=10).text
            assert "test_fleet_dedupe_total" in full
            scoped = requests.get(
                f"http://{s1.host}:{s1.port}/metrics?scope=server",
                timeout=10).text
            assert "test_fleet_dedupe_total" not in scoped
            assert "serving_requests_total" in scoped
            fm = requests.get(curl + "/fleet/metrics", timeout=10).text
            assert "test_fleet_dedupe_total" not in fm
            assert "serving_requests_total" in fm
        finally:
            coord.stop()
            s1.stop()
            s2.stop()

    def test_dead_worker_does_not_fail_fleet_view(self):
        from mmlspark_tpu.serving import ServingCoordinator, ServingServer
        srv = ServingServer(_doubler(), max_batch_size=4)
        srv.start()
        coord = ServingCoordinator().start()
        curl = f"http://{coord.host}:{coord.port}"
        try:
            ServingCoordinator.register_worker(curl, srv.host, srv.port)
            # a registered-but-dead worker (nothing listens on port 9)
            requests.post(curl + "/register",
                          json={"host": "127.0.0.1", "port": 9},
                          timeout=10)
            fleet = coord.fleet_stats(timeout=2.0)
            assert fleet["n_workers"] == 2
            assert fleet["n_responding"] == 1
            assert "error" in fleet["workers"]["127.0.0.1:9"]
            # the merged exposition flags the dead worker instead of
            # silently summing an incomplete fleet
            merged = dict(
                ((n, l), v) for n, l, v in
                parse_prometheus(coord.fleet_metrics(timeout=2.0)))
            assert merged[("serving_worker_up",
                           (("worker", "127.0.0.1:9"),))] == 0.0
            assert merged[("serving_worker_up",
                           (("worker", f"{srv.host}:{srv.port}"),))] == 1.0
        finally:
            coord.stop()
            srv.stop()


# ---------------------------------------------------------------------------
# Hot-path overhead
# ---------------------------------------------------------------------------

@pytest.mark.perf
class TestOverhead:
    """The <2 us/update budget that makes always-on telemetry viable
    (headline numbers live in bench.py's ``telemetry_overhead_v1``)."""

    BUDGET_NS = 2000

    def _per_op_ns(self, fn, n=20000, rounds=3):
        best = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter_ns()
            for _ in range(n):
                fn()
            best = min(best, (time.perf_counter_ns() - t0) / n)
        return best

    def test_counter_inc_under_budget(self):
        child = MetricsRegistry().counter("c_total", labels=("k",)) \
                                 .labels("hot")
        assert self._per_op_ns(child.inc) < self.BUDGET_NS

    def test_histogram_observe_under_budget(self):
        child = MetricsRegistry().histogram("h_ms").labels()
        assert self._per_op_ns(lambda: child.observe(3.7)) < self.BUDGET_NS

    def test_stage_timings_span_under_budget(self):
        from mmlspark_tpu.core.profiling import StageTimings
        t = StageTimings()

        def one():
            with t.span("hot"):
                pass

        # a span adds generator-contextmanager machinery + two clock
        # reads on top of the observe; it runs per BATCH (not per
        # request), so its budget is looser: 4x
        assert self._per_op_ns(one) < 4 * self.BUDGET_NS
