"""Pipeline stages: frame utilities, data prep, batching, image ops."""

from mmlspark_tpu.stages.basic import (
    DropColumns, SelectColumns, RenameColumn, Repartition, Cacher,
    CheckpointData, Explode, Lambda, ScaleColumn, UDFTransformer,
    TextPreprocessor,
    UnicodeNormalize, ClassBalancer, ClassBalancerModel, PartitionSample,
    MultiColumnAdapter, EnsembleByKey, SummarizeData, Timer, TimerModel,
)
from mmlspark_tpu.stages.prep import (
    ValueIndexer, ValueIndexerModel, IndexToValue,
    CleanMissingData, CleanMissingDataModel, DataConversion,
)
from mmlspark_tpu.stages.batching import (
    BucketBatcher, FixedBatcher, DynamicBufferedBatcher, TimeIntervalBatcher,
    FixedMiniBatchTransformer, DynamicMiniBatchTransformer, FlattenBatch,
)
from mmlspark_tpu.stages.image import (
    ImageTransformer, ResizeImageTransformer, UnrollImage, UnrollBinaryImage,
    ImageSetAugmenter,
)

__all__ = [
    "DropColumns", "SelectColumns", "RenameColumn", "Repartition", "Cacher",
    "CheckpointData", "Explode", "Lambda", "ScaleColumn", "UDFTransformer",
    "TextPreprocessor", "UnicodeNormalize", "ClassBalancer",
    "ClassBalancerModel", "PartitionSample", "MultiColumnAdapter",
    "EnsembleByKey", "SummarizeData", "Timer", "TimerModel",
    "ValueIndexer", "ValueIndexerModel", "IndexToValue",
    "CleanMissingData", "CleanMissingDataModel", "DataConversion",
    "BucketBatcher", "FixedBatcher", "DynamicBufferedBatcher",
    "TimeIntervalBatcher",
    "FixedMiniBatchTransformer", "DynamicMiniBatchTransformer", "FlattenBatch",
    "ImageTransformer", "ResizeImageTransformer", "UnrollImage",
    "UnrollBinaryImage", "ImageSetAugmenter",
]
