"""Image pipeline stages: the OpenCV-Transformer replacement.

Capability parity with `image-transformer/src/main/scala/
ImageTransformer.scala` (stage-list transformer), `ResizeImageTransformer.
scala`, `UnrollImage.scala`, and `ImageSetAugmenter.scala` — executed
TPU-first: rows are bucketed by image shape, each bucket is stacked into
an NHWC batch and pushed through ONE jitted op-chain on device, then
scattered back to rows. (The reference instead loops rows through JNI.)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param, HasInputCol, HasOutputCol
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.ops import image as ops


def _bucket_by_shape(images: Sequence[np.ndarray]) -> Dict[Tuple[int, ...], List[int]]:
    buckets: Dict[Tuple[int, ...], List[int]] = {}
    for i, im in enumerate(images):
        buckets.setdefault(tuple(np.asarray(im).shape), []).append(i)
    return buckets


def _apply_bucketed(images: Sequence[np.ndarray],
                    fn: Callable[[Any], Any]) -> List[np.ndarray]:
    """Stack same-shape rows, run one jitted program per shape, scatter back."""
    import jax
    out: List[Optional[np.ndarray]] = [None] * len(images)
    jitted = jax.jit(fn)
    for shape, idxs in _bucket_by_shape(images).items():
        batch = np.stack([np.asarray(images[i], dtype=np.float32) for i in idxs])
        result = np.asarray(jitted(batch))
        for j, i in enumerate(idxs):
            out[i] = result[j]
    return out  # type: ignore[return-value]


class ImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Applies a configured chain of image ops to an image column.

    Fluent stage list mirroring the reference API::

        ImageTransformer().resize(32, 32).flip().normalize(...)

    Parity: ImageTransformer.scala:22-207,237,266.
    """

    input_col = Param("image", "image column", ptype=str)
    output_col = Param("image", "output column", ptype=str)
    stages = Param(None, "list of (op, kwargs) image stages", ptype=list)

    def _stages(self) -> List[Tuple[str, Dict[str, Any]]]:
        return list(self.stages or [])

    def _add(self, op: str, **kwargs) -> "ImageTransformer":
        self.stages = self._stages() + [(op, kwargs)]
        return self

    # fluent builders (names mirror the reference's stage names)
    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._add("resize", height=height, width=width)

    def crop(self, x: int, y: int, height: int, width: int) -> "ImageTransformer":
        return self._add("crop", x0=x, y0=y, height=height, width=width)

    def center_crop(self, height: int, width: int) -> "ImageTransformer":
        return self._add("center_crop", height=height, width=width)

    def color_format(self, fmt: str) -> "ImageTransformer":
        return self._add("color_format", fmt=fmt)

    def blur(self, height: int, width: int) -> "ImageTransformer":
        return self._add("box_blur", kh=int(height), kw=int(width))

    def threshold(self, threshold: float, max_val: float = 255.0,
                  threshold_type: int = ops.THRESH_BINARY) -> "ImageTransformer":
        return self._add("threshold", thresh=threshold, max_val=max_val,
                         threshold_type=threshold_type)

    def gaussian_kernel(self, apperture_size: int, sigma: float) -> "ImageTransformer":
        return self._add("gaussian_blur", radius=int(apperture_size), sigma=sigma)

    def flip(self, flip_code: int = ops.FLIP_HORIZONTAL) -> "ImageTransformer":
        return self._add("flip", flip_code=flip_code)

    def normalize(self, mean: Sequence[float], std: Sequence[float],
                  scale: float = 1.0) -> "ImageTransformer":
        return self._add("normalize", mean=list(mean), std=list(std), scale=scale)

    # execution
    _OPS: Dict[str, Callable] = {
        "resize": ops.resize,
        "crop": ops.crop,
        "center_crop": ops.center_crop,
        "color_format": ops.color_format,
        "box_blur": ops.box_blur,
        "threshold": ops.threshold,
        "gaussian_blur": ops.gaussian_blur,
        "flip": ops.flip,
        "normalize": ops.normalize,
    }

    def _chain(self):
        stages = self._stages()

        def apply(batch):
            for op, kwargs in stages:
                batch = self._OPS[op](batch, **kwargs)
            return batch
        return apply

    def transform(self, df: DataFrame) -> DataFrame:
        col = df[self.input_col]
        if col.dtype == np.dtype("O"):
            images = list(col)
            out = _apply_bucketed(images, self._chain())
            shapes = {o.shape for o in out}
            if len(shapes) == 1:
                return df.with_column(self.output_col, np.stack(out))
            return df.with_column(self.output_col, np.array(out, dtype=object))
        # already a stacked NHWC tensor column: one jitted call
        import jax
        out = np.asarray(jax.jit(self._chain())(col.astype(np.float32)))
        return df.with_column(self.output_col, out)


class ResizeImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Resize-only transformer (parity: ResizeImageTransformer.scala:17,54)."""

    input_col = Param("image", "image column", ptype=str)
    output_col = Param("image", "output column", ptype=str)
    height = Param(None, "target height", ptype=int)
    width = Param(None, "target width", ptype=int)

    def transform(self, df: DataFrame) -> DataFrame:
        return ImageTransformer(
            input_col=self.input_col, output_col=self.output_col,
        ).resize(self.height, self.width).transform(df)


class UnrollImage(Transformer, HasInputCol, HasOutputCol):
    """Image column -> flat CHW feature-vector column.

    Parity: UnrollImage.scala:21,25,84 (CHW unroll to DenseVector).
    """

    input_col = Param("image", "image column", ptype=str)
    output_col = Param("features", "output vector column", ptype=str)

    def transform(self, df: DataFrame) -> DataFrame:
        col = df[self.input_col]
        if col.dtype == np.dtype("O"):
            col = np.stack([np.asarray(v, dtype=np.float32) for v in col])
        import jax
        out = np.asarray(jax.jit(ops.unroll)(col.astype(np.float32)))
        return df.with_column(self.output_col, out)


class UnrollBinaryImage(Transformer, HasInputCol, HasOutputCol):
    """Encoded image bytes -> flat CHW vector, decoding host-side.

    Parity: UnrollBinaryImage (UnrollImage.scala:122).
    """

    input_col = Param("bytes", "binary image column", ptype=str)
    output_col = Param("features", "output vector column", ptype=str)
    height = Param(None, "optional resize height", ptype=int)
    width = Param(None, "optional resize width", ptype=int)

    def transform(self, df: DataFrame) -> DataFrame:
        from mmlspark_tpu.io.images import decode_image
        images = [decode_image(b) for b in df[self.input_col]]
        bad = [i for i, im in enumerate(images) if im is None]
        if bad:
            raise ValueError(f"undecodable images at rows {bad[:10]}")
        work = df.with_column("__img", np.array(images, dtype=object))
        if self.height is not None and self.width is not None:
            work = ResizeImageTransformer(input_col="__img", output_col="__img",
                                          height=self.height,
                                          width=self.width).transform(work)
        out = UnrollImage(input_col="__img",
                          output_col=self.output_col).transform(work)
        return out.drop("__img")


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    """Expand a dataset with flipped copies (parity: ImageSetAugmenter.scala)."""

    input_col = Param("image", "image column", ptype=str)
    output_col = Param("image", "output column", ptype=str)
    flip_left_right = Param(True, "add horizontally flipped copies", ptype=bool)
    flip_up_down = Param(False, "add vertically flipped copies", ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        base = df if self.input_col == self.output_col else \
            df.with_column(self.output_col, df[self.input_col])
        frames = [base]
        for enabled, code in ((self.flip_left_right, ops.FLIP_HORIZONTAL),
                              (self.flip_up_down, ops.FLIP_VERTICAL)):
            if enabled:
                flipper = ImageTransformer(input_col=self.input_col,
                                           output_col=self.output_col).flip(code)
                frames.append(flipper.transform(df))
        return DataFrame.concat(frames)
