"""Small DataFrame operations packaged as pipeline stages.

Capability parity with the reference's `src/pipeline-stages` module
(`pipeline-stages/src/main/scala/*.scala`): tiny, composable frame→frame
stages so whole workflows serialize as one Pipeline. Also hosts the
multi-column adapter (`multi-column-adapter/MultiColumnAdapter.scala:17`),
partition sampling (`partition-sample/PartitionSample.scala:141`), dataset
checkpointing (`checkpoint-data/CheckpointData.scala:49`), and key-grouped
ensembling (`ensemble/EnsembleByKey.scala:21`).

TPU-native notes: these run host-side on the columnar frame (pure numpy) —
they shape data *around* device work and must not trace. ``EnsembleByKey``'s
grouped averaging is the only numeric hot spot and uses vectorized
segment-sums rather than per-group Python loops.
"""

from __future__ import annotations

import os
import unicodedata
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, py_scalar as _py, \
    is_null as _is_null, obj_col as _obj_col
from mmlspark_tpu.core.params import (
    Param, HasInputCol, HasInputCols, HasOutputCol, HasOutputCols,
    HasLabelCol, in_set, in_range,
)
from mmlspark_tpu.core.stage import Transformer, Estimator, Model, PipelineStage
# re-exported here because this module is the parity home of the
# reference's pipeline-stages (`Timer.scala:14-90`): the Timer wraps any
# stage, logs its fit/transform wall-clock, AND records every span into
# the process-wide metrics registry (pipeline_stage_duration_ms), so
# batch pipelines and the serving plane report through one telemetry
# surface — see docs/observability.md
from mmlspark_tpu.core.stage import Timer, TimerModel  # noqa: F401


class DropColumns(Transformer):
    """Drop the listed columns (`pipeline-stages/DropColumns.scala`)."""

    cols = Param(None, "columns to drop", ptype=list)

    def transform(self, df: DataFrame) -> DataFrame:
        return df.drop(*(self.cols or []))


class SelectColumns(Transformer):
    """Keep only the listed columns (`pipeline-stages/SelectColumns.scala`)."""

    cols = Param(None, "columns to keep", ptype=list)

    def transform(self, df: DataFrame) -> DataFrame:
        return df.select(self.cols or [])


class RenameColumn(Transformer, HasInputCol, HasOutputCol):
    """Rename one column (`pipeline-stages/RenameColumn.scala`)."""

    def transform(self, df: DataFrame) -> DataFrame:
        return df.rename({self.input_col: self.output_col})


class ScaleColumn(Transformer, HasInputCol, HasOutputCol):
    """``output_col = input_col * scale + offset`` — a fully persistable
    arithmetic stage (all-JSON params, no complex state).

    Exists for pipelines that need a cheap numeric map, and as the
    canonical *versionable* serving model: two saved ``ScaleColumn``
    checkpoints with different ``scale`` are distinguishable model
    versions, which the rollout tests and ``tools/chaos_serving.py``'s
    kill-mid-rollout drill stage and flip through real checkpoint
    directories (see docs/serving.md "Zero-downtime rollout")."""

    scale = Param(1.0, "multiplier", ptype=float)
    offset = Param(0.0, "additive constant", ptype=float)

    def transform(self, df: DataFrame) -> DataFrame:
        x = np.asarray(df[self.input_col], dtype=np.float64)
        return df.with_column(self.output_col,
                              x * float(self.scale) + float(self.offset))


class Repartition(Transformer):
    """Reorder rows so ``n`` contiguous shards are statistically similar.

    Parity: `pipeline-stages/Repartition.scala`. The columnar frame has no
    partitions — sharding happens at device dispatch — so the only
    observable effect of a Spark round-robin repartition worth keeping is
    the row dispersal itself (``disperse=True``); with ``disperse=False``
    this is an identity stage kept for pipeline API compatibility.
    """

    n = Param(None, "number of shards", ptype=int, validator=in_range(lo=1))
    disperse = Param(False, "round-robin disperse rows across shards", ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        n = self.n or 1
        if self.disperse and df.num_rows:
            order = np.argsort(np.arange(df.num_rows) % n, kind="stable")
            df = df.take(order)
        return df


class Cacher(Transformer):
    """Materialize the frame (`pipeline-stages/Cacher.scala`).

    Frames here are eager numpy, so caching means ensuring every column is
    a contiguous owned array (detaching views/lazy wrappers).
    """

    def transform(self, df: DataFrame) -> DataFrame:
        data = {k: np.ascontiguousarray(v) if v.dtype != np.dtype("O")
                else v for k, v in df.to_dict().items()}
        return df._derive(data)


class CheckpointData(Transformer):
    """Persist the frame to disk and return the reloaded copy.

    Parity: `checkpoint-data/CheckpointData.scala:49` (cache/persist with a
    storage level). Disk round-trip truncates upstream lineage the way a
    Spark checkpoint does and gives a restartable artifact.
    """

    path = Param(None, "directory to checkpoint into", ptype=str)
    remove_checkpoint = Param(False, "delete the checkpoint after reload",
                              ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        path = self.path
        os.makedirs(path, exist_ok=True)
        df.save(os.path.join(path, "frame.npz"))
        out = DataFrame.load(os.path.join(path, "frame.npz"))
        if self.remove_checkpoint:
            os.remove(os.path.join(path, "frame.npz"))
            meta_path = os.path.join(path, "frame.meta.json")
            if os.path.exists(meta_path):
                os.remove(meta_path)
        return out


class Explode(Transformer, HasInputCol, HasOutputCol):
    """Explode a list-valued column into one row per element.

    Parity: `pipeline-stages/Explode.scala`.
    """

    def transform(self, df: DataFrame) -> DataFrame:
        col = df[self.input_col]
        lengths = np.array([len(v) for v in col], dtype=np.int64)
        idx = np.repeat(np.arange(df.num_rows), lengths)
        flat: List[Any] = [item for v in col for item in v]
        out = df.take(idx)
        return out.with_column(self.output_col or self.input_col, flat)


class Lambda(Transformer):
    """Arbitrary frame→frame function as a stage.

    Parity: `pipeline-stages/Lambda.scala` (arbitrary df→df function).
    The function is a complex param persisted via cloudpickle-free source
    capture is NOT attempted — like the reference's UDF params, a loaded
    Lambda requires re-supplying the function.
    """

    transform_fn = Param(None, "frame -> frame function", complex=True)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.transform_fn(df)


class UDFTransformer(Transformer, HasInputCol, HasInputCols, HasOutputCol):
    """Apply a per-value (or per-row-tuple) function to produce a column.

    Parity: `pipeline-stages/UDFTransformer.scala`. With ``input_col`` the
    udf maps value→value; with ``input_cols`` it maps (v1, v2, ...)→value.
    ``vectorized=True`` passes whole numpy arrays instead (the TPU-friendly
    path — hand the udf arrays, let it call jax itself).
    """

    udf = Param(None, "the function to apply", complex=True)
    vectorized = Param(False, "pass whole columns instead of scalars", ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        fn = self.udf
        if self.input_cols:
            cols = [df[c] for c in self.input_cols]
            if self.vectorized:
                values = fn(*cols)
            else:
                values = [fn(*vals) for vals in zip(*cols)]
        else:
            col = df[self.input_col]
            values = fn(col) if self.vectorized else [fn(v) for v in col]
        return df.with_column(self.output_col, values)


class _Trie:
    """Character trie for longest-match find/replace."""

    __slots__ = ("children", "value")

    def __init__(self):
        self.children: Dict[str, "_Trie"] = {}
        self.value: Optional[str] = None

    def put(self, key: str, value: str) -> None:
        node = self
        for ch in key:
            node = node.children.setdefault(ch, _Trie())
        node.value = value

    def longest_match(self, text: str, start: int):
        node, best = self, None
        for i in range(start, len(text)):
            node = node.children.get(text[i])
            if node is None:
                break
            if node.value is not None:
                best = (i + 1, node.value)
        return best


class TextPreprocessor(Transformer, HasInputCol, HasOutputCol):
    """Trie-based longest-match find/replace over strings.

    Parity: `pipeline-stages/TextPreprocessor.scala:14` (trie find/replace
    with an optional normalization function applied first).
    """

    map = Param(None, "substring -> replacement map", ptype=dict)
    norm_func = Param("identity", "normalization applied before matching",
                      validator=in_set("identity", "lowercase"))

    def transform(self, df: DataFrame) -> DataFrame:
        trie = _Trie()
        for k, v in (self.map or {}).items():
            trie.put(k, v)
        lower = self.norm_func == "lowercase"

        def process(text: str) -> str:
            if lower:
                text = text.lower()
            out, i = [], 0
            while i < len(text):
                m = trie.longest_match(text, i)
                if m is None:
                    out.append(text[i])
                    i += 1
                else:
                    i, val = m
                    out.append(val)
            return "".join(out)

        values = [process(str(v)) for v in df[self.input_col]]
        return df.with_column(self.output_col, values)


class UnicodeNormalize(Transformer, HasInputCol, HasOutputCol):
    """Unicode-normalize strings (`pipeline-stages/UnicodeNormalize.scala`)."""

    form = Param("NFKD", "unicode normal form",
                 validator=in_set("NFC", "NFD", "NFKC", "NFKD"))
    lower = Param(True, "lowercase after normalizing", ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        def norm(v):
            s = unicodedata.normalize(self.form, str(v))
            return s.lower() if self.lower else s
        return df.with_column(self.output_col,
                              [norm(v) for v in df[self.input_col]])


class ClassBalancer(Estimator, HasInputCol, HasOutputCol):
    """Compute inverse-frequency class weights as a column.

    Parity: `pipeline-stages/ClassBalancer.scala` — fit counts each level of
    ``input_col`` and emits weight = max_count / count; the model joins the
    weight back per row (feeds ``HasWeightCol`` learners).
    """

    broadcast_join = Param(True, "unused; kept for API parity", ptype=bool)

    def fit(self, df: DataFrame) -> "ClassBalancerModel":
        from collections import Counter
        counts = Counter(_py(v) for v in df[self.input_col])
        top = max(counts.values())
        levels = sorted(counts, key=lambda v: (isinstance(v, str), str(v)))
        weights = [top / counts[lv] for lv in levels]
        return ClassBalancerModel(
            input_col=self.input_col,
            output_col=self.output_col or "weight",
        )._with_table(levels, weights)


class ClassBalancerModel(Model, HasInputCol, HasOutputCol):
    levels = Param(None, "class levels", ptype=list)
    weights = Param(None, "per-level weights", ptype=list)

    def _with_table(self, levels: List[Any], weights: List[float]):
        self.set(levels=levels, weights=weights)
        return self

    def transform(self, df: DataFrame) -> DataFrame:
        table = {lv: w for lv, w in zip(self.levels, self.weights)}
        col = df[self.input_col]
        out = np.array([table[_py(v)] for v in col], dtype=np.float64)
        return df.with_column(self.output_col or "weight", out)


class PartitionSample(Transformer):
    """Head / random-sample row selection as a stage.

    Parity: `partition-sample/PartitionSample.scala:141` (modes: head,
    random sample, assign-to-partition). The partition-assignment mode maps
    to tagging rows with a shard id column.
    """

    mode = Param("randomSample", "sampling mode",
                 validator=in_set("head", "randomSample", "assignToPartition"))
    count = Param(1000, "rows for head mode", ptype=int)
    percent = Param(0.1, "fraction for randomSample", ptype=float,
                    validator=in_range(0.0, 1.0))
    seed = Param(0, "rng seed", ptype=int)
    new_col_name = Param("Partition", "shard-id column for assignToPartition",
                         ptype=str)
    num_parts = Param(10, "shards for assignToPartition", ptype=int)

    def transform(self, df: DataFrame) -> DataFrame:
        if self.mode == "head":
            return df.head(self.count)
        if self.mode == "randomSample":
            return df.sample(self.percent, seed=self.seed)
        rng = np.random.default_rng(self.seed)
        ids = rng.integers(0, self.num_parts, size=df.num_rows)
        return df.with_column(self.new_col_name, ids.astype(np.int64))


class MultiColumnAdapter(Transformer):
    """Apply a single-column stage across many column pairs.

    Parity: `multi-column-adapter/MultiColumnAdapter.scala:17`. The base
    stage must expose ``input_col``/``output_col`` params; it is copied per
    column pair. Estimator bases: use :class:`MultiColumnAdapterEstimator`.
    """

    base_stage = Param(None, "the single-column stage to replicate", complex=True)
    input_cols = Param(None, "input columns", ptype=list)
    output_cols = Param(None, "output columns", ptype=list)

    def _pairs(self):
        ins, outs = self.input_cols or [], self.output_cols or []
        if len(ins) != len(outs):
            raise ValueError("input_cols and output_cols must align")
        return list(zip(ins, outs))

    def transform(self, df: DataFrame) -> DataFrame:
        for i, o in self._pairs():
            df = self.base_stage.copy(input_col=i, output_col=o).transform(df)
        return df

    def _save_extra(self, path, arrays):
        self._save_substage(path, "base_stage")

    def _load_extra(self, path, arrays):
        self._load_substage(path, "base_stage")


class EnsembleByKey(Transformer):
    """Group rows by key column(s); average (or collect) value columns.

    Parity: `ensemble/EnsembleByKey.scala:21` — used to ensemble per-model
    scores sharing an id. Vector and scalar columns both average; string
    strategy is "collect". Uses ``np.add.at`` segment sums, no per-group
    Python loop.
    """

    keys = Param(None, "key columns", ptype=list)
    cols = Param(None, "value columns to aggregate", ptype=list)
    strategy = Param("mean", "aggregation strategy", validator=in_set("mean"))
    collapse_group = Param(True, "one row per key (vs broadcast back)", ptype=bool)
    vector_dims = Param(None, "unused; API parity", ptype=dict)

    def transform(self, df: DataFrame) -> DataFrame:
        keys = self.keys or []
        key_tuples = list(zip(*[df[k] for k in keys]))
        uniq: Dict[tuple, int] = {}
        group = np.empty(df.num_rows, dtype=np.int64)
        for row_i, kt in enumerate(key_tuples):
            kt = tuple(_py(v) for v in kt)
            group[row_i] = uniq.setdefault(kt, len(uniq))
        n_groups = len(uniq)
        counts = np.bincount(group, minlength=n_groups).astype(np.float64)

        data: Dict[str, Any] = {}
        meta: Dict[str, Any] = {}
        for j, k in enumerate(keys):
            vals = [kt[j] for kt in uniq.keys()]
            data[k] = vals if vals and isinstance(vals[0], str) else np.asarray(vals)
        for c in self.cols or []:
            col = df[c]
            if col.dtype == np.dtype("O"):
                collected: List[List[Any]] = [[] for _ in range(n_groups)]
                for g, v in zip(group, col):
                    collected[g].append(v)
                data[f"{c}_collected"] = _obj_col(collected)
                continue
            sums = np.zeros((n_groups,) + col.shape[1:], dtype=np.float64)
            np.add.at(sums, group, col.astype(np.float64))
            denom = counts.reshape((n_groups,) + (1,) * (col.ndim - 1))
            data[f"{c}_mean"] = sums / np.maximum(denom, 1.0)
            if df.get_metadata(c):
                meta[f"{c}_mean"] = dict(df.get_metadata(c))

        out = DataFrame(data, metadata=meta)
        if self.collapse_group:
            return out
        joined = df
        for name in out.columns:
            if name in keys:
                continue
            col = out[name]
            if col.dtype == np.dtype("O"):
                joined = joined.with_column(
                    name, _obj_col([col[g] for g in group]))
            else:
                joined = joined.with_column(name, col[group])
        return joined


class SummarizeData(Transformer):
    """Per-column counts / basic stats / percentiles as a frame.

    Parity: `summarize-data/SummarizeData.scala:99` (counts, basic stats,
    sample percentiles; error-threshold param kept for API parity — the
    percentiles here are exact).
    """

    counts = Param(True, "include count/unique/missing", ptype=bool)
    basic = Param(True, "include mean/std/min/max", ptype=bool)
    percentiles = Param(True, "include p0.5/1/5/25/50/75/95/99/99.5", ptype=bool)
    error_threshold = Param(0.0, "approximation error (exact here)", ptype=float)

    _PCTS = [0.5, 1, 5, 25, 50, 75, 95, 99, 99.5]

    def transform(self, df: DataFrame) -> DataFrame:
        rows: List[Dict[str, Any]] = []
        for name in df.columns:
            col = df[name]
            row: Dict[str, Any] = {"Feature": name}
            is_num = col.dtype != np.dtype("O") and col.ndim == 1 \
                and col.dtype.kind in "bifu"
            vals = col.astype(np.float64) if is_num else None
            finite = vals[np.isfinite(vals)] if is_num else None
            if self.counts:
                row["Count"] = float(len(col))
                if is_num:
                    row["Unique Value Count"] = float(len(np.unique(col)))
                    row["Missing Value Count"] = float(np.sum(~np.isfinite(vals)))
                else:
                    row["Unique Value Count"] = float(len(set(map(str, col))))
                    row["Missing Value Count"] = float(
                        sum(_is_null(v) for v in col))
            if self.basic:
                row["Mean"] = float(np.mean(finite)) if is_num and len(finite) else float("nan")
                row["Standard Deviation"] = (
                    float(np.std(finite, ddof=1)) if is_num and len(finite) > 1
                    else float("nan"))
                row["Min"] = float(np.min(finite)) if is_num and len(finite) else float("nan")
                row["Max"] = float(np.max(finite)) if is_num and len(finite) else float("nan")
            if self.percentiles:
                for p in self._PCTS:
                    key = f"P{p}"
                    row[key] = (float(np.percentile(finite, p))
                                if is_num and len(finite) else float("nan"))
            rows.append(row)
        return DataFrame.from_rows(rows)
