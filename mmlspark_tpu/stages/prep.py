"""Data-preparation estimators: indexing, imputation, type conversion.

Capability parity with the reference's data-prep modules:
- ``ValueIndexer``/``IndexToValue`` — typed, null-ordering-aware categorical
  indexing with inverse (`value-indexer/ValueIndexer.scala:54,101`,
  `IndexToValue.scala:26`, null ordering at `ValueIndexer.scala:38`).
- ``CleanMissingData`` — per-column mean/median/custom imputation
  (`clean-missing-data/CleanMissingData.scala:46,127`).
- ``DataConversion`` — column type conversion + date formatting
  (`data-conversion/DataConversion.scala:23`).

These run host-side (numpy) and stamp categorical metadata so downstream
AutoML featurization and the GBDT engine see the levels
(`core/schema/Categoricals.scala` parity via ``core.schema``).
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, py_scalar as _py, \
    is_null as _is_null, obj_col as _obj_col
from mmlspark_tpu.core.params import (
    Param, HasInputCol, HasOutputCol, in_set,
)
from mmlspark_tpu.core import schema as S
from mmlspark_tpu.core.stage import Transformer, Estimator, Model


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Index a column's distinct values to [0, n) with typed level metadata.

    Parity: `value-indexer/ValueIndexer.scala:54` — levels are sorted in
    the column's natural order with nulls placed per ``null_ordering``
    (`ValueIndexer.scala:38`); the output column carries categorical
    metadata consumed by `IndexToValue` and AutoML featurization.
    """

    null_ordering = Param("nullsFirst", "where nulls sort",
                          validator=in_set("nullsFirst", "nullsLast", "none"))

    def fit(self, df: DataFrame) -> "ValueIndexerModel":
        col = df[self.input_col]
        values = [_py(v) for v in col]
        non_null = sorted({v for v in values if not _is_null(v)},
                          key=lambda v: (isinstance(v, str), v))
        has_null = any(_is_null(v) for v in values)
        levels: List[Any] = list(non_null)
        if has_null and self.null_ordering != "none":
            if self.null_ordering == "nullsFirst":
                levels = [None] + levels
            else:
                levels = levels + [None]
        return ValueIndexerModel(
            input_col=self.input_col,
            output_col=self.output_col or f"{self.input_col}_indexed",
            levels=levels,
        )


class ValueIndexerModel(Model, HasInputCol, HasOutputCol):
    """Parity: `ValueIndexer.scala:101` (ValueIndexerModel)."""

    levels = Param(None, "ordered category levels (None = null level)",
                   ptype=list)

    def transform(self, df: DataFrame) -> DataFrame:
        levels = self.levels or []
        lookup = {lv: i for i, lv in enumerate(levels) if lv is not None}
        null_index = levels.index(None) if None in levels else -1
        col = df[self.input_col]
        out = np.empty(len(col), dtype=np.int64)
        for i, v in enumerate(col):
            v = _py(v)
            if _is_null(v):
                if null_index < 0:
                    raise ValueError(
                        f"null in column {self.input_col!r} but no null level")
                out[i] = null_index
            else:
                if v not in lookup:
                    raise ValueError(
                        f"unseen value {v!r} in column {self.input_col!r}")
                out[i] = lookup[v]
        meta = S.make_categorical_meta(
            levels, has_null_level=None in levels)
        return df.with_column(self.output_col, out, metadata=meta)


class IndexToValue(Transformer, HasInputCol, HasOutputCol):
    """Map an indexed column back to its original values.

    Parity: `value-indexer/IndexToValue.scala:26` — reads the categorical
    levels from column metadata.
    """

    def transform(self, df: DataFrame) -> DataFrame:
        meta = df.get_metadata(self.input_col)
        levels = S.categorical_levels(meta)
        if levels is None:
            raise ValueError(
                f"column {self.input_col!r} has no categorical metadata")
        col = df[self.input_col].astype(np.int64)
        values = [levels[i] for i in col]
        return df.with_column(self.output_col, values)


class CleanMissingData(Estimator):
    """Impute missing values per column: mean / median / custom constant.

    Parity: `clean-missing-data/CleanMissingData.scala:46`. Fit computes the
    replacement per input column over finite values; the model fills NaN/None.
    """

    input_cols = Param(None, "columns to clean", ptype=list)
    output_cols = Param(None, "output columns (default: in place)", ptype=list)
    cleaning_mode = Param("Mean", "imputation mode",
                          validator=in_set("Mean", "Median", "Custom"))
    custom_value = Param(None, "replacement for Custom mode")

    def fit(self, df: DataFrame) -> "CleanMissingDataModel":
        fills: List[float] = []
        for name in self.input_cols or []:
            col = df[name]
            if col.dtype == np.dtype("O"):
                vals = np.array([v for v in col if not _is_null(v)],
                                dtype=np.float64)
            else:
                vals = col.astype(np.float64)
                vals = vals[np.isfinite(vals)]
            if self.cleaning_mode == "Mean":
                fill = float(np.mean(vals)) if len(vals) else 0.0
            elif self.cleaning_mode == "Median":
                fill = float(np.median(vals)) if len(vals) else 0.0
            else:
                fill = float(self.custom_value)
            fills.append(fill)
        return CleanMissingDataModel(
            input_cols=list(self.input_cols or []),
            output_cols=list(self.output_cols or self.input_cols or []),
            fill_values=fills,
        )


class CleanMissingDataModel(Model):
    """Parity: `CleanMissingData.scala:127` (CleanMissingDataModel)."""

    input_cols = Param(None, "columns to clean", ptype=list)
    output_cols = Param(None, "output columns", ptype=list)
    fill_values = Param(None, "per-column replacement values", ptype=list)

    def transform(self, df: DataFrame) -> DataFrame:
        for name, out_name, fill in zip(self.input_cols, self.output_cols,
                                        self.fill_values):
            col = df[name]
            if col.dtype == np.dtype("O"):
                vals = np.array([fill if _is_null(v) else float(v)
                                 for v in col], dtype=np.float64)
            else:
                vals = col.astype(np.float64).copy()
                vals[~np.isfinite(vals)] = fill
            df = df.with_column(out_name, vals)
        return df


_CONVERSIONS = {
    "boolean": np.bool_,
    "byte": np.int8,
    "short": np.int16,
    "integer": np.int32,
    "long": np.int64,
    "float": np.float32,
    "double": np.float64,
    "string": None,   # handled specially
    "date": None,     # handled specially
    "toCategorical": None,
    "clearCategorical": None,
}


class DataConversion(Transformer):
    """Convert column types; parse/format dates; toggle categorical metadata.

    Parity: `data-conversion/DataConversion.scala:23` — ``convert_to`` is one
    of boolean/byte/short/integer/long/float/double/string/date/
    toCategorical/clearCategorical; ``date_time_format`` is a strptime/
    strftime pattern for the date conversions.
    """

    cols = Param(None, "columns to convert", ptype=list)
    convert_to = Param("double", "target type",
                       validator=in_set(*_CONVERSIONS))
    date_time_format = Param("%Y-%m-%d %H:%M:%S", "date parse/format pattern",
                             ptype=str)

    def transform(self, df: DataFrame) -> DataFrame:
        for name in self.cols or []:
            df = self._convert(df, name)
        return df

    def _convert(self, df: DataFrame, name: str) -> DataFrame:
        col = df[name]
        target = self.convert_to
        if target == "toCategorical":
            model = ValueIndexer(input_col=name, output_col=name).fit(df)
            return model.transform(df)
        if target == "clearCategorical":
            meta = df.get_metadata(name)
            levels = S.categorical_levels(meta)
            if levels is not None:
                values = [levels[int(i)] for i in col]
                return df.with_column(name, values, metadata={})
            return df.with_metadata(name, {})
        if target == "string":
            if col.dtype == np.dtype("O"):
                values = [None if _is_null(v) else str(v) for v in col]
            elif np.issubdtype(col.dtype, np.floating):
                values = [repr(float(v)) for v in col]
            else:
                values = [str(v.item() if isinstance(v, np.generic) else v)
                          for v in col]
            return df.with_column(name, values)
        if target == "date":
            fmt = self.date_time_format
            if col.dtype == np.dtype("O"):
                # string -> epoch seconds; nulls become NaN
                values = np.array(
                    [np.nan if _is_null(v) else
                     _dt.datetime.strptime(str(v), fmt)
                     .replace(tzinfo=_dt.timezone.utc).timestamp()
                     for v in col], dtype=np.float64)
                if not np.any(np.isnan(values)):
                    values = values.astype(np.int64)
                return df.with_column(name, values,
                                      metadata={"datetime": True})
            # numeric epoch seconds -> formatted string; nulls become None
            values = _obj_col([
                None if _is_null(_py(v)) else
                _dt.datetime.fromtimestamp(int(v), tz=_dt.timezone.utc)
                .strftime(fmt) for v in col])
            return df.with_column(name, values)
        np_type = _CONVERSIONS[target]
        if col.dtype == np.dtype("O"):
            def parse(v):
                if _is_null(v):
                    return np.nan if np_type in (np.float32, np.float64) else 0
                if target == "boolean" and isinstance(v, str):
                    return v.strip().lower() in ("true", "1", "yes")
                return float(v) if np_type in (np.float32, np.float64) \
                    else int(float(v))
            arr = np.array([parse(v) for v in col], dtype=np_type)
        else:
            arr = col.astype(np_type)
        return df.with_column(name, arr)
