"""Minibatching: row streams <-> batch rows, plus iterator batchers.

Capability parity with `io/http/src/main/scala/MiniBatchTransformer.scala`
(FixedMiniBatchTransformer / DynamicMiniBatchTransformer / FlattenBatch)
and the iterator batchers in `Batchers.scala:12,65,117,131`
(DynamicBufferedBatcher, FixedBatcher, TimeIntervalBatcher) used by the
HTTP/serving layer to trade latency for batch efficiency.

In the columnar world a "batch row" is a row whose cells are lists/arrays
of the original cell type.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Transformer


# ---------------------------------------------------------------------------
# Iterator batchers (host-side; serving hot path)
# ---------------------------------------------------------------------------

class FixedBatcher:
    """Group an iterator into lists of exactly ``batch_size`` (last may be short)."""

    def __init__(self, batch_size: int):
        self.batch_size = batch_size

    def __call__(self, it: Iterable[Any]) -> Iterator[List[Any]]:
        batch: List[Any] = []
        for x in it:
            batch.append(x)
            if len(batch) >= self.batch_size:
                yield batch
                batch = []
        if batch:
            yield batch


class DynamicBufferedBatcher:
    """Background-thread buffering: each batch is whatever is ready.

    Parity: DynamicBufferedBatcher (`Batchers.scala:12`) — a producer
    thread fills a bounded queue; the consumer drains everything
    currently available into one batch, so slow consumers get bigger
    batches instead of backpressure.
    """

    _DONE = object()

    def __init__(self, max_buffer_size: int = 1000):
        self.max_buffer_size = max_buffer_size

    def __call__(self, it: Iterable[Any]) -> Iterator[List[Any]]:
        q: "queue.Queue[Any]" = queue.Queue(maxsize=self.max_buffer_size)
        error: List[BaseException] = []

        def produce():
            try:
                for x in it:
                    q.put(x)
            except BaseException as e:  # propagate to consumer
                error.append(e)
            finally:
                q.put(self._DONE)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        done = False
        while not done:
            batch = [q.get()]  # block for at least one element
            while True:
                try:
                    batch.append(q.get_nowait())
                except queue.Empty:
                    break
            if batch and batch[-1] is self._DONE:
                batch.pop()
                done = True
            if batch:
                yield batch
        if error:
            raise error[0]


class BucketBatcher:
    """Group an iterator along the power-of-two bucket ladder:
    1, 2, 4, ... up to ``cap``, then ``cap`` forever (final partial batch
    as-is).

    The streaming companion of the serving data plane's shape buckets
    (:func:`mmlspark_tpu.parallel.sharding.bucket_target` — the same
    ladder): pushing a stream through it dispatches every compiled
    bucket shape exactly once on the way up, so it doubles as the
    warm-up schedule for bucketed scorers and servers
    (``tools/bench_serving_pipeline.py`` warms its workers with it).
    """

    def __init__(self, cap: int = 1024):
        from mmlspark_tpu.parallel.sharding import bucket_target
        self.cap = max(int(cap), 1)
        self._target = bucket_target

    def __call__(self, it: Iterable[Any]) -> Iterator[List[Any]]:
        size = 1
        batch: List[Any] = []
        for x in it:
            batch.append(x)
            if len(batch) >= size:
                yield batch
                batch = []
                size = min(self._target(size + 1, self.cap), self.cap)
        if batch:
            yield batch


class TimeIntervalBatcher:
    """Emit a batch at most every ``interval`` seconds (parity: Batchers.scala:131)."""

    def __init__(self, interval: float, max_batch_size: int = 10 ** 9):
        self.interval = interval
        self.max_batch_size = max_batch_size

    def __call__(self, it: Iterable[Any]) -> Iterator[List[Any]]:
        batch: List[Any] = []
        deadline = time.monotonic() + self.interval
        for x in it:
            batch.append(x)
            if len(batch) >= self.max_batch_size or time.monotonic() >= deadline:
                yield batch
                batch = []
                deadline = time.monotonic() + self.interval
        if batch:
            yield batch


# ---------------------------------------------------------------------------
# DataFrame-level batch/flatten stages
# ---------------------------------------------------------------------------

def _group_column(col: np.ndarray, bounds: Sequence[int]) -> np.ndarray:
    out = []
    for i in range(len(bounds) - 1):
        chunk = col[bounds[i]:bounds[i + 1]]
        out.append(list(chunk) if col.dtype == np.dtype("O") else np.asarray(chunk))
    return np.array(out, dtype=object)


class FixedMiniBatchTransformer(Transformer):
    """Group every ``batch_size`` rows into one batch row.

    Parity: FixedMiniBatchTransformer (`MiniBatchTransformer.scala:40`).
    """

    batch_size = Param(10, "rows per batch", ptype=int)
    max_buffer_size = Param(None, "unused; API parity", ptype=int)

    def transform(self, df: DataFrame) -> DataFrame:
        n = df.num_rows
        bounds = list(range(0, n, self.batch_size)) + [n]
        return DataFrame({name: _group_column(df[name], bounds)
                          for name in df.columns})


class DynamicMiniBatchTransformer(Transformer):
    """Single-batch grouping of whatever rows are present.

    Parity: DynamicMiniBatchTransformer (`MiniBatchTransformer.scala`) —
    in batch mode all available rows form one minibatch; streaming uses
    DynamicBufferedBatcher at the iterator level.
    """

    max_batch_size = Param(2 ** 31 - 1, "cap on rows per batch", ptype=int)

    def transform(self, df: DataFrame) -> DataFrame:
        return FixedMiniBatchTransformer(
            batch_size=min(self.max_batch_size, max(df.num_rows, 1))
        ).transform(df)


class FlattenBatch(Transformer):
    """Inverse of minibatching: explode batch rows back to scalar rows.

    Parity: FlattenBatch (`MiniBatchTransformer.scala:160`).
    """

    def transform(self, df: DataFrame) -> DataFrame:
        if df.num_rows == 0:
            return df
        cols = {name: [] for name in df.columns}
        for row in df.rows():
            lengths = {len(v) for v in row.values()}
            if len(lengths) != 1:
                raise ValueError(f"ragged batch row: lengths {lengths}")
            for name, v in row.items():
                cols[name].extend(list(v))
        return DataFrame({name: cols[name] for name in df.columns})
