"""mmlspark_tpu: a TPU-native ML pipelines framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of MMLSpark
(tbiiann/mmlspark): composable Estimator/Transformer pipelines over columnar
data, deep-network scoring and pjit data-parallel training, a from-scratch
distributed GBDT engine, image ops, AutoML featurization/training/evaluation/
tuning, a SAR recommender, LIME interpretation, and an HTTP serving layer.

The execution model is TPU-first: columnar batches become pytrees of device
arrays; the reference's per-partition native C++ calls become per-host sharded
``jit`` dispatch; its socket/MPI communication becomes XLA collectives over a
``jax.sharding.Mesh``.
"""

from mmlspark_tpu.version import __version__

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.environment import (
    accelerator_count, describe, environment_info,
)
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Transformer, Estimator, Model, Evaluator, PipelineStage
from mmlspark_tpu.core.pipeline import Pipeline, PipelineModel

__all__ = [
    "__version__",
    "DataFrame",
    "Param",
    "PipelineStage",
    "Transformer",
    "Estimator",
    "Model",
    "Evaluator",
    "Pipeline",
    "PipelineModel",
]
