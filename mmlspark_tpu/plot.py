"""Matplotlib helpers for scored frames.

Parity: `src/plot/src/main/python/plot.py` — the reference ships small
confusion-matrix / ROC plotting utilities for notebook use. These accept
either a scored :class:`DataFrame` or plain arrays and return the axes
so callers can style further.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np


def _ax(ax):
    if ax is not None:
        return ax
    import matplotlib.pyplot as plt
    return plt.gca()


def confusion_matrix(y_true, y_pred, labels: Optional[Sequence[Any]] = None,
                     ax=None):
    """Draw a labelled confusion-matrix heatmap; returns the axes."""
    y_true = np.asarray(y_true)
    y_pred = np.asarray(y_pred)
    if labels is None:
        labels = sorted(set(y_true.tolist()) | set(y_pred.tolist()))
    idx = {v: i for i, v in enumerate(labels)}
    m = np.zeros((len(labels), len(labels)), dtype=np.int64)
    for t, p in zip(y_true, y_pred):
        m[idx[t], idx[p]] += 1
    ax = _ax(ax)
    ax.imshow(m, cmap="Blues")
    ax.set_xticks(range(len(labels)), [str(v) for v in labels])
    ax.set_yticks(range(len(labels)), [str(v) for v in labels])
    ax.set_xlabel("predicted")
    ax.set_ylabel("actual")
    for i in range(len(labels)):
        for j in range(len(labels)):
            ax.text(j, i, str(m[i, j]), ha="center", va="center")
    return ax


def roc(y_true, scores, ax=None):
    """Draw the ROC curve (threshold sweep); returns the axes."""
    y_true = np.asarray(y_true).astype(bool)
    scores = np.asarray(scores, dtype=np.float64)
    order = np.argsort(-scores)
    tps = np.cumsum(y_true[order])
    fps = np.cumsum(~y_true[order])
    tpr = tps / max(tps[-1], 1)
    fpr = fps / max(fps[-1], 1)
    ax = _ax(ax)
    ax.plot(np.concatenate([[0.0], fpr]), np.concatenate([[0.0], tpr]))
    ax.plot([0, 1], [0, 1], linestyle="--", color="gray")
    ax.set_xlabel("false positive rate")
    ax.set_ylabel("true positive rate")
    return ax
