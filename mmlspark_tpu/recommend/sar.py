"""SAR (Smart Adaptive Recommendations) — TPU-native.

Capability parity with `recommendation/src/main/scala/SAR.scala:36,82,148`
and `SARModel.scala:21`:

* user-item affinity with exponential time decay
  (`calculateUserItemAffinities`),
* item-item similarity from co-occurrence counts, as cooccurrence / lift /
  Jaccard (`calculateItemItemSimilarity`),
* top-k recommendation for all users (`SARModel.recommendForAllUsers`).

TPU-first design: where the reference does broadcast sparse matrix
multiplies over Spark partitions, here both the co-occurrence count
``C = B^T B`` (B = binarized user-item matrix) and the scoring matmul
``scores = A @ S`` are dense bfloat16-friendly matmuls jitted onto the MXU.
Users are the batch axis, so multi-chip scoring shards users over the
``data`` mesh axis.
"""

from __future__ import annotations

import functools as _functools
from typing import Optional

import numpy as np


def _lazy_jit(**jit_kwargs):
    """jax.jit applied on first call, so importing this module neither
    imports jax nor touches the backend; the jitted function is cached, so
    repeated calls hit the trace cache (no per-call retrace)."""
    def deco(fn):
        compiled = []

        @_functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not compiled:
                import jax
                compiled.append(jax.jit(fn, **jit_kwargs))
            return compiled[0](*args, **kwargs)
        return wrapper
    return deco

from mmlspark_tpu.core.dataframe import DataFrame, obj_col
from mmlspark_tpu.core.params import Param, in_range, in_set
from mmlspark_tpu.core.stage import Estimator, Model

SECONDS_PER_DAY = 86400.0


def _affinity_matrix(users: np.ndarray, items: np.ndarray,
                     ratings: np.ndarray,
                     timestamps: Optional[np.ndarray],
                     n_users: int, n_items: int,
                     time_decay: bool, half_life_days: float) -> np.ndarray:
    """Dense (n_users, n_items) affinity with exponential time decay.

    Parity: SAR.scala:36-80 — affinity = sum_e rating_e * 2^(-(t_ref - t_e)/T).
    """
    weights = ratings.astype(np.float32)
    if time_decay and timestamps is not None:
        t = timestamps.astype(np.float64)
        t_ref = float(t.max())
        age_days = (t_ref - t) / SECONDS_PER_DAY
        weights = weights * np.exp2(
            -age_days / float(half_life_days)).astype(np.float32)
    aff = np.zeros((n_users, n_items), dtype=np.float32)
    np.add.at(aff, (users, items), weights)
    return aff


@_lazy_jit(static_argnames=("metric",))
def _build_similarity(aff, metric, support_threshold):
    """B = binarize(aff); C = B^T B (one MXU matmul); then the metric."""
    import jax.numpy as jnp
    b = (aff > 0).astype(jnp.float32)
    cooc = b.T @ b
    return _similarity_from_cooccurrence(cooc, metric, support_threshold)


@_lazy_jit(static_argnames=("remove_seen",))
def _score_users(aff, sim, remove_seen):
    """scores = aff @ sim, with seen items masked out when asked.

    Module-level jit: compiled once per (shape, remove_seen); aff/sim are
    arguments, not baked-in constants, so repeated scoring calls hit the
    trace cache.
    """
    import jax.numpy as jnp
    s = aff @ sim
    if remove_seen:
        s = jnp.where(aff > 0, -jnp.inf, s)
    return s


def _similarity_from_cooccurrence(cooc, metric: str,
                                  support_threshold: int):
    """Item-item similarity from a dense co-occurrence count matrix.

    Parity: SAR.scala:82-147 (jaccard / lift / plain counts, with
    ``supportThreshold`` zeroing under-supported pairs). Pure jnp — runs
    under jit.
    """
    import jax.numpy as jnp
    diag = jnp.diagonal(cooc)
    if metric == "jaccard":
        denom = diag[:, None] + diag[None, :] - cooc
        sim = jnp.where(denom > 0, cooc / denom, 0.0)
    elif metric == "lift":
        denom = diag[:, None] * diag[None, :]
        sim = jnp.where(denom > 0, cooc / denom, 0.0)
    else:  # cooccurrence
        sim = cooc
    return jnp.where(cooc >= support_threshold, sim, 0.0)


class SAR(Estimator):
    """Fit a SAR model from (user, item, rating[, timestamp]) events."""

    user_col = Param("user_idx", "indexed user column (int)")
    item_col = Param("item_idx", "indexed item column (int)")
    rating_col = Param("rating", "rating/affinity weight column")
    timestamp_col = Param(None, "optional unix-seconds timestamp column")
    time_decay_enabled = Param(True, "apply exponential time decay")
    time_decay_half_life = Param(
        30.0, "half-life of event weight, days", in_range(lo=1e-6))
    similarity_function = Param(
        "jaccard", "item-item similarity metric",
        in_set("jaccard", "lift", "cooccurrence"))
    support_threshold = Param(
        4, "min co-occurrence count for a nonzero similarity",
        in_range(lo=0))
    num_users = Param(None, "total user count (default: max index + 1)")
    num_items = Param(None, "total item count (default: max index + 1)")

    def fit(self, df: DataFrame) -> "SARModel":
        import jax.numpy as jnp

        users = np.asarray(df[self.user_col], dtype=np.int64)
        items = np.asarray(df[self.item_col], dtype=np.int64)
        if self.rating_col and self.rating_col in df:
            ratings = np.asarray(df[self.rating_col], dtype=np.float32)
        else:
            ratings = np.ones(len(users), dtype=np.float32)
        ts = None
        if self.timestamp_col and self.timestamp_col in df:
            ts = np.asarray(df[self.timestamp_col], dtype=np.float64)

        n_users = int(self.num_users or users.max() + 1)
        n_items = int(self.num_items or items.max() + 1)

        aff = _affinity_matrix(users, items, ratings, ts, n_users, n_items,
                               self.time_decay_enabled,
                               self.time_decay_half_life)

        sim = np.asarray(_build_similarity(
            jnp.asarray(aff), self.similarity_function,
            jnp.float32(self.support_threshold)))
        return SARModel(user_col=self.user_col, item_col=self.item_col,
                        rating_col=self.rating_col,
                        affinity=aff, similarity=sim)


class SARModel(Model):
    """Fitted SAR: score = affinity @ similarity; top-k per user."""

    user_col = Param("user_idx", "indexed user column (int)")
    item_col = Param("item_idx", "indexed item column (int)")
    rating_col = Param("rating", "rating column name for output")
    affinity = Param(None, "(n_users, n_items) affinity", complex=True)
    similarity = Param(None, "(n_items, n_items) similarity", complex=True)
    remove_seen = Param(True, "exclude items the user already interacted with")

    def _scores(self, user_rows: np.ndarray,
                remove_seen: bool) -> np.ndarray:
        import jax.numpy as jnp
        return np.asarray(_score_users(jnp.asarray(self.affinity[user_rows]),
                                       jnp.asarray(self.similarity),
                                       remove_seen=remove_seen))

    def recommend_for_all_users(self, k: int) -> DataFrame:
        """Parity: SARModel.recommendForAllUsers (SARModel.scala:21).

        With ``remove_seen``, users with fewer than k unseen items get
        shorter (ragged) recommendation lists rather than -inf fillers.
        """
        n_users = self.affinity.shape[0]
        scores = self._scores(np.arange(n_users), self.remove_seen)
        top = np.argsort(-scores, axis=1)[:, :k].astype(np.int32)
        ratings = np.take_along_axis(scores, top, axis=1)
        recs, rats = [], []
        for t, r in zip(top, ratings.astype(np.float32)):
            valid = np.isfinite(r)
            recs.append(t[valid])
            rats.append(r[valid])
        return DataFrame({
            self.user_col: np.arange(n_users, dtype=np.int32),
            "recommendations": obj_col(recs),
            "ratings": obj_col(rats),
        })

    def transform(self, df: DataFrame) -> DataFrame:
        """Score each (user, item) row: predicted affinity."""
        users = np.asarray(df[self.user_col], dtype=np.int64)
        items = np.asarray(df[self.item_col], dtype=np.int64)
        uniq, inverse = np.unique(users, return_inverse=True)
        scores = self._scores(uniq, remove_seen=False)
        return df.with_column("prediction",
                              scores[inverse, items].astype(np.float32))

    def _save_extra(self, path, arrays):
        arrays["affinity"] = self.affinity
        arrays["similarity"] = self.similarity

    def _load_extra(self, path, arrays):
        self.affinity = arrays["affinity"]
        self.similarity = arrays["similarity"]
