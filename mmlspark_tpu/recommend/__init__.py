"""Recommendation: SAR recommender + ranking evaluation infrastructure.

Capability parity with the reference's `src/recommendation/` module
(`SAR.scala`, `SARModel.scala`, `RecommendationIndexer.scala`,
`RankingAdapter.scala`, `RankingEvaluator.scala`,
`RankingTrainValidationSplit.scala`) rebuilt TPU-first: affinity and
item-item similarity are dense matmuls on the MXU instead of broadcast
sparse multiplies over Spark partitions.
"""

from mmlspark_tpu.recommend.indexer import (
    RecommendationIndexer, RecommendationIndexerModel,
)
from mmlspark_tpu.recommend.sar import SAR, SARModel
from mmlspark_tpu.recommend.ranking import (
    AdvancedRankingMetrics, RankingAdapter, RankingAdapterModel,
    RankingEvaluator, RankingTrainValidationSplit,
    RankingTrainValidationSplitModel, per_user_split,
)

__all__ = [
    "RecommendationIndexer", "RecommendationIndexerModel",
    "SAR", "SARModel",
    "AdvancedRankingMetrics", "RankingAdapter", "RankingAdapterModel",
    "RankingEvaluator", "RankingTrainValidationSplit",
    "RankingTrainValidationSplitModel", "per_user_split",
]
