"""User/item string <-> contiguous-index codec for recommenders.

Parity: `recommendation/src/main/scala/RecommendationIndexer.scala:16`
(a two-column StringIndexer whose model can also invert predictions back
to original ids). Contiguous int32 indices are what lets the SAR math be
dense device matrices.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, obj_col, py_scalar
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Estimator, Model


class RecommendationIndexer(Estimator):
    """Fit categorical maps for the user and item columns."""

    user_input_col = Param("user", "raw user id column")
    item_input_col = Param("item", "raw item id column")
    user_output_col = Param("user_idx", "indexed user column")
    item_output_col = Param("item_idx", "indexed item column")
    rating_col = Param(None, "optional rating column passed through")

    def fit(self, df: DataFrame) -> "RecommendationIndexerModel":
        users = sorted({py_scalar(v) for v in df[self.user_input_col]},
                       key=str)
        items = sorted({py_scalar(v) for v in df[self.item_input_col]},
                       key=str)
        return RecommendationIndexerModel(
            user_input_col=self.user_input_col,
            item_input_col=self.item_input_col,
            user_output_col=self.user_output_col,
            item_output_col=self.item_output_col,
            user_levels=users, item_levels=items)


class RecommendationIndexerModel(Model):
    user_input_col = Param("user", "raw user id column")
    item_input_col = Param("item", "raw item id column")
    user_output_col = Param("user_idx", "indexed user column")
    item_output_col = Param("item_idx", "indexed item column")
    user_levels = Param(None, "ordered distinct user ids", complex=True)
    item_levels = Param(None, "ordered distinct item ids", complex=True)

    def _lookup(self, levels: List, values) -> np.ndarray:
        table: Dict = {v: i for i, v in enumerate(levels)}
        out = np.empty(len(values), dtype=np.int32)
        for i, v in enumerate(values):
            v = py_scalar(v)
            if v not in table:
                raise KeyError(f"unseen id {v!r}")
            out[i] = table[v]
        return out

    def transform(self, df: DataFrame) -> DataFrame:
        out = df.with_column(
            self.user_output_col,
            self._lookup(self.user_levels, df[self.user_input_col]))
        out = out.with_column(
            self.item_output_col,
            self._lookup(self.item_levels, df[self.item_input_col]))
        return out

    def inverse_transform_items(self, df: DataFrame,
                                col: str) -> DataFrame:
        """Map an indexed item column (scalar or list per row) back to ids."""
        items = self.item_levels
        vals = []
        for v in df[col]:
            if np.ndim(v) > 0:
                vals.append([items[int(i)] for i in np.asarray(v).ravel()])
            else:
                vals.append(items[int(v)])
        return df.with_column(col, obj_col(vals))

    @property
    def num_users(self) -> int:
        return len(self.user_levels)

    @property
    def num_items(self) -> int:
        return len(self.item_levels)

    def _save_extra(self, path, arrays):
        arrays["user_levels"] = obj_col(self.user_levels)
        arrays["item_levels"] = obj_col(self.item_levels)

    def _load_extra(self, path, arrays):
        self.user_levels = [py_scalar(v) for v in arrays["user_levels"]]
        self.item_levels = [py_scalar(v) for v in arrays["item_levels"]]
