"""Ranking evaluation + train/validation-split infrastructure.

Capability parity with `recommendation/src/main/scala/RankingEvaluator.scala:97,14`
(`AdvancedRankingMetrics`: ndcg@k, map, precision@k, recall@k, mrr, fcp),
`RankingAdapter.scala:66,104` (adapt a recommender so its output frame holds
per-user predicted and ground-truth item lists) and
`RankingTrainValidationSplit.scala:22,295` (per-user chronological/random
split + grid evaluation).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, obj_col
from mmlspark_tpu.core.params import Param, in_range, in_set
from mmlspark_tpu.core.stage import Estimator, Evaluator, Model


class AdvancedRankingMetrics:
    """Metrics over parallel lists of (predicted items, relevant items).

    Parity: RankingEvaluator.scala:14-95. Pure numpy — list lengths are
    ragged and tiny; nothing here is worth a device round-trip.
    """

    def __init__(self, predictions: Sequence[Sequence],
                 ground_truth: Sequence[Sequence], k: int):
        self.pred = [list(p) for p in predictions]
        self.truth = [set(t) for t in ground_truth]
        self.k = k

    def precision_at_k(self) -> float:
        vals = [len(set(p[:self.k]) & t) / self.k
                for p, t in zip(self.pred, self.truth)]
        return float(np.mean(vals)) if vals else 0.0

    def recall_at_k(self) -> float:
        vals = [len(set(p[:self.k]) & t) / max(len(t), 1)
                for p, t in zip(self.pred, self.truth)]
        return float(np.mean(vals)) if vals else 0.0

    def ndcg_at_k(self) -> float:
        vals = []
        for p, t in zip(self.pred, self.truth):
            dcg = sum(1.0 / np.log2(i + 2)
                      for i, item in enumerate(p[:self.k]) if item in t)
            ideal = sum(1.0 / np.log2(i + 2)
                        for i in range(min(len(t), self.k)))
            vals.append(dcg / ideal if ideal > 0 else 0.0)
        return float(np.mean(vals)) if vals else 0.0

    def map_metric(self) -> float:
        vals = []
        for p, t in zip(self.pred, self.truth):
            hits, acc = 0, 0.0
            for i, item in enumerate(p):
                if item in t:
                    hits += 1
                    acc += hits / (i + 1.0)
            vals.append(acc / max(len(t), 1))
        return float(np.mean(vals)) if vals else 0.0

    def map_at_k(self) -> float:
        vals = []
        for p, t in zip(self.pred, self.truth):
            hits, acc = 0, 0.0
            for i, item in enumerate(p[:self.k]):
                if item in t:
                    hits += 1
                    acc += hits / (i + 1.0)
            vals.append(acc / max(min(len(t), self.k), 1))
        return float(np.mean(vals)) if vals else 0.0

    def mrr(self) -> float:
        vals = []
        for p, t in zip(self.pred, self.truth):
            rank = next((i + 1 for i, item in enumerate(p) if item in t), None)
            vals.append(1.0 / rank if rank else 0.0)
        return float(np.mean(vals)) if vals else 0.0

    def recommended_fraction(self) -> float:
        """Fraction of users with at least one relevant recommendation."""
        vals = [1.0 if set(p[:self.k]) & t else 0.0
                for p, t in zip(self.pred, self.truth)]
        return float(np.mean(vals)) if vals else 0.0

    def fcp(self) -> float:
        """Fraction of concordant pairs: among (relevant, irrelevant) item
        pairs in a user's predicted list, how often the relevant one is
        ranked first, averaged over users with at least one such pair."""
        vals = []
        for p, t in zip(self.pred, self.truth):
            rel = [i for i, item in enumerate(p) if item in t]
            irr = [i for i, item in enumerate(p) if item not in t]
            if not rel or not irr:
                continue
            concordant = sum(1 for r in rel for s in irr if r < s)
            vals.append(concordant / (len(rel) * len(irr)))
        return float(np.mean(vals)) if vals else 0.0

    def diversity_at_k(self) -> float:
        """Distinct items recommended in top-k across users / distinct
        items relevant anywhere (coverage of the catalog actually used)."""
        recommended = {item for p in self.pred for item in p[:self.k]}
        universe = {item for t in self.truth for item in t} | recommended
        return len(recommended) / max(len(universe), 1)

    def get(self, name: str) -> float:
        table = {
            "precisionAtk": self.precision_at_k,
            "recallAtK": self.recall_at_k,
            "ndcgAt": self.ndcg_at_k,
            "map": self.map_metric,
            "mapk": self.map_at_k,
            "mrr": self.mrr,
            "fcp": self.fcp,
            "recommendedAtK": self.recommended_fraction,
            "diversityAtK": self.diversity_at_k,
        }
        return table[name]()

    def all_metrics(self) -> Dict[str, float]:
        return {n: self.get(n)
                for n in ("map", "ndcgAt", "precisionAtk", "recallAtK",
                          "mrr", "mapk", "fcp", "recommendedAtK",
                          "diversityAtK")}


class RankingEvaluator(Evaluator):
    """Evaluate a frame of per-user prediction/label item lists.

    Parity: RankingEvaluator.scala:97 (metricName param, k param).
    """

    k = Param(10, "cutoff for @k metrics", in_range(lo=1))
    metric_name = Param("ndcgAt", "which metric",
                        in_set("ndcgAt", "map", "mapk", "precisionAtk",
                               "recallAtK", "mrr", "fcp", "recommendedAtK",
                               "diversityAtK"))
    prediction_col = Param("recommendations", "predicted item-list column")
    label_col = Param("labels", "ground-truth item-list column")

    def _metrics(self, df: DataFrame) -> AdvancedRankingMetrics:
        return AdvancedRankingMetrics(
            [list(np.ravel(p)) for p in df[self.prediction_col]],
            [list(np.ravel(t)) for t in df[self.label_col]], self.k)

    def evaluate(self, df: DataFrame) -> float:
        return self._metrics(df).get(self.metric_name)

    def evaluate_all(self, df: DataFrame) -> DataFrame:
        m = self._metrics(df).all_metrics()
        return DataFrame({k: [v] for k, v in m.items()})


class RankingAdapter(Estimator):
    """Wrap a recommender Estimator so evaluation frames come out directly.

    Parity: RankingAdapter.scala:66 — fit the inner recommender, then
    ``transform(test)`` emits one row per user with top-k predictions and
    that user's ground-truth items.
    """

    recommender = Param(None, "inner recommender estimator", complex=True)
    k = Param(10, "how many items to recommend", in_range(lo=1))
    user_col = Param("user_idx", "indexed user column")
    item_col = Param("item_idx", "indexed item column")
    rating_col = Param("rating", "rating column")

    def fit(self, df: DataFrame) -> "RankingAdapterModel":
        model = self.recommender.fit(df)
        return RankingAdapterModel(
            recommender_model=model, k=self.k, user_col=self.user_col,
            item_col=self.item_col, rating_col=self.rating_col)


class RankingAdapterModel(Model):
    recommender_model = Param(None, "fitted recommender", complex=True)
    k = Param(10, "how many items to recommend")
    user_col = Param("user_idx", "indexed user column")
    item_col = Param("item_idx", "indexed item column")
    rating_col = Param("rating", "rating column")

    def transform(self, df: DataFrame) -> DataFrame:
        recs = self.recommender_model.recommend_for_all_users(self.k)
        rec_map = {int(u): list(np.ravel(r)) for u, r in
                   zip(recs[self.user_col], recs["recommendations"])}
        users = np.asarray(df[self.user_col], dtype=np.int64)
        items = np.asarray(df[self.item_col], dtype=np.int64)
        truth: Dict[int, List[int]] = {}
        for u, i in zip(users, items):
            truth.setdefault(int(u), []).append(int(i))
        uids = sorted(truth)
        return DataFrame({
            self.user_col: np.asarray(uids, dtype=np.int32),
            "recommendations": obj_col(
                [rec_map.get(u, []) for u in uids]),
            "labels": obj_col([truth[u] for u in uids]),
        })

    def _save_extra(self, path, arrays):
        import os
        self.recommender_model.save(os.path.join(path, "inner"))

    def _load_extra(self, path, arrays):
        import os
        from mmlspark_tpu.core.stage import PipelineStage
        self.recommender_model = PipelineStage.load(
            os.path.join(path, "inner"))


def per_user_split(df: DataFrame, user_col: str, train_ratio: float,
                   seed: int = 0, min_ratings: int = 1):
    """Split events per user so every user appears in both halves.

    Parity: RankingTrainValidationSplit.scala's stratified split (:295).
    """
    rng = np.random.default_rng(seed)
    users = np.asarray(df[user_col], dtype=np.int64)
    train_mask = np.zeros(len(users), dtype=bool)
    for u in np.unique(users):
        idx = np.flatnonzero(users == u)
        rng.shuffle(idx)
        n_train = max(int(round(len(idx) * train_ratio)), min_ratings)
        n_train = min(n_train, max(len(idx) - 1, 1))
        train_mask[idx[:n_train]] = True
    return df.filter(train_mask), df.filter(~train_mask)


class RankingTrainValidationSplit(Estimator):
    """Grid-search a recommender by ranking metric on a per-user split.

    Parity: RankingTrainValidationSplit.scala:22 (estimator + paramMaps +
    evaluator + trainRatio).
    """

    estimator = Param(None, "recommender estimator", complex=True)
    evaluator = Param(None, "RankingEvaluator", complex=True)
    param_maps = Param(None, "list of {param: value} dicts to try",
                       complex=True)
    train_ratio = Param(0.75, "per-user train fraction",
                        in_range(lo=0.0, hi=1.0))
    user_col = Param("user_idx", "indexed user column")
    item_col = Param("item_idx", "indexed item column")
    seed = Param(0, "split seed")

    def fit(self, df: DataFrame) -> "RankingTrainValidationSplitModel":
        evaluator = self.evaluator or RankingEvaluator()
        train, valid = per_user_split(df, self.user_col, self.train_ratio,
                                      seed=self.seed)
        param_maps = self.param_maps or [{}]
        results = []
        for pm in param_maps:
            est = self.estimator.copy().set(**pm)
            adapter = RankingAdapter(
                recommender=est, k=evaluator.k, user_col=self.user_col,
                item_col=self.item_col)
            model = adapter.fit(train)
            metric = evaluator.evaluate(model.transform(valid))
            results.append((metric, pm, model))
        best = max(results, key=lambda r: r[0])
        return RankingTrainValidationSplitModel(
            best_model=best[2], best_params=best[1],
            validation_metrics=[r[0] for r in results])


class RankingTrainValidationSplitModel(Model):
    best_model = Param(None, "best fitted RankingAdapterModel", complex=True)
    best_params = Param(None, "winning param map", complex=True)
    validation_metrics = Param(None, "metric per param map", complex=True)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.best_model.transform(df)

    def recommend_for_all_users(self, k: int) -> DataFrame:
        return self.best_model.recommender_model.recommend_for_all_users(k)

    def _save_extra(self, path, arrays):
        import json
        import os
        self.best_model.save(os.path.join(path, "inner"))
        arrays["validation_metrics"] = np.asarray(
            self.validation_metrics or [], dtype=np.float64)
        from mmlspark_tpu.core.serialize import _json_default
        with open(os.path.join(path, "best_params.json"), "w") as f:
            json.dump(self.best_params or {}, f, default=_json_default)

    def _load_extra(self, path, arrays):
        import json
        import os
        from mmlspark_tpu.core.stage import PipelineStage
        self.best_model = PipelineStage.load(os.path.join(path, "inner"))
        self.validation_metrics = list(arrays["validation_metrics"])
        params_file = os.path.join(path, "best_params.json")
        if os.path.exists(params_file):  # absent in pre-fix checkpoints
            with open(params_file) as f:
                self.best_params = json.load(f)
