"""Pallas fused softmax-cross-entropy over a linear vocabulary head.

The transformer LM's loss section — ``logits = h @ W``; ``ce =
lse(logits) - logits[label]`` — is memory-bound under XLA at production
vocab sizes: the (T, V) f32 logits (1 GB at T=8k, V=32k) round-trip HBM
for the logsumexp, the gold gather, and again for ``d_logits`` and both
backward matmuls (~6 GB of traffic per step, measured as ~34% of the
b8/s1024 train step). This module fuses the whole section into three
Pallas kernels that keep every (T_tile, V_tile) logit block in VMEM:

- **forward** — streams vocab tiles ``h_i @ W_j`` on the MXU with the
  running-max / running-sum-exp carry (the same online-softmax contract
  as ``parallel/pallas_attention.py``), extracts the gold logit with an
  in-tile iota==label mask, and stores the logits ONCE in the compute
  dtype (bf16 halves the only large HBM write).
- **dh backward** (vocab-innermost grid) — rebuilds ``p = exp(l - lse)``
  from the stored tile, forms ``d_l = (p - onehot) * g`` in VMEM, and
  accumulates ``dh += d_l @ W_j^T`` in scratch. ``d_l`` never reaches
  HBM.
- **dW backward** (token-innermost grid) — same ``d_l`` rebuild,
  accumulates ``dW_j += h_i^T @ d_l`` in scratch.

Total: the 3 matmuls the math requires (no recompute of the logits
product in either backward) and ~1.5 GB of bf16 tile traffic instead of
~6 GB of f32 round-trips.

The op is a ``jax.custom_vjp`` returning the per-token CE vector, so
masking / pipeline gating / psum stay in the caller exactly as in the
XLA path, and the upstream cotangent ``g`` (= mask/count after autodiff)
becomes the per-token scale on ``d_l``. Composes inside VMA-checked
``shard_map``: outputs carry the union of the operands'
varying-manual-axes, and the dW cotangent is psum'd over the
token-holding axes in the vjp (returning an invariant grad for the
replicated head weight).

Reference parity: replaces the CE tail of the CNTK training loop
(`src/cntk-train/src/main/scala/CNTKLearner.scala:85` — there the loss
node is CNTK's fused cross_entropy_with_softmax on GPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

T_TILE = 512    # token-tile edge (sublanes of the logit block)
V_TILE = 2048   # vocab-tile edge (lanes of the logit block)
_NEG_INF = -1e30


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _vma(*xs):
    out = frozenset()
    for x in xs:
        out = out | (getattr(jax.typeof(x), "vma", frozenset())
                     or frozenset())
    return out


# VMEM the largest kernel may request before Mosaic compiles stop
# fitting. Calibrated on v5e with the default tiles: 12 MB configs
# compile, 18 MB configs fail — 14 MB keeps the measured-good shapes
# and rejects the measured-bad ones with margin.
_VMEM_BUDGET = 14 * 2**20


def _kernel_vmem_bytes(d: int, tt: int, tv: int, itemsize: int = 2) -> int:
    """Worst-kernel VMEM estimate: double-buffered operand/output blocks
    plus the persistent f32 accumulator scratch."""
    fwd = 2 * (tt * d + d * tv + tt * tv) * itemsize
    dh = 2 * (tt * tv + d * tv + tt * d) * itemsize + tt * d * 4
    dw = 2 * (tt * tv + tt * d + d * tv) * itemsize + d * tv * 4
    return max(fwd, dh, dw)


def fused_ce_available(t: int, d: int, v: int,
                       itemsize: int = 2) -> bool:
    """Shape+backend eligibility for the default tiles: the model dim
    rides the lane axis of the ``h`` tile (lane-aligned), the kernels
    block-load the FULL d dimension (so wide models must fit the VMEM
    budget — fall back to XLA rather than fail the Mosaic compile), and
    small token counts are excluded (tile padding to T_TILE would cost
    more than the XLA einsum it replaces). V is padded/masked
    internally, any size works. ``itemsize`` is the compute dtype's
    byte width (2 for bf16, 4 for f32) — the VMEM budget is a dtype
    question, not just a shape one."""
    return (d % 128 == 0 and t >= T_TILE
            and _kernel_vmem_bytes(d, T_TILE, V_TILE,
                                   itemsize) <= _VMEM_BUDGET
            and jax.default_backend() == "tpu")


def _col_ids(j, tq: int, tv: int):
    """Global vocab column ids of tile j, shaped (tq, tv)."""
    return j * tv + jax.lax.broadcasted_iota(jnp.int32, (tq, tv), 1)


def _ce_fwd_kernel(lbl_ref, h_ref, w_ref, logits_ref, lse_ref, gold_ref,
                   m_scr, s_scr, g_scr, *, v_total: int, tv: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        s_scr[:] = jnp.zeros_like(s_scr)
        g_scr[:] = jnp.zeros_like(g_scr)

    logits = jax.lax.dot_general(                       # (TQ, TV) f32
        h_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    cols = _col_ids(j, logits.shape[0], tv)
    if v_total % tv:
        # W is zero-padded to the tile grid; padded columns must not
        # contribute to the normalizer (a 0 logit would)
        logits = jnp.where(cols < v_total, logits, _NEG_INF)
    logits_ref[:] = logits.astype(logits_ref.dtype)

    m_prev = m_scr[:]                                   # (TQ, 1)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    s_scr[:] = s_scr[:] * alpha + jnp.sum(
        jnp.exp(logits - m_new), axis=1, keepdims=True)
    m_scr[:] = m_new
    # gold logit: each label lives in exactly one tile; masked (pad)
    # columns can never match a label < v_total
    hit = cols == lbl_ref[:]                            # (TQ, TV)
    g_scr[:] += jnp.sum(jnp.where(hit, logits, 0.0), axis=1,
                        keepdims=True)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        lse_ref[:] = m_scr[:] + jnp.log(s_scr[:])
        gold_ref[:] = g_scr[:]


def _d_logits(lbl_ref, g_ref, logits_ref, lse_ref, j, tv: int):
    """Rebuild ``d_l = (softmax - onehot(label)) * g`` for one stored
    tile, entirely in VMEM. Stored -inf (vocab-pad) columns exp to 0."""
    logits = logits_ref[:].astype(jnp.float32)
    p = jnp.exp(logits - lse_ref[:])                    # (TQ, TV)
    hit = _col_ids(j, logits.shape[0], tv) == lbl_ref[:]
    return (p - hit.astype(jnp.float32)) * g_ref[:]


def _ce_dh_kernel(lbl_ref, g_ref, logits_ref, w_ref, lse_ref,
                  dh_ref, dh_scr, *, tv: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        dh_scr[:] = jnp.zeros_like(dh_scr)

    dl = _d_logits(lbl_ref, g_ref, logits_ref, lse_ref, j, tv)
    dh_scr[:] += jax.lax.dot_general(                   # (TQ, D)
        dl.astype(w_ref.dtype), w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        dh_ref[:] = dh_scr[:].astype(dh_ref.dtype)


def _ce_dw_kernel(lbl_ref, g_ref, logits_ref, h_ref, lse_ref,
                  dw_ref, dw_scr, *, tv: int):
    # grid is (j, i): token tiles innermost so dW_j accumulates in VMEM
    j, i = pl.program_id(0), pl.program_id(1)

    @pl.when(i == 0)
    def _():
        dw_scr[:] = jnp.zeros_like(dw_scr)

    dl = _d_logits(lbl_ref, g_ref, logits_ref, lse_ref, j, tv)
    dw_scr[:] += jax.lax.dot_general(                   # (D, TV)
        h_ref[:], dl.astype(h_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(i == pl.num_programs(1) - 1)
    def _():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)


@functools.partial(jax.jit, static_argnames=("v_total", "interpret",
                                             "tt", "tv"))
def _fwd_call(h, w, lbl, v_total: int, interpret: bool,
              tt: int = T_TILE, tv: int = V_TILE):
    """h (T_p, D); w (D, V_p); lbl (T_p, 1) int32 — all tile-padded."""
    t_p, d = h.shape
    v_p = w.shape[1]
    grid = (t_p // tt, v_p // tv)
    vma = _vma(h, lbl)
    return pl.pallas_call(
        functools.partial(_ce_fwd_kernel, v_total=v_total, tv=tv),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tt, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tt, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, tv), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((tt, tv), lambda i, j: (i, j)),
            pl.BlockSpec((tt, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tt, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            # logits stored once, in the compute dtype (the only large
            # write this op makes)
            jax.ShapeDtypeStruct((t_p, v_p), h.dtype, vma=vma),
            jax.ShapeDtypeStruct((t_p, 1), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((t_p, 1), jnp.float32, vma=vma),
        ],
        scratch_shapes=[pltpu.VMEM((tt, 1), jnp.float32)] * 3,
        interpret=interpret,
    )(lbl, h, w)


@functools.partial(jax.jit, static_argnames=("interpret", "tt", "tv"))
def _bwd_call(h, w, lbl, g, logits, lse, interpret: bool,
              tt: int = T_TILE, tv: int = V_TILE):
    t_p, d = h.shape
    v_p = w.shape[1]
    ni, nj = t_p // tt, v_p // tv
    vma = _vma(h, lbl, g)
    dh = pl.pallas_call(
        functools.partial(_ce_dh_kernel, tv=tv),
        grid=(ni, nj),
        in_specs=[
            pl.BlockSpec((tt, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tt, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tt, tv), lambda i, j: (i, j)),
            pl.BlockSpec((d, tv), lambda i, j: (0, j)),
            pl.BlockSpec((tt, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tt, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((t_p, d), h.dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((tt, d), jnp.float32)],
        interpret=interpret,
    )(lbl, g, logits, w, lse)

    dw = pl.pallas_call(
        functools.partial(_ce_dw_kernel, tv=tv),
        grid=(nj, ni),
        in_specs=[
            pl.BlockSpec((tt, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((tt, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((tt, tv), lambda j, i: (i, j)),
            pl.BlockSpec((tt, d), lambda j, i: (i, 0)),
            pl.BlockSpec((tt, 1), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((d, tv), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((d, v_p), w.dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((d, tv), jnp.float32)],
        interpret=interpret,
    )(lbl, g, logits, h, lse)
    return dh, dw


# --- inner op on tile-padded operands (pad/slice live OUTSIDE the
# custom_vjp: jnp.pad's transpose un-pads the cotangents for free) ----


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused_padded(h_p, w_p, lbl, v_total: int, interpret: bool,
                  tt: int = T_TILE, tv: int = V_TILE):
    ce, _ = _fused_padded_fwd(h_p, w_p, lbl, v_total, interpret,
                              tt, tv)
    return ce


def _fused_padded_fwd(h_p, w_p, lbl, v_total, interpret,
                      tt=T_TILE, tv=V_TILE):
    logits, lse, gold = _fwd_call(h_p, w_p, lbl, v_total, interpret,
                                  tt, tv)
    return (lse - gold)[:, 0], (h_p, w_p, lbl, logits, lse)


def _fused_padded_bwd(v_total, interpret, tt, tv, res, g):
    h_p, w_p, lbl, logits, lse = res
    # token-pad rows and vocab-pad columns self-silence: their g is the
    # pad of the caller's cotangent (zero), and pad-column p is
    # exp(-inf - lse) = 0. The wrapper pvary'd every operand to a common
    # axis set, so dW comes back VARYING over the token-holding axes and
    # pvary's transpose (a psum at the wrapper boundary) delivers the
    # invariant total to the replicated head weight.
    g2 = g[:, None]
    miss = tuple(sorted(_vma(h_p) - _vma(g2)))
    if miss:
        g2 = jax.lax.pcast(g2, miss, to="varying")
    dh, dw = _bwd_call(h_p, w_p, lbl, g2, logits, lse,
                       interpret, tt, tv)
    lbl_zero = np.zeros(lbl.shape, dtype=jax.dtypes.float0)
    return dh, dw, lbl_zero


_fused_padded.defvjp(_fused_padded_fwd, _fused_padded_bwd)


def fused_softmax_xent(h, w, labels, compute_dtype=None,
                       interpret: bool = False,
                       t_tile: int = None, v_tile: int = None):
    """Per-token cross-entropy ``lse(h @ w) - (h @ w)[labels]``.

    h (T, D) float; w (D, V) float; labels (T,) integer. Returns (T,)
    f32. ``compute_dtype`` (default: h's dtype) is the matmul-input /
    stored-logits dtype — pass bf16 for the MXU fast path; accumulation
    and the CE are always f32, and the h/w cotangents flow back through
    the dtype cast exactly as in the XLA einsum path. Differentiable in
    h and w. ``interpret=True`` runs the kernels interpreted (CPU
    tests)."""
    t, d = h.shape
    v = w.shape[1]
    dt = compute_dtype or h.dtype
    tt, tv = t_tile or T_TILE, v_tile or V_TILE
    t_p, v_p = _round_up(t, tt), _round_up(v, tv)
    h_p = jnp.pad(h.astype(dt), ((0, t_p - t), (0, 0)))
    w_p = jnp.pad(w.astype(dt), ((0, 0), (0, v_p - v)))
    lbl = jnp.pad(labels.astype(jnp.int32), (0, t_p - t))[:, None]
    # under VMA-checked shard_map the kernel operands must agree on
    # their varying axes: pcast each to the union (for the replicated
    # head weight, the varying-cast's transpose psums dW back to
    # invariant). NOTE: interpret mode requires check_vma=False in the
    # enclosing shard_map — the HLO interpreter re-evaluates the kernel
    # body with vma-typed values, where kernel-created iota/scratch
    # constants cannot be vma-matched (the compiled TPU path has no
    # such re-evaluation and runs fine under check_vma=True).
    union = _vma(h_p, w_p, lbl)
    h_p, w_p, lbl = (
        jax.lax.pcast(x, tuple(sorted(union - _vma(x))), to="varying")
        if union - _vma(x) else x
        for x in (h_p, w_p, lbl))
    ce_p = _fused_padded(h_p, w_p, lbl, v, interpret, tt, tv)
    return ce_p[:t]
