"""Batched image ops on device: the OpenCV-replacement compute path.

Capability parity with the reference's OpenCV stages
(`image-transformer/src/main/scala/ImageTransformer.scala:22-207`: resize,
crop, colorFormat, blur, threshold, gaussianKernel, flip) — but TPU-first:
every op maps over an NHWC batch of same-shaped images as a jitted XLA
program (VPU elementwise + MXU convs), instead of per-row JNI `Mat` calls.
Variable-shape inputs are handled one level up by shape-bucketing
(`ImageTransformer` groups rows by shape before dispatch).

Convention: float32 NHWC in [0, 255] inside pipelines; uint8 at the I/O
boundary. Channel order is RGB throughout the framework (the reference
inherits OpenCV's BGR; converters are provided for parity with models
trained on BGR input).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# OpenCV-compatible constants (parity: ImageTransformer.scala threshold/flip)
THRESH_BINARY = 0
THRESH_BINARY_INV = 1
THRESH_TRUNC = 2
THRESH_TOZERO = 3
THRESH_TOZERO_INV = 4

FLIP_VERTICAL = 0    # flip around x-axis
FLIP_HORIZONTAL = 1  # flip around y-axis
FLIP_BOTH = -1


def _as_batch(images: jnp.ndarray) -> Tuple[jnp.ndarray, bool]:
    """Accept HWC or NHWC; return NHWC plus whether input was single."""
    if images.ndim == 3:
        return images[None], True
    if images.ndim != 4:
        raise ValueError(f"expected HWC or NHWC, got shape {images.shape}")
    return images, False


def _unbatch(out: jnp.ndarray, single: bool) -> jnp.ndarray:
    return out[0] if single else out


def resize(images: jnp.ndarray, height: int, width: int,
           method: str = "linear", antialias: bool = True) -> jnp.ndarray:
    """Resize NHWC batch to (height, width). Parity: Imgproc.resize."""
    x, single = _as_batch(images)
    n, _, _, c = x.shape
    out = jax.image.resize(x.astype(jnp.float32), (n, height, width, c),
                           method=method, antialias=antialias)
    return _unbatch(out, single)


def center_crop(images: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    x, single = _as_batch(images)
    h, w = x.shape[1], x.shape[2]
    top = max((h - height) // 2, 0)
    left = max((w - width) // 2, 0)
    out = x[:, top:top + height, left:left + width, :]
    return _unbatch(out, single)


def crop(images: jnp.ndarray, x0: int, y0: int,
         height: int, width: int) -> jnp.ndarray:
    """Crop at (x0, y0). Parity: CropImage stage (x,y,height,width params)."""
    x, single = _as_batch(images)
    out = x[:, y0:y0 + height, x0:x0 + width, :]
    return _unbatch(out, single)


def flip(images: jnp.ndarray, flip_code: int = FLIP_HORIZONTAL) -> jnp.ndarray:
    """Parity: Core.flip with OpenCV flip codes."""
    x, single = _as_batch(images)
    if flip_code == FLIP_VERTICAL:
        out = x[:, ::-1, :, :]
    elif flip_code == FLIP_HORIZONTAL:
        out = x[:, :, ::-1, :]
    elif flip_code == FLIP_BOTH:
        out = x[:, ::-1, ::-1, :]
    else:
        raise ValueError(f"bad flip code {flip_code}")
    return _unbatch(out, single)


def _depthwise_conv(x: jnp.ndarray, kernel2d: jnp.ndarray) -> jnp.ndarray:
    """Depthwise 2D convolution of NHWC by one 2D kernel, replicate borders.

    Border handling matches OpenCV's default (non-zero border extension),
    so constant regions stay constant at the edges.
    """
    kh, kw = kernel2d.shape
    top, bottom = (kh - 1) // 2, kh // 2
    left, right = (kw - 1) // 2, kw // 2
    x = x.astype(jnp.float32)
    x = jnp.pad(x, ((0, 0), (top, bottom), (left, right), (0, 0)), mode="edge")
    c = x.shape[-1]
    k = kernel2d.astype(jnp.float32)[:, :, None, None]
    k = jnp.tile(k, (1, 1, 1, c))  # HWIO with feature_group_count=C
    return jax.lax.conv_general_dilated(
        x, k, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c)


def box_blur(images: jnp.ndarray, kh: int, kw: int) -> jnp.ndarray:
    """Normalized box filter. Parity: Imgproc.blur."""
    x, single = _as_batch(images)
    kernel = jnp.full((kh, kw), 1.0 / (kh * kw))
    return _unbatch(_depthwise_conv(x, kernel), single)


def gaussian_kernel(radius: int, sigma: float) -> jnp.ndarray:
    """2D Gaussian kernel. Parity: GaussianKernel stage (radius, sigma)."""
    ax = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    g = jnp.exp(-(ax ** 2) / (2.0 * sigma ** 2))
    k = jnp.outer(g, g)
    return k / jnp.sum(k)


def gaussian_blur(images: jnp.ndarray, radius: int, sigma: float) -> jnp.ndarray:
    x, single = _as_batch(images)
    return _unbatch(_depthwise_conv(x, gaussian_kernel(radius, sigma)), single)


def threshold(images: jnp.ndarray, thresh: float, max_val: float = 255.0,
              threshold_type: int = THRESH_BINARY) -> jnp.ndarray:
    """Parity: Imgproc.threshold with the five OpenCV modes."""
    x, single = _as_batch(images)
    x = x.astype(jnp.float32)
    above = x > thresh
    if threshold_type == THRESH_BINARY:
        out = jnp.where(above, max_val, 0.0)
    elif threshold_type == THRESH_BINARY_INV:
        out = jnp.where(above, 0.0, max_val)
    elif threshold_type == THRESH_TRUNC:
        out = jnp.where(above, thresh, x)
    elif threshold_type == THRESH_TOZERO:
        out = jnp.where(above, x, 0.0)
    elif threshold_type == THRESH_TOZERO_INV:
        out = jnp.where(above, 0.0, x)
    else:
        raise ValueError(f"bad threshold type {threshold_type}")
    return _unbatch(out, single)


def to_grayscale(images: jnp.ndarray) -> jnp.ndarray:
    """RGB -> single-channel luma. Parity: Imgproc.cvtColor COLOR_*2GRAY."""
    x, single = _as_batch(images)
    weights = jnp.array([0.299, 0.587, 0.114], dtype=jnp.float32)
    out = jnp.tensordot(x.astype(jnp.float32), weights, axes=[[3], [0]])[..., None]
    return _unbatch(out, single)


def swap_rb(images: jnp.ndarray) -> jnp.ndarray:
    """RGB<->BGR. Parity: cvtColor COLOR_BGR2RGB / RGB2BGR."""
    x, single = _as_batch(images)
    return _unbatch(x[..., ::-1], single)


def color_format(images: jnp.ndarray, fmt: str) -> jnp.ndarray:
    fmt = fmt.lower()
    if fmt in ("gray", "grey", "grayscale"):
        return to_grayscale(images)
    if fmt in ("bgr", "rgb_to_bgr", "bgr_to_rgb", "swap_rb"):
        return swap_rb(images)
    if fmt in ("rgb", "identity"):
        return images
    raise ValueError(f"unknown color format {fmt!r}")


def normalize(images: jnp.ndarray, mean: Sequence[float],
              std: Sequence[float], scale: float = 1.0) -> jnp.ndarray:
    """(x*scale - mean)/std per channel — standard model preprocessing."""
    x, single = _as_batch(images)
    m = jnp.asarray(mean, dtype=jnp.float32)
    s = jnp.asarray(std, dtype=jnp.float32)
    return _unbatch((x.astype(jnp.float32) * scale - m) / s, single)


def unroll(images: jnp.ndarray) -> jnp.ndarray:
    """Flatten NHWC images to (N, C*H*W) vectors in CHW order.

    Parity: UnrollImage's CHW unroll to DenseVector
    (`UnrollImage.scala:21,84` — feature vector layout models expect).
    """
    x, single = _as_batch(images)
    n, h, w, c = x.shape
    out = jnp.transpose(x, (0, 3, 1, 2)).reshape(n, c * h * w)
    return out[0] if single else out


def reroll(vectors: jnp.ndarray, height: int, width: int,
           channels: int) -> jnp.ndarray:
    """Inverse of :func:`unroll`: (N, C*H*W) -> NHWC."""
    single = vectors.ndim == 1
    v = vectors[None] if single else vectors
    x = v.reshape(v.shape[0], channels, height, width).transpose(0, 2, 3, 1)
    return x[0] if single else x
