"""Pipeline stage base classes: Transformer / Estimator / Model / Evaluator.

Capability parity with the Spark ML stage model the whole reference is built
on: an ``Estimator.fit(df)`` returns a ``Model`` (a ``Transformer``);
``Transformer.transform(df)`` maps a columnar frame to a columnar frame;
``Evaluator.evaluate(df)`` computes metrics. Stages carry declared params,
a uid, and directory-based persistence.
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Dict, Optional

import numpy as np

from mmlspark_tpu.core import registry, serialize
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Params

_uid_counter = itertools.count()


class PipelineStage(Params):
    """Base for all stages: params + uid + persistence + registry."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._uid: Optional[str] = None

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        registry.register(cls)

    @property
    def uid(self) -> str:
        if self._uid is None:
            self._uid = f"{type(self).__name__}_{next(_uid_counter):04d}"
        return self._uid

    # -- persistence hooks --------------------------------------------------

    def save(self, path: str) -> None:
        serialize.save_stage(self, path)

    @staticmethod
    def load(path: str) -> "PipelineStage":
        return serialize.load_stage(path)

    def _save_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        """Override to persist complex state (put ndarrays into ``arrays``)."""

    def _load_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        """Override to restore complex state saved by ``_save_extra``."""

    def _save_substage(self, path: str, name: str) -> None:
        """Persist a complex stage-valued param under ``path/name`` (None ok)."""
        import os
        stage = getattr(self, name)
        if stage is not None:
            stage.save(os.path.join(path, name))

    def _load_substage(self, path: str, name: str) -> None:
        """Restore a stage saved by ``_save_substage`` (missing -> None)."""
        import os
        sub = os.path.join(path, name)
        if os.path.isdir(sub):
            setattr(self, name, PipelineStage.load(sub))

    def __repr__(self) -> str:
        params = ", ".join(f"{k}={v!r}" for k, v in self._param_values.items())
        return f"{type(self).__name__}({params})"


class Transformer(PipelineStage):
    def transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


class Estimator(PipelineStage):
    def fit(self, df: DataFrame) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted transformer produced by an Estimator."""


class Evaluator(PipelineStage):
    def evaluate(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError


# -- fluent API (parity: core/spark/FluentAPI.scala df.mlTransform/mlFit) ----

def ml_transform(df: DataFrame, *stages: Transformer) -> DataFrame:
    for s in stages:
        df = s.transform(df)
    return df


def ml_fit(df: DataFrame, estimator: Estimator) -> Model:
    return estimator.fit(df)


_STAGE_HIST = None


def _stage_histogram():
    """The shared per-stage span histogram: batch pipelines (this Timer)
    and the serving plane's ``StageTimings`` report through the same
    telemetry surface, so one ``/metrics`` scrape covers both. Cached
    at module level so a Timer-wrapped transform pays one dict lookup,
    not a registry-lock round trip per call."""
    global _STAGE_HIST
    if _STAGE_HIST is None:
        from mmlspark_tpu.core.telemetry import REGISTRY
        _STAGE_HIST = REGISTRY.histogram(
            "pipeline_stage_duration_ms",
            "Wall-clock of Timer-wrapped pipeline stage fits/transforms.",
            labels=("stage", "phase"))
    return _STAGE_HIST


class Timer(Estimator):
    """Wraps a stage and logs wall-clock of its fit/transform.

    Parity: pipeline-stages Timer (an Estimator producing a TimerModel,
    `Timer.scala:14-90`). Fitting times the inner estimator's fit (or wraps
    a transformer directly); the TimerModel times each transform. Every
    span also lands in the process-wide metrics registry
    (``pipeline_stage_duration_ms{stage=...,phase=fit|transform}``), so
    batch pipelines report through the same exposition as serving.
    """

    from mmlspark_tpu.core.params import Param as _P
    stage = _P(None, "the stage to time", complex=True)

    def fit(self, df: DataFrame) -> "TimerModel":
        inner = self.stage
        if isinstance(inner, Estimator):
            from mmlspark_tpu.core.tracing import ambient_tracer
            t0 = time.time()
            # the span nests under any ambient trace (a traced batch
            # job sees Timer-wrapped fits in its captured timeline)
            with ambient_tracer().span(
                    f"fit:{type(self.stage).__name__}",
                    route="pipeline"):
                inner = inner.fit(df)
            dt = time.time() - t0
            _stage_histogram().labels(
                type(self.stage).__name__, "fit").observe(dt * 1000.0)
            print(f"[Timer] {type(self.stage).__name__}.fit took "
                  f"{dt:.3f}s")
        return TimerModel(stage=inner)

    def _save_extra(self, path, arrays):
        self._save_substage(path, "stage")

    def _load_extra(self, path, arrays):
        self._load_substage(path, "stage")


class TimerModel(Model):
    from mmlspark_tpu.core.params import Param as _P
    stage = _P(None, "the fitted stage to time", complex=True)

    def transform(self, df: DataFrame) -> DataFrame:
        from mmlspark_tpu.core.tracing import ambient_tracer
        t0 = time.time()
        with ambient_tracer().span(
                f"transform:{type(self.stage).__name__}",
                route="pipeline"):
            out = self.stage.transform(df)
        dt = time.time() - t0
        _stage_histogram().labels(
            type(self.stage).__name__, "transform").observe(dt * 1000.0)
        print(f"[Timer] {type(self.stage).__name__}.transform took "
              f"{dt:.3f}s")
        return out

    def _save_extra(self, path, arrays):
        self._save_substage(path, "stage")

    def _load_extra(self, path, arrays):
        self._load_substage(path, "stage")
