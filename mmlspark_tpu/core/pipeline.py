"""Pipeline composition: fit a chain of stages, get a PipelineModel."""

from __future__ import annotations

import os
from typing import Dict, List, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Estimator, Model, PipelineStage, Transformer


class Pipeline(Estimator):
    """Chain of stages; ``fit`` runs estimators in order, threading data.

    Every fit/transform runs under a :mod:`~mmlspark_tpu.core.tracing`
    span (one ``pipeline.fit`` root — or a child, when an ambient span
    exists — with one child per stage), so a slow batch fit leaves the
    same tail-captured timeline a slow serving request does.
    """

    stages = Param(None, "ordered list of pipeline stages", complex=True)

    def fit(self, df: DataFrame) -> "PipelineModel":
        from mmlspark_tpu.core.tracing import ambient_tracer
        tracer = ambient_tracer()
        fitted: List[Transformer] = []
        stages = list(self.stages or [])
        last_fit = max((i for i, s in enumerate(stages)
                        if isinstance(s, Estimator)), default=-1)
        with tracer.span("pipeline.fit", route="pipeline",
                         n_stages=len(stages)):
            for i, stage in enumerate(stages):
                name = type(stage).__name__
                if isinstance(stage, Estimator):
                    with tracer.span(f"fit:{name}", stage_index=i):
                        model = stage.fit(df)
                    fitted.append(model)
                elif isinstance(stage, Transformer):
                    model = stage
                    fitted.append(stage)
                else:
                    raise TypeError(f"not a pipeline stage: {stage!r}")
                if i < last_fit:  # no estimator downstream -> skip it
                    with tracer.span(f"transform:{name}", stage_index=i):
                        df = model.transform(df)
        return PipelineModel(stages=fitted)

    def _save_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        _save_stage_list(self.stages or [], path)

    def _load_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        self.stages = _load_stage_list(path)


class PipelineModel(Model):
    stages = Param(None, "ordered list of fitted transformers", complex=True)

    def transform(self, df: DataFrame) -> DataFrame:
        # per-stage spans: under a serving dispatch the executor has
        # bound the batch-representative request span, so these nest
        # inside that request's "dispatch" — the captured trace then
        # shows WHICH stage of the served pipeline was slow
        from mmlspark_tpu.core.tracing import ambient_tracer
        tracer = ambient_tracer()
        with tracer.span("pipeline.transform", route="pipeline",
                         n_stages=len(self.stages or [])):
            for i, stage in enumerate(self.stages or []):
                with tracer.span(f"transform:{type(stage).__name__}",
                                 stage_index=i):
                    df = stage.transform(df)
        return df

    def _save_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        _save_stage_list(self.stages or [], path)

    def _load_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        self.stages = _load_stage_list(path)


def _save_stage_list(stages: Sequence[PipelineStage], path: str) -> None:
    for i, stage in enumerate(stages):
        stage.save(os.path.join(path, f"stage_{i:03d}"))


def _load_stage_list(path: str) -> List[PipelineStage]:
    out = []
    i = 0
    while os.path.isdir(os.path.join(path, f"stage_{i:03d}")):
        out.append(PipelineStage.load(os.path.join(path, f"stage_{i:03d}")))
        i += 1
    return out
