"""Pipeline composition: fit a chain of stages, get a PipelineModel."""

from __future__ import annotations

import os
from typing import Dict, List, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Estimator, Model, PipelineStage, Transformer


class Pipeline(Estimator):
    """Chain of stages; ``fit`` runs estimators in order, threading data."""

    stages = Param(None, "ordered list of pipeline stages", complex=True)

    def fit(self, df: DataFrame) -> "PipelineModel":
        fitted: List[Transformer] = []
        stages = list(self.stages or [])
        last_fit = max((i for i, s in enumerate(stages)
                        if isinstance(s, Estimator)), default=-1)
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(df)
                fitted.append(model)
            elif isinstance(stage, Transformer):
                model = stage
                fitted.append(stage)
            else:
                raise TypeError(f"not a pipeline stage: {stage!r}")
            if i < last_fit:  # no estimator downstream -> skip the transform
                df = model.transform(df)
        return PipelineModel(stages=fitted)

    def _save_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        _save_stage_list(self.stages or [], path)

    def _load_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        self.stages = _load_stage_list(path)


class PipelineModel(Model):
    stages = Param(None, "ordered list of fitted transformers", complex=True)

    def transform(self, df: DataFrame) -> DataFrame:
        for stage in self.stages or []:
            df = stage.transform(df)
        return df

    def _save_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        _save_stage_list(self.stages or [], path)

    def _load_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        self.stages = _load_stage_list(path)


def _save_stage_list(stages: Sequence[PipelineStage], path: str) -> None:
    for i, stage in enumerate(stages):
        stage.save(os.path.join(path, f"stage_{i:03d}"))


def _load_stage_list(path: str) -> List[PipelineStage]:
    out = []
    i = 0
    while os.path.isdir(os.path.join(path, f"stage_{i:03d}")):
        out.append(PipelineStage.load(os.path.join(path, f"stage_{i:03d}")))
        i += 1
    return out
