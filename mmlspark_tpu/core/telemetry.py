"""Unified telemetry: metrics registry, Prometheus exposition, trace ids.

The reference's observability stops at wall-clock stage timing
(`pipeline-stages/Timer.scala:14-90`); sustained perf at pod scale is won
by continuous low-overhead production telemetry instead — step timings,
queue depths, per-stage histograms that are always on, not one-off
profiler traces. This module is the one place those primitives live so
every layer reports through the same surface:

* :class:`MetricsRegistry` — process-wide (or per-component) home for
  :class:`Counter` / :class:`Gauge` / :class:`Histogram` families with
  Prometheus-style labels. Hot-path updates are lock-striped (a bounded
  pool of locks shared round-robin across children, so a thousand
  metrics never allocate a thousand locks and two busy counters rarely
  contend) and cost well under 2 us each — cheap enough to leave on in
  production (the `perf`-marked test in ``tests/test_telemetry.py`` and
  the ``telemetry_overhead_v1`` bench both enforce the budget).
* :func:`MetricsRegistry.render` — the Prometheus text exposition format
  (``text/plain; version=0.0.4``), served by every worker's
  ``GET /metrics`` (:mod:`mmlspark_tpu.serving.server`).
* :func:`parse_prometheus` / :func:`merge_prometheus` — the minimal
  scrape parser the :class:`~mmlspark_tpu.serving.server.ServingCoordinator`
  uses to fold N workers' scrapes into one fleet view (sample values are
  summed across workers, so counters and histogram buckets aggregate
  exactly and per-worker gauges become fleet totals).
* ``trace_context`` — a :mod:`contextvars` carried ``X-Trace-Id``:
  generated (or adopted from the inbound header) at serving ingress,
  flowed through collect -> dispatch -> encode, stamped into journal
  lines, HTTP egress headers (:mod:`mmlspark_tpu.io.http`), and every
  log record (:mod:`mmlspark_tpu.core.logs`).

Clocks are injectable (:class:`mmlspark_tpu.core.resilience.Clock`), so
chaos tests drive :meth:`Histogram.time` spans deterministically.

Usage::

    from mmlspark_tpu.core.telemetry import REGISTRY

    hits = REGISTRY.counter("cache_hits_total", "Cache hits.",
                            labels=("layer",))
    hot = hits.labels("l1")       # bind the child once, outside the loop
    hot.inc()                     # lock-striped, sub-microsecond

    lat = REGISTRY.histogram("rpc_latency_ms", "RPC wall-clock.")
    with lat.time():              # observes milliseconds on exit
        do_rpc()
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import math
import os
import re
import struct
import threading
import time
import uuid
from bisect import bisect_left
from typing import (
    Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple,
)

from mmlspark_tpu.core.resilience import Clock, SYSTEM_CLOCK

__all__ = [
    "BoundedLabelSet", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "REGISTRY",
    "DEFAULT_LATENCY_BUCKETS_MS", "log_buckets",
    "render_registries", "parse_prometheus", "merge_prometheus",
    "render_samples", "MetricsSnapshot", "snapshot_registries",
    "write_snapshot",
    "MetricsPusher", "quantile_from_buckets",
    "collect_samples", "encode_write_request", "compress_write_request",
    "snappy_available",
    "CONTENT_TYPE", "OPENMETRICS_CONTENT_TYPE",
    "REMOTE_WRITE_CONTENT_TYPE",
    "TRACE_HEADER", "new_trace_id", "current_trace_id", "trace_context",
    "trace_id_from_headers", "sanitize_trace_id",
]


# ---------------------------------------------------------------------------
# Lock striping
# ---------------------------------------------------------------------------

# children draw their update lock from this fixed pool round-robin: the
# common case (each hot child holds its own stripe) contends on nothing,
# while pathological label cardinality shares locks instead of allocating
# one per child forever
_N_STRIPES = 64
_STRIPES = tuple(threading.Lock() for _ in range(_N_STRIPES))
_stripe_counter = itertools.count()


def _next_stripe() -> threading.Lock:
    return _STRIPES[next(_stripe_counter) % _N_STRIPES]


# ---------------------------------------------------------------------------
# Buckets
# ---------------------------------------------------------------------------

def log_buckets(lo: float, hi: float) -> Tuple[float, ...]:
    """A 1-2.5-5 log-scale bucket ladder covering ``[lo, hi]``."""
    if lo <= 0 or hi <= lo:
        raise ValueError(f"need 0 < lo < hi, got ({lo}, {hi})")
    out: List[float] = []
    decade = 10.0 ** math.floor(math.log10(lo))
    while decade <= hi:
        for m in (1.0, 2.5, 5.0):
            edge = decade * m
            if lo <= edge <= hi:
                out.append(edge)
        decade *= 10.0
    return tuple(out)


def quantile_from_buckets(edges: Tuple[float, ...],
                          counts: List[int], q: float) -> Optional[float]:
    """Estimate the ``q``-quantile of a fixed-bucket histogram from
    its per-bucket counts (``len(edges) + 1`` entries, +Inf last) with
    linear interpolation inside the landing bucket — the
    ``histogram_quantile()`` PromQL estimate, computed locally. A rank
    landing in the +Inf bucket returns the top edge (the ladder's
    honest maximum); ``None`` on an empty histogram."""
    total = sum(counts)
    if total <= 0 or not edges:
        return None
    rank = q * total
    cum = 0
    lo = 0.0
    for edge, n in zip(edges, counts):
        if cum + n >= rank and n > 0:
            return lo + (rank - cum) / n * (edge - lo)
        cum += n
        lo = edge
    return float(edges[-1])


#: fixed log-scale latency ladder, in milliseconds: 0.1 ms .. 10 s.
#: Fixed (not per-metric-adaptive) so scrapes from different workers and
#: different build versions aggregate bucket-for-bucket in the fleet view.
DEFAULT_LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class BoundedLabelSet:
    """Cap on tracked label values: past ``cap`` distinct values, new
    ones fold into the ``overflow`` key, so unbounded input domains
    (hosts in a URL column, breaker names) cannot grow a long-lived
    process's registry and exposition without limit.

    :meth:`key` returns ``(label_value, overflowed)`` — callers skip
    non-aggregatable samples (e.g. a state gauge, which would be
    last-writer-wins across unrelated overflow members) when
    ``overflowed`` is True.
    """

    def __init__(self, cap: int = 256, overflow: str = "other"):
        self.cap = int(cap)
        self.overflow = overflow
        self._seen: set = set()
        #: monotonic count of :meth:`key` calls that folded into the
        #: overflow label — the observable evidence that the cap is
        #: too small for the live value domain (per-tenant metric rows
        #: surface it so an operator sees "other" is hiding tenants)
        self.n_overflowed = 0

    def key(self, value: str) -> Tuple[str, bool]:
        if value in self._seen:        # set membership: atomic under GIL
            return value, False
        if len(self._seen) < self.cap:
            self._seen.add(value)
            return value, False
        self.n_overflowed += 1
        return self.overflow, True

    def values(self) -> Tuple[str, ...]:
        """The tracked (non-overflow) label values, sorted — a stats
        surface, not for hot paths."""
        return tuple(sorted(self._seen))


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"') \
                .replace("\n", "\\n")


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Prometheus sample value: integers without a decimal point."""
    if v != v or v in (float("inf"), float("-inf")):
        return {float("inf"): "+Inf", float("-inf"): "-Inf"}.get(v, "NaN")
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


# ---------------------------------------------------------------------------
# Children (one per label-value combination; the hot-path objects)
# ---------------------------------------------------------------------------

class _CounterChild:
    """Monotonic count. ``set_function`` turns the child into a zero-cost
    *view* over an existing monotonic value (e.g. a server's own
    ``n_shed`` int maintained under its own lock) — the hot path then
    pays nothing extra and only exposition reads the callable."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self):
        self._lock = _next_stripe()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    def set_function(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0

    @property
    def value(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        with self._lock:
            return self._value


class _GaugeChild:
    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self):
        self._lock = _next_stripe()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def set_function(self, fn: Callable[[], float]) -> None:
        """Read the gauge from ``fn`` at exposition time (live views —
        queue depths, breaker states — without hot-path writes)."""
        self._fn = fn

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0

    @property
    def value(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        with self._lock:
            return self._value


class _HistogramChild:
    """Fixed-bucket histogram + running sum/count/last/max.

    ``observe`` is the hot path: one C-speed ``bisect`` over the edge
    tuple, then four updates under the stripe lock.

    Exemplars: when a trace id is bound, the observation's bucket
    remembers ``(trace_id, value, unix_ts)`` — last-traced-observation
    sampling, written OUTSIDE the stripe lock (one list-slot store,
    atomic under the GIL; a torn read across the tuple is impossible
    because the tuple is built first and the slot swap is one
    bytecode). A p99 bucket in the exposition then links straight to a
    captured trace (see :mod:`mmlspark_tpu.core.tracing`).
    """

    __slots__ = ("_lock", "_edges", "_counts", "_sum", "_count",
                 "_last", "_max", "_clock", "_exemplars")

    def __init__(self, edges: Tuple[float, ...], clock: Clock):
        self._lock = _next_stripe()
        self._edges = edges
        self._counts = [0] * (len(edges) + 1)   # +1: the +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._last = 0.0
        self._max = 0.0
        self._clock = clock
        # one optional (trace_id, value, unix_ts) per bucket, +Inf incl.
        self._exemplars: List[Optional[Tuple[str, float, float]]] = \
            [None] * (len(edges) + 1)

    def observe(self, value: float) -> None:
        i = bisect_left(self._edges, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            self._last = value
            if value > self._max:
                self._max = value
        # exemplar write stays OUTSIDE the lock stripe: the contextvar
        # read is the only cost untraced hot paths pay
        tid = _trace_id.get()
        if tid is not None:
            self._exemplars[i] = (tid, value, time.time())

    def exemplars(self) -> List[Optional[Tuple[str, float, float]]]:
        return list(self._exemplars)

    @contextlib.contextmanager
    def time(self, scale: float = 1000.0) -> Iterator[None]:
        """Observe the block's wall-clock on exit — in milliseconds by
        default (matching :data:`DEFAULT_LATENCY_BUCKETS_MS`)."""
        t0 = self._clock.now()
        try:
            yield
        finally:
            self.observe((self._clock.now() - t0) * scale)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"count": self._count, "sum": self._sum,
                    "last": self._last, "max": self._max,
                    "buckets": list(self._counts)}

    def cumulative_rows(self, edges
                        ) -> "Tuple[List[Tuple[str, int]], float, int]":
        """``([(le_label, cumulative_count), ...], sum, count)`` with
        the ``+Inf`` overflow row last — the ONE expansion of this
        child into Prometheus histogram samples, shared by the text
        exposition (:meth:`Histogram._render_child`) and the
        remote-write encoder (:func:`collect_samples`) so the scrape
        and the push can never disagree."""
        s = self.stats()
        rows: List[Tuple[str, int]] = []
        cum = 0
        for edge, n in zip(edges, s["buckets"]):
            cum += n
            rows.append((_fmt(edge), cum))
        cum += s["buckets"][-1]
        rows.append(("+Inf", cum))
        return rows, s["sum"], s["count"]

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self._edges) + 1)
            self._sum = 0.0
            self._count = 0
            self._last = 0.0
            self._max = 0.0
            self._exemplars = [None] * (len(self._edges) + 1)


# ---------------------------------------------------------------------------
# Families
# ---------------------------------------------------------------------------

class _Family:
    """A named metric + its per-label-value children.

    Label-less families proxy the child API (``inc``/``set``/``observe``
    on the family hit the single default child), so simple metrics need
    no ``labels()`` call at all.
    """

    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Tuple[str, ...]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._children: Dict[Tuple[str, ...], Any] = {}
        self._create_lock = threading.Lock()

    def _new_child(self):
        raise NotImplementedError

    def labels(self, *values) -> Any:
        """The child for these label values (created on first use).
        Bind it once outside a hot loop — the dict lookup here is cheap
        but not free."""
        key = tuple(str(v) for v in values)
        if len(key) != len(self.label_names):
            raise ValueError(
                f"{self.name} takes labels {self.label_names}, "
                f"got {len(key)} value(s)")
        child = self._children.get(key)      # atomic under the GIL
        if child is None:
            with self._create_lock:
                child = self._children.get(key)
                if child is None:
                    child = self._new_child()
                    self._children[key] = child
        return child

    def children(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._create_lock:
            return list(self._children.items())

    def _default(self):
        return self.labels()

    def _label_str(self, key: Tuple[str, ...],
                   extra: Tuple[Tuple[str, str], ...] = ()) -> str:
        pairs = [f'{n}="{_escape_label(v)}"'
                 for n, v in zip(self.label_names, key)]
        pairs += [f'{n}="{_escape_label(v)}"' for n, v in extra]
        return "{" + ",".join(pairs) + "}" if pairs else ""

    def render(self, exemplars: bool = False) -> List[str]:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        for key, child in sorted(self.children()):
            lines.extend(self._render_child(key, child,
                                            exemplars=exemplars))
        return lines

    def _render_child(self, key, child, exemplars: bool = False
                      ) -> List[str]:
        return [f"{self.name}{self._label_str(key)} {_fmt(child.value)}"]


class Counter(_Family):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._default().set_function(fn)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(_Family):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._default().set_function(fn)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name, help, label_names,
                 buckets: Tuple[float, ...], clock: Clock):
        edges = tuple(float(b) for b in buckets)
        if not edges or any(a >= b for a, b in zip(edges, edges[1:])):
            raise ValueError(
                f"{name}: buckets must be strictly increasing, "
                f"got {buckets!r}")
        self.buckets = edges
        self._clock = clock
        super().__init__(name, help, label_names)

    def _new_child(self):
        return _HistogramChild(self.buckets, self._clock)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def time(self, scale: float = 1000.0):
        return self._default().time(scale)

    def stats(self) -> Dict[str, Any]:
        return self._default().stats()

    @staticmethod
    def _exemplar_suffix(ex) -> str:
        """OpenMetrics exemplar: ``# {trace_id="..."} value ts`` after
        a bucket sample. Emitted ONLY in the OpenMetrics exposition
        (``render(exemplars=True)``): the classic 0.0.4 text-format
        grammar allows nothing after the value but a timestamp, and a
        vanilla Prometheus scraper fails the WHOLE scrape on the ``#``
        token. The in-house parser and the fleet merge take the value
        as the first post-label token and ignore the trailer either
        way."""
        if ex is None:
            return ""
        tid, value, ts = ex
        return (f' # {{trace_id="{_escape_label(tid)}"}} '
                f"{_fmt(value)} {_fmt(round(ts, 3))}")

    def _render_child(self, key, child, exemplars: bool = False
                      ) -> List[str]:
        rows, total, count = child.cumulative_rows(self.buckets)
        ex = child.exemplars() if exemplars else [None] * len(rows)
        lines = [
            f"{self.name}_bucket"
            f"{self._label_str(key, (('le', le),))} {cum}"
            f"{self._exemplar_suffix(ex[i])}"
            for i, (le, cum) in enumerate(rows)]
        lines.append(
            f"{self.name}_sum{self._label_str(key)} {_fmt(total)}")
        lines.append(
            f"{self.name}_count{self._label_str(key)} {count}")
        return lines


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

class MetricsRegistry:
    """A home for metric families; one process-wide :data:`REGISTRY`
    plus per-component instances (each :class:`ServingServer` keeps its
    own, so two workers in one test process never mix counts).

    ``counter``/``gauge``/``histogram`` are get-or-create: a second call
    with the same name returns the same family (and raises on a
    kind/label mismatch — two call sites silently sharing a name with
    different schemas is a bug worth failing loudly on).
    """

    def __init__(self, clock: Clock = SYSTEM_CLOCK):
        self.clock = clock
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, factory: Callable[[], _Family],
                       kind: str, label_names: Tuple[str, ...]) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = factory()
                self._families[name] = fam
                return fam
        if fam.kind != kind or fam.label_names != tuple(label_names):
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind} with "
                f"labels {fam.label_names}, requested {kind} with "
                f"{tuple(label_names)}")
        return fam

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Counter:
        labels = tuple(labels)
        return self._get_or_create(
            name, lambda: Counter(name, help, labels), "counter", labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Gauge:
        labels = tuple(labels)
        return self._get_or_create(
            name, lambda: Gauge(name, help, labels), "gauge", labels)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_MS
                  ) -> Histogram:
        labels = tuple(labels)
        fam = self._get_or_create(
            name,
            lambda: Histogram(name, help, labels, buckets, self.clock),
            "histogram", labels)
        # schema mismatches fail loudly (see class docstring) — buckets
        # are schema too: silently inheriting another call site's ladder
        # would collapse out-of-range samples into +Inf with no error
        requested = tuple(float(b) for b in buckets)
        if fam.buckets != requested:
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{fam.buckets}, requested {requested}")
        return fam

    def families(self) -> List[_Family]:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def render(self, exemplars: bool = False) -> str:
        """Prometheus text exposition (version 0.0.4): families sorted
        by name, children by label values — byte-stable for goldens.
        ``exemplars=True`` appends OpenMetrics exemplar trailers to
        histogram bucket lines — serve that ONLY under the OpenMetrics
        content type (:data:`OPENMETRICS_CONTENT_TYPE`): the classic
        format's grammar rejects the trailer and a strict scraper
        would fail the whole scrape."""
        lines: List[str] = []
        for fam in self.families():
            lines.extend(fam.render(exemplars=exemplars))
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Zero every child's accumulators IN PLACE (tests; a
        production registry never resets — counters are forever).
        Families and children survive, so call sites holding cached
        family/child references (io/http, resilience, trainer, Timer)
        stay wired to the exposition — dropping families would orphan
        those caches into invisible updates."""
        for fam in self.families():
            for _, child in fam.children():
                child.reset()


#: the process-wide default registry: framework-level metrics
#: (pipeline stages, trainer, HTTP/resilience) report here; servers add
#: their own per-instance registry on top (see ``GET /metrics``).
REGISTRY = MetricsRegistry()

#: the exposition content type.
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: the OpenMetrics content type — the exposition a scraper must
#: negotiate (Accept header) to receive histogram exemplars.
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


def render_registries(*registries: MetricsRegistry,
                      exemplars: bool = False) -> str:
    """Concatenate several registries' expositions (a worker's
    ``/metrics`` = its own registry + the process-wide one)."""
    return "".join(r.render(exemplars=exemplars) for r in registries)


# ---------------------------------------------------------------------------
# Metrics snapshots (batch jobs that exit before a scrape)
# ---------------------------------------------------------------------------

def write_snapshot(directory: str, text: str, tag: Optional[str] = None,
                   prefix: str = "metrics", keep: int = 0) -> str:
    """Write already-rendered exposition ``text`` to
    ``directory/<prefix>-<tag>.prom`` (any io.fs target — a checkpoint
    dir, gs://...). ``tag`` defaults to a UTC timestamp; ``keep > 0``
    prunes the directory to the newest ``keep`` snapshots (tags sort
    lexically: both timestamps and zero-padded step tags order
    correctly). Returns the path.

    This is the shared write path under :func:`snapshot_registries`
    (which scrapes, then calls here) and the TSDB Recorder (which
    dumps the SAME scrape it ingests — one scrape per interval, not
    one per consumer)."""
    from mmlspark_tpu.io import fs as _fs
    if tag is None:
        tag = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    _fs.makedirs(directory)
    path = _fs.join(directory, f"{prefix}-{tag}.prom")
    _fs.write_text(path, text)
    if keep > 0:
        mine = sorted(
            p for p in _fs.find_files(directory, recursive=False)
            if os.path.basename(p).startswith(prefix + "-")
            and p.endswith(".prom"))
        for old in mine[:-keep]:
            try:
                if _fs.is_remote(old):
                    fs_obj, p = _fs.get_fs(old)
                    fs_obj.rm(p)
                else:
                    os.remove(old)
            except Exception:  # noqa: BLE001 — pruning is best-effort
                pass
    return path


def snapshot_registries(directory: str, tag: Optional[str] = None,
                        registries: Iterable[MetricsRegistry] = (),
                        prefix: str = "metrics", keep: int = 0) -> str:
    """Scrape ``registries`` (default: the process-wide one) and write
    the exposition via :func:`write_snapshot`. Returns the path."""
    regs = tuple(registries) or (REGISTRY,)
    return write_snapshot(directory, render_registries(*regs), tag,
                          prefix, keep)


class MetricsSnapshot:
    """Periodic registry-scrape dumper for batch jobs.

    A Prometheus server scrapes long-lived workers, but a training or
    ETL job that exits between scrapes leaves no telemetry behind.
    ``MetricsSnapshot`` writes the exposition to a directory on an
    interval (daemon thread) and once more on :meth:`stop`, so the
    job's final counters always land on disk — the in-repo stand-in
    for a push gateway. The trainer also drops a scrape next to every
    checkpoint (``metrics-step<NNNNNNNN>.prom``), so a preempted fit's
    telemetry survives exactly as far as its checkpoints do.

    Usage::

        with MetricsSnapshot("/ckpt/telemetry", interval_s=60):
            run_job()
    """

    def __init__(self, directory: str,
                 registries: Iterable[MetricsRegistry] = (),
                 interval_s: float = 60.0, keep: int = 24,
                 prefix: str = "metrics"):
        self.directory = directory
        self.registries = tuple(registries) or (REGISTRY,)
        self.interval_s = float(interval_s)
        self.keep = int(keep)
        self.prefix = prefix
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def write_now(self, tag: Optional[str] = None) -> str:
        return snapshot_registries(self.directory, tag, self.registries,
                                   self.prefix, self.keep)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.write_now()
            except Exception:  # noqa: BLE001 — telemetry never kills jobs
                from mmlspark_tpu.core.logs import get_logger
                get_logger("telemetry").warning(
                    "metrics snapshot to %s failed", self.directory,
                    exc_info=True)

    def start(self) -> "MetricsSnapshot":
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the writer and flush one final snapshot (the scrape a
        batch job exists to leave behind)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self.write_now()
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self) -> "MetricsSnapshot":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# Prometheus remote-write protobuf encoding (hand-rolled, zero deps)
# ---------------------------------------------------------------------------
#
# The native remote-write v1 wire format is a snappy-compressed
# protobuf ``prometheus.WriteRequest``:
#
#   message WriteRequest { repeated TimeSeries timeseries = 1; }
#   message TimeSeries   { repeated Label labels = 1;
#                          repeated Sample samples = 2; }
#   message Label        { string name = 1; string value = 2; }
#   message Sample       { double value = 1; int64 timestamp = 2; }
#
# Four messages, three wire types — small enough to encode by hand
# (varints + length-delimited fields + one little-endian double), so a
# real Prometheus can ingest pushes directly at /api/v1/write with no
# protobuf dependency baked into the image. ``python-snappy`` is
# optional: when absent the encoder still produces valid protobuf and
# the pusher sends it UNCOMPRESSED (spec-noncompliant but accepted by
# several shims; the text exposition stays the default path either
# way, so nothing regresses without snappy).


def _pb_varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1            # int64 timestamps encode two's-complement
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _pb_delim(field: int, payload: bytes) -> bytes:
    return _pb_varint((field << 3) | 2) + _pb_varint(len(payload)) + payload


def _pb_label(name: str, value: str) -> bytes:
    return (_pb_delim(1, name.encode()) + _pb_delim(2, str(value).encode()))


def _pb_sample(value: float, ts_ms: int) -> bytes:
    return (_pb_varint((1 << 3) | 1) + struct.pack("<d", float(value))
            + _pb_varint(2 << 3) + _pb_varint(int(ts_ms)))


def _pb_series(name: str, labels, value: float, ts_ms: int) -> bytes:
    # labels MUST be sorted by name with __name__ first per the spec
    pairs = sorted([("__name__", name)] + list(labels))
    body = b"".join(_pb_delim(1, _pb_label(n, v)) for n, v in pairs)
    body += _pb_delim(2, _pb_sample(value, ts_ms))
    return _pb_delim(1, body)


def collect_samples(*registries: MetricsRegistry
                    ) -> List[Tuple[str, Tuple[Tuple[str, str], ...], float]]:
    """Flatten registries into ``(metric_name, ((label, value), ...),
    sample_value)`` rows — histograms expand to the standard
    ``_bucket``/``_sum``/``_count`` series with cumulative ``le``
    counts, exactly mirroring the text exposition."""
    rows: List[Tuple[str, Tuple[Tuple[str, str], ...], float]] = []
    for reg in registries:
        for fam in reg.families():
            base = tuple(fam.label_names)
            for key, child in sorted(fam.children()):
                labels = tuple(zip(base, key))
                if fam.kind in ("counter", "gauge"):
                    rows.append((fam.name, labels, float(child.value)))
                    continue
                # one expansion shared with the text exposition
                # (cumulative_rows), so scrape and push cannot drift
                hrows, total, count = child.cumulative_rows(fam.buckets)
                rows.extend((f"{fam.name}_bucket",
                             labels + (("le", le),), float(cum))
                            for le, cum in hrows)
                rows.append((f"{fam.name}_sum", labels, float(total)))
                rows.append((f"{fam.name}_count", labels, float(count)))
    return rows


def encode_write_request(*registries: MetricsRegistry,
                         ts_ms: Optional[int] = None,
                         extra_labels: Tuple[Tuple[str, str], ...] = ()
                         ) -> bytes:
    """Serialize registries as a ``prometheus.WriteRequest`` protobuf
    (uncompressed — see :func:`compress_write_request`)."""
    if ts_ms is None:
        ts_ms = int(time.time() * 1000)
    return b"".join(
        _pb_series(name, labels + extra_labels, value, ts_ms)
        for name, labels, value in collect_samples(*registries))


def snappy_available() -> bool:
    try:
        import snappy  # noqa: F401
        return True
    except ImportError:
        return False


def compress_write_request(payload: bytes) -> Tuple[bytes, Optional[str]]:
    """Snappy-compress when the optional codec exists: returns
    ``(body, content_encoding)`` — ``(payload, None)`` in the
    snappy-less fallback, which stays valid protobuf and is accepted
    by permissive receivers."""
    if snappy_available():
        import snappy
        return snappy.compress(payload), "snappy"
    return payload, None


#: remote-write v1 request content type
REMOTE_WRITE_CONTENT_TYPE = "application/x-protobuf"


# ---------------------------------------------------------------------------
# Remote-write: push the exposition to a live gateway
# ---------------------------------------------------------------------------

class MetricsPusher:
    """Background remote-write: POST the registry exposition to a
    push-gateway URL on an interval, and once more on :meth:`stop`.

    :class:`MetricsSnapshot` leaves scrapes on *disk*;
    ``MetricsPusher`` closes the remaining gap to a LIVE Prometheus —
    point ``url`` at a Pushgateway job path
    (``http://gw:9091/metrics/job/<job>``) or any remote-write-shim
    endpoint that accepts the text exposition. Sends go through
    :mod:`mmlspark_tpu.io.http`'s resilient client: a jittered/bounded
    :class:`~mmlspark_tpu.core.resilience.RetryPolicy` per push and a
    circuit breaker on the gateway host, so a dead gateway costs one
    short retry schedule per interval (then an instant breaker-refused
    attempt), never a hung telemetry thread. Push failures are counted
    (``n_errors``) and logged — telemetry must never kill the job.

    Usage::

        with MetricsPusher("http://gw:9091/metrics/job/train",
                           interval_s=30):
            run_job()                  # final flush on exit
    """

    def __init__(self, url: str,
                 registries: Iterable[MetricsRegistry] = (),
                 interval_s: float = 30.0, timeout: float = 5.0,
                 policy=None, headers: Optional[Dict[str, str]] = None,
                 header_provider: Optional[
                     Callable[[], Optional[Dict[str, str]]]] = None,
                 session=None, format: str = "text"):
        self.url = url
        self.registries = tuple(registries) or (REGISTRY,)
        self.interval_s = float(interval_s)
        self.timeout = float(timeout)
        # wire format: "text" (default — Pushgateway and every text
        # shim) or "remote_write" (the NATIVE Prometheus remote-write
        # v1 protobuf, pointed straight at /api/v1/write: hand-rolled
        # WriteRequest encoding + snappy compression when the optional
        # codec exists; without snappy the same valid protobuf goes
        # uncompressed with no Content-Encoding — permissive receivers
        # accept it, strict ones 400 visibly in last_status rather
        # than silently dropping samples)
        if format not in ("text", "remote_write"):
            raise ValueError(f"unknown push format {format!r} "
                             "(expected 'text' or 'remote_write')")
        self.format = format
        self.n_uncompressed = 0   # snappy-less remote-write pushes
        # auth surface: ``headers`` are static (set once, sent on every
        # push); ``header_provider`` is re-invoked per push and its
        # result layered on top, so short-lived bearer tokens rotate
        # without restarting the pusher. Provider failures are counted
        # + logged and the push proceeds with the static set — a broken
        # token refresher degrades to 401s at the gateway (visible in
        # last_status), never a dead telemetry thread.
        self.headers = dict(headers or {})
        self.header_provider = header_provider
        self.n_pushes = 0
        self.n_errors = 0
        self.last_status: Optional[int] = None
        self._policy = policy
        self._session = session
        self._client = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _get_client(self):
        # lazy: io.http imports this module, so the cycle must resolve
        # at call time; a pusher that never pushes imports nothing
        if self._client is None:
            from mmlspark_tpu.core.resilience import (
                BreakerBoard, RetryPolicy,
            )
            from mmlspark_tpu.io.http import HTTPClient
            policy = self._policy or RetryPolicy(
                max_attempts=3, base=0.2, cap=2.0)
            # a PRIVATE breaker board: the push gateway's health must
            # not open the process-wide SHARED_BREAKERS entry some
            # model egress may share, and vice versa
            self._client = HTTPClient(
                timeout=self.timeout, policy=policy,
                breakers=BreakerBoard(failure_threshold=5,
                                      reset_timeout=30.0),
                session=self._session)
        return self._client

    def push_now(self) -> bool:
        """One synchronous push; True iff the gateway answered 2xx
        (after the retry schedule). Never raises."""
        from mmlspark_tpu.io.http import HTTPRequestData
        if self.format == "remote_write":
            body, encoding = compress_write_request(
                encode_write_request(*self.registries))
            h = {"Content-Type": REMOTE_WRITE_CONTENT_TYPE,
                 "X-Prometheus-Remote-Write-Version": "0.1.0"}
            if encoding is not None:
                h["Content-Encoding"] = encoding
            else:
                self.n_uncompressed += 1
        else:
            body = render_registries(*self.registries).encode()
            h = {"Content-Type": CONTENT_TYPE}
        h.update(self.headers)
        if self.header_provider is not None:
            try:
                h.update(self.header_provider() or {})
            except Exception:  # noqa: BLE001 — a broken token refresher
                self.n_errors += 1     # must not kill the push cadence
                from mmlspark_tpu.core.logs import get_logger
                get_logger("telemetry").warning(
                    "metrics push header_provider raised; pushing with "
                    "static headers only", exc_info=True)
        req = HTTPRequestData(url=self.url, method="POST", headers=h,
                              body=body)
        # bind a trace id with no ambient span: egress spans then mark
        # themselves mid-trace and a flaky gateway cannot churn the
        # trace store with one-span error captures every interval
        with trace_context():
            resp = self._get_client().send([req])[0]
        self.last_status = resp.status_code if resp is not None else None
        ok = resp is not None and 200 <= resp.status_code < 300
        if ok:
            self.n_pushes += 1
        else:
            self.n_errors += 1
            from mmlspark_tpu.core.logs import get_logger
            get_logger("telemetry").warning(
                "metrics push to %s failed (status=%s reason=%s)",
                self.url, getattr(resp, "status_code", None),
                getattr(resp, "reason", "no response"))
        return ok

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.push_now()
            except Exception:  # noqa: BLE001 — telemetry never kills jobs
                from mmlspark_tpu.core.logs import get_logger
                get_logger("telemetry").warning(
                    "metrics push to %s raised", self.url, exc_info=True)

    def start(self) -> "MetricsPusher":
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the pusher and flush one final push — the scrape that
        carries a batch job's terminal counters to the gateway."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.timeout + 5)
            self._thread = None
        try:
            self.push_now()
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self) -> "MetricsPusher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# Scrape parsing + fleet merge
# ---------------------------------------------------------------------------

# the label block matches QUOTED values (backslash escapes honored), so
# a value containing '}' or ',' cannot truncate the block
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(\{\s*(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"\s*,?\s*)*\})?'
    r'\s+(\S+)')
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r'\\(.)')


def _unescape_label(value: str) -> str:
    # one pass over \X pairs: sequential str.replace would mis-handle a
    # literal backslash followed by 'n' (escaped \\ + n is NOT \n)
    return _UNESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), value)


def parse_prometheus(text: str
                     ) -> List[Tuple[str, Tuple[Tuple[str, str], ...], float]]:
    """Parse an exposition into ``(name, sorted label pairs, value)``
    samples. Minimal by design: enough to round-trip what
    :meth:`MetricsRegistry.render` emits (the coordinator merging its
    own workers' scrapes), not a general OpenMetrics parser."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labels_raw, value_raw = m.groups()
        try:
            value = float(value_raw)
        except ValueError:
            continue
        labels = tuple(sorted(
            (k, _unescape_label(v))
            for k, v in _LABEL_PAIR_RE.findall(labels_raw or "")))
        out.append((name, labels, value))
    return out


def render_samples(samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                                 float]) -> str:
    """Render ``{(name, labels): value}`` samples (e.g. a
    :func:`merge_prometheus` result) back into exposition lines, with
    the SAME escaping/formatting as :meth:`MetricsRegistry.render` —
    newline-bearing label values and infinities survive the
    round-trip. No HELP/TYPE comments (a merge has no single source
    family)."""
    lines = []
    for (name, labels), value in sorted(samples.items()):
        label_str = "{" + ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in labels) + "}" \
            if labels else ""
        lines.append(f"{name}{label_str} {_fmt(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def merge_prometheus(texts: Iterable[str]
                     ) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """Fold N workers' scrapes into one: sample values summed per
    ``(name, labels)``. Exact for counters and histogram
    buckets/sums/counts; per-worker gauges (queue depth, inflight)
    become fleet totals, which is the number an operator wants."""
    merged: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for text in texts:
        for name, labels, value in parse_prometheus(text):
            key = (name, labels)
            merged[key] = merged.get(key, 0.0) + value
    return merged


# ---------------------------------------------------------------------------
# Trace ids
# ---------------------------------------------------------------------------

TRACE_HEADER = "X-Trace-Id"

_trace_id: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("mmlspark_tpu_trace_id", default=None)

# same trick as the serving rids: uuid4 per request is an os.urandom
# syscall; a process-unique random prefix + a counter is unique across
# the fleet and ~free per id
_TRACE_PREFIX = uuid.uuid4().hex[:16]
_TRACE_COUNTER = itertools.count(1)


def new_trace_id() -> str:
    return f"{_TRACE_PREFIX}{next(_TRACE_COUNTER):08x}"


def current_trace_id() -> Optional[str]:
    """The trace id bound to this context, or None outside any trace."""
    return _trace_id.get()


@contextlib.contextmanager
def trace_context(trace_id: Optional[str] = None) -> Iterator[str]:
    """Bind a trace id (generated when None) to the current context;
    every log record and egress HTTP request inside the block carries
    it. Contextvars do NOT cross thread handoffs — a staged pipeline
    re-enters ``trace_context`` per stage from the id it carried on the
    work item (see ``serving/server.py``)."""
    tid = trace_id or new_trace_id()
    token = _trace_id.set(tid)
    try:
        yield tid
    finally:
        _trace_id.reset(token)


_TRACE_ID_OK_RE = re.compile(r"[A-Za-z0-9._-]{1,128}")


def sanitize_trace_id(raw) -> Optional[str]:
    """Sanitize an inbound trace id to ``[A-Za-z0-9._-]`` (<= 128
    chars), ``None`` when nothing survives. Spaces and ``=`` would let
    a client inject spoofed ``key=value`` tokens into the worker's own
    plain-format log lines — the PR 3 ingress contract, shared with
    :func:`mmlspark_tpu.core.tracing.extract_span_context`. A clean id
    (the overwhelmingly common case — our own ids always are) passes
    on one C-speed fullmatch; only dirty input pays the per-char
    scrub. The fast path keeps context extraction inside the
    2 us/hop ``trace_propagation_overhead_v1`` budget."""
    if not raw:
        return None
    if type(raw) is not str:
        raw = str(raw)
    if _TRACE_ID_OK_RE.fullmatch(raw):
        return raw
    raw = "".join(ch for ch in raw.strip()[:128]
                  if ch.isalnum() or ch in "._-")
    return raw or None


def trace_id_from_headers(headers) -> str:
    """Adopt the inbound ``X-Trace-Id`` (sanitized — it lands in logs
    and journal lines) or mint a fresh one."""
    raw = headers.get(TRACE_HEADER) if headers is not None else None
    return sanitize_trace_id(raw) or new_trace_id()


# ---------------------------------------------------------------------------
# Build info
# ---------------------------------------------------------------------------

_BUILD_INFO: Optional[Dict[str, str]] = None


def build_info() -> Dict[str, str]:
    """The process's build identity — framework/jax/jaxlib versions
    and the accelerator kind — computed once (the jax import and
    device query are not free) and shared by every registration."""
    global _BUILD_INFO
    if _BUILD_INFO is None:
        from mmlspark_tpu.version import __version__
        info = {"version": __version__, "jax": "none",
                "jaxlib": "none", "device_kind": "none"}
        try:
            import jax
            info["jax"] = jax.__version__
            try:
                import jaxlib
                info["jaxlib"] = getattr(jaxlib, "__version__",
                                         jax.__version__)
            except Exception:
                info["jaxlib"] = jax.__version__
            devices = jax.devices()
            if devices:
                info["device_kind"] = str(devices[0].device_kind)
        except Exception:  # pragma: no cover - jax always importable
            pass
        _BUILD_INFO = info
    return dict(_BUILD_INFO)


def register_build_info(registry: MetricsRegistry,
                        frontend: str = "none") -> Dict[str, str]:
    """Stamp the ``serving_build_info`` gauge (constant 1; identity in
    the labels — the Prometheus ``*_build_info`` convention) into
    ``registry`` and return the label dict for ``/stats`` echo.
    ``frontend`` distinguishes the serving edge in play (``eventloop``
    / ``threaded`` / ``coordinator``)."""
    info = build_info()
    info["frontend"] = str(frontend)
    g = registry.gauge(
        "serving_build_info",
        "Constant 1; build identity in the labels.",
        labels=("version", "jax", "jaxlib", "device_kind", "frontend"))
    g.labels(info["version"], info["jax"], info["jaxlib"],
             info["device_kind"], info["frontend"]).set(1.0)
    return info
