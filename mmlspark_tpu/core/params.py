"""Declarative parameter system for pipeline stages.

Capability parity with Spark ML ``Params`` as used throughout the reference
(`core/contracts/src/main/scala/Params.scala:10-82`, the extended param types
in `core/serialize/src/main/scala/params/`): every stage declares typed,
documented, validated params; params serialize to JSON for persistence; and
shared mixins (``HasInputCol`` etc.) give a uniform API across stages.

Python-native design: params are class-level :class:`Param` descriptors;
stages accept them as constructor keyword arguments and expose snake_case
attributes plus a fluent ``.set(**kwargs)``.
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Callable, Dict, Optional, Type


class Param:
    """A declared, typed, documented parameter on a stage class."""

    def __init__(self, default: Any = None, doc: str = "",
                 validator: Optional[Callable[[Any], bool]] = None,
                 ptype: Optional[Type] = None, complex: bool = False):
        self.default = default
        self.doc = doc
        self.validator = validator
        self.ptype = ptype
        # complex params (models, functions, frames) are excluded from JSON
        # and persisted via the owning stage's _save_extra/_load_extra hooks
        # (parity: ComplexParam hierarchy, core/serialize/ComplexParam.scala)
        self.complex = complex
        self.name: str = ""  # filled by __set_name__

    def __set_name__(self, owner, name):
        self.name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj._param_values.get(self.name, self.default)

    def __set__(self, obj, value):
        obj._set_param(self.name, value)

    def validate(self, value: Any) -> None:
        """Validate an already-coerced value (coercion lives in _set_param)."""
        if value is None:
            return
        if self.ptype is not None:
            if not isinstance(value, self.ptype):
                raise TypeError(
                    f"param {self.name!r} expects {self.ptype.__name__}, "
                    f"got {type(value).__name__}: {value!r}")
        if self.validator is not None and not self.validator(value):
            raise ValueError(f"invalid value for param {self.name!r}: {value!r}")


def in_range(lo=None, hi=None):
    def check(v):
        return (lo is None or v >= lo) and (hi is None or v <= hi)
    return check


def in_set(*options):
    opts = set(options)
    return lambda v: v in opts


class Params:
    """Base class collecting :class:`Param` descriptors and their values."""

    def __init__(self, **kwargs):
        self._param_values: Dict[str, Any] = {}
        self.set(**kwargs)

    @classmethod
    def params(cls) -> Dict[str, Param]:
        out: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    out[k] = v
        return out

    def _set_param(self, name: str, value: Any) -> None:
        p = type(self).params().get(name)
        if p is None:
            raise KeyError(f"{type(self).__name__} has no param {name!r}")
        if value is not None and p.ptype is float and isinstance(value, int) \
                and not isinstance(value, bool):
            value = float(value)
        p.validate(value)
        self._param_values[name] = value

    def set(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            self._set_param(k, v)
        return self

    def get(self, name: str) -> Any:
        return getattr(self, name)

    def is_set(self, name: str) -> bool:
        return name in self._param_values

    def get_param_values(self, include_defaults: bool = False) -> Dict[str, Any]:
        if include_defaults:
            return {k: getattr(self, k) for k in type(self).params()}
        return dict(self._param_values)

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(type(self).params().items()):
            current = self._param_values.get(name, p.default)
            lines.append(f"{name}: {p.doc} (default: {p.default!r}, "
                         f"current: {current!r})")
        return "\n".join(lines)

    def copy(self, **overrides) -> "Params":
        out = _copy.copy(self)
        out._param_values = dict(self._param_values)
        out.set(**overrides)
        return out

    def _json_params(self) -> Dict[str, Any]:
        """Explicitly-set non-complex params, for JSON persistence."""
        declared = type(self).params()
        return {k: v for k, v in self._param_values.items()
                if not declared[k].complex}


# ---------------------------------------------------------------------------
# Shared param mixins (parity: core/contracts/Params.scala HasInputCol etc.)
# ---------------------------------------------------------------------------

class HasInputCol(Params):
    input_col = Param(None, "name of the input column", ptype=str)


class HasInputCols(Params):
    input_cols = Param(None, "names of the input columns", ptype=list)


class HasOutputCol(Params):
    output_col = Param(None, "name of the output column", ptype=str)


class HasOutputCols(Params):
    output_cols = Param(None, "names of the output columns", ptype=list)


class HasLabelCol(Params):
    label_col = Param("label", "name of the label column", ptype=str)


class HasFeaturesCol(Params):
    features_col = Param("features", "name of the features column", ptype=str)


class HasWeightCol(Params):
    weight_col = Param(None, "name of the instance weight column", ptype=str)
