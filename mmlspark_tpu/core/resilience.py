"""Composable resilience primitives: retries, deadlines, circuit breakers.

The reference ecosystem's production value was HTTP pipelines that keep
working under throttling and partial failure (`HTTPClients.scala:107-133`
advanced handlers, Spark Serving's exactly-once commits). This module is
the one place those behaviors are defined so every layer — HTTP-on-columns
handlers (:mod:`mmlspark_tpu.io.http`), service bindings
(:mod:`mmlspark_tpu.io.services`), the serving frontend and its client
(:mod:`mmlspark_tpu.serving.server`), and the fault-tolerant trainer
(:mod:`mmlspark_tpu.models.trainer`) — shares the same policy vocabulary:

* :class:`RetryPolicy` — exponential backoff with decorrelated jitter,
  bounded by BOTH an attempt budget and an elapsed-time budget, honoring
  server ``Retry-After`` hints.
* :class:`Deadline` — an absolute time budget that propagates across
  process boundaries via the ``X-Deadline-Ms`` header and is checked at
  every expensive boundary (before batch dispatch, before commit).
* :class:`CircuitBreaker` — closed/open/half-open per dependency (host,
  worker), so a dead endpoint sheds load instantly instead of burning a
  full retry schedule per request.

Every primitive takes an injectable :class:`Clock`, so chaos tests
(:mod:`mmlspark_tpu.testing.faults`, ``tests/test_resilience.py``) drive
state transitions deterministically with zero wall-clock sleeps.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple


# ---------------------------------------------------------------------------
# Clocks
# ---------------------------------------------------------------------------

class Clock:
    """Injectable time source: monotonic ``now()`` + ``sleep()``."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class ManualClock(Clock):
    """Deterministic clock for tests: ``sleep`` advances ``now`` instantly.

    Backoffs, deadline expiry, and breaker reset timers all resolve
    against this clock, so a chaos test walks closed -> open -> half-open
    -> closed without a single wall-clock wait.
    """

    def __init__(self, start: float = 0.0):
        self._t = float(start)
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._t

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)

    def advance(self, seconds: float) -> None:
        with self._lock:
            self._t += max(float(seconds), 0.0)


SYSTEM_CLOCK = Clock()


# ---------------------------------------------------------------------------
# Telemetry hooks (lazy: telemetry imports Clock from this module, so the
# metric families are resolved at first event, never at import time)
# ---------------------------------------------------------------------------

_METRICS: Optional[Dict[str, Any]] = None
_BREAKER_STATE_VALUES = {"closed": 0, "half_open": 1, "open": 2}


def _metrics() -> Dict[str, Any]:
    global _METRICS
    if _METRICS is None:
        from mmlspark_tpu.core.telemetry import BoundedLabelSet, REGISTRY
        _METRICS = {
            # breaker names are per dependency (host, worker url): an
            # unbounded fan-out must not grow the registry forever
            "breaker_labels": BoundedLabelSet(256),
            "retries": REGISTRY.counter(
                "resilience_retries_total",
                "Retry attempts actually scheduled (a backoff sleep was "
                "taken) across every policy-driven caller."),
            "breaker_transitions": REGISTRY.counter(
                "breaker_transitions_total",
                "Circuit-breaker state transitions.",
                labels=("breaker", "to")),
            "breaker_state": REGISTRY.gauge(
                "breaker_state",
                "Current breaker state per dependency: 0 closed, "
                "1 half-open, 2 open.", labels=("breaker",)),
        }
    return _METRICS


def _breaker_event(name: str, to_state: str) -> None:
    """Record a breaker transition (called with the breaker lock held —
    safe: telemetry takes only its own stripe locks and never calls
    back). Telemetry must never break a failure path, hence the guard."""
    try:
        m = _metrics()
        key, overflow = m["breaker_labels"].key(name or "unnamed")
        m["breaker_transitions"].labels(key, to_state).inc()
        # transitions aggregate sensibly under "other"; a shared state
        # gauge does not (last-writer-wins across unrelated breakers
        # would report closed while another overflow breaker is open)
        if not overflow:
            m["breaker_state"].labels(key).set(
                _BREAKER_STATE_VALUES[to_state])
    except Exception:  # noqa: BLE001 — observability is best-effort
        pass


# ---------------------------------------------------------------------------
# Deadlines
# ---------------------------------------------------------------------------

class DeadlineExceeded(Exception):
    """A time budget ran out before the work completed."""


class Deadline:
    """An absolute point in time the work must finish by.

    Propagation: :meth:`to_header` encodes the REMAINING budget in
    milliseconds under ``X-Deadline-Ms``; the receiving layer rebuilds an
    absolute deadline against its own clock with :meth:`from_headers`.
    Relative-on-the-wire is deliberate — it needs no cross-host clock
    sync, at the cost of ignoring network transit time (the budget
    restarts on arrival), the same tradeoff gRPC's timeout header makes.
    """

    HEADER = "X-Deadline-Ms"

    def __init__(self, timeout: float, clock: Clock = SYSTEM_CLOCK):
        self.clock = clock
        self._expires = clock.now() + float(timeout)

    @staticmethod
    def from_headers(headers, clock: Clock = SYSTEM_CLOCK
                     ) -> Optional["Deadline"]:
        """Deadline from an ``X-Deadline-Ms`` header, or None without one
        (or with a malformed value — an unparsable budget must not turn
        into an instant 504)."""
        raw = headers.get(Deadline.HEADER) if headers else None
        if raw is None:
            return None
        try:
            return Deadline(float(raw) / 1000.0, clock=clock)
        except (TypeError, ValueError):
            return None

    def remaining(self) -> float:
        return self._expires - self.clock.now()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def to_header(self) -> str:
        return str(max(int(self.remaining() * 1000), 0))

    def check(self, what: str = "operation") -> None:
        if self.expired:
            raise DeadlineExceeded(
                f"{what} exceeded its deadline by {-self.remaining():.3f}s")


# ---------------------------------------------------------------------------
# Retry policies
# ---------------------------------------------------------------------------

class RetryPolicy:
    """Retry schedule: exponential backoff + decorrelated jitter, bounded
    by attempts AND elapsed time, ``Retry-After`` aware.

    ``delay_{n+1} = min(cap, uniform(base, delay_n * 3))`` — the
    decorrelated-jitter formula, which desynchronizes retry storms from
    many clients while keeping expected growth exponential. The jitter
    stream is seedable for tests (see below). ``backoffs`` takes
    an explicit delay list instead (the legacy fixed-list handlers ride
    this path and gain the budget/deadline bounds for free).

    One policy object is immutable shared config; each logical call gets
    its own :class:`RetrySchedule` via :meth:`schedule`. ``seed=None``
    (the default) draws each schedule's jitter from OS entropy — the
    production mode, where concurrent callers MUST desynchronize; pass
    a seed only when a test needs to pin the exact delay sequence.
    """

    def __init__(self, max_attempts: int = 4, base: float = 0.1,
                 cap: float = 10.0, budget: Optional[float] = None,
                 retry_statuses: Tuple[int, ...] = (429, 500, 502, 503, 504),
                 backoffs: Optional[Tuple[float, ...]] = None,
                 seed: Optional[int] = None, clock: Clock = SYSTEM_CLOCK):
        if backoffs is not None:
            backoffs = tuple(float(b) for b in backoffs)
            max_attempts = len(backoffs) + 1
        self.max_attempts = max(int(max_attempts), 1)
        self.base = float(base)
        self.cap = float(cap)
        self.budget = float(budget) if budget is not None else None
        self.retry_statuses = tuple(retry_statuses)
        self.backoffs = backoffs
        self.seed = seed
        self.clock = clock

    def retryable_status(self, status: int) -> bool:
        """Transport failures land as status 0 and always retry."""
        return status == 0 or status in self.retry_statuses

    def schedule(self, deadline: Optional[Deadline] = None
                 ) -> "RetrySchedule":
        return RetrySchedule(self, deadline)

    def call(self, fn: Callable[[], Any],
             retryable: Callable[[Exception], bool] = lambda e: True,
             deadline: Optional[Deadline] = None) -> Any:
        """Run ``fn`` under this policy, retrying exceptions ``retryable``
        accepts; re-raises the last error when the budget is spent."""
        sched = self.schedule(deadline)
        while True:
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — classified below
                if not retryable(e) or sched.give_up():
                    raise


class RetrySchedule:
    """Mutable per-call retry state produced by :meth:`RetryPolicy.schedule`."""

    def __init__(self, policy: RetryPolicy, deadline: Optional[Deadline]):
        self.policy = policy
        self.deadline = deadline
        self.attempt = 0          # completed attempts so far
        self._started = policy.clock.now()
        self._delay = policy.base
        self._rng = random.Random(policy.seed)

    def _next_delay(self) -> float:
        if self.policy.backoffs is not None:
            return self.policy.backoffs[self.attempt - 1]
        self._delay = min(self.policy.cap,
                          self._rng.uniform(self.policy.base,
                                            self._delay * 3.0))
        return self._delay

    def give_up(self, retry_after: Optional[float] = None) -> bool:
        """Called after a failed attempt. Returns True when no retry
        budget remains; otherwise sleeps the next backoff (at least
        ``retry_after`` when the server sent one) and returns False."""
        self.attempt += 1
        clock = self.policy.clock
        if self.attempt >= self.policy.max_attempts:
            return True
        wait = self._next_delay()
        if retry_after is not None:
            try:
                wait = max(wait, float(retry_after))
            except (TypeError, ValueError):
                pass
        elapsed = clock.now() - self._started
        if self.policy.budget is not None \
                and elapsed + wait > self.policy.budget:
            return True
        if self.deadline is not None and wait >= self.deadline.remaining():
            return True     # the retry could never finish in time
        try:
            _metrics()["retries"].inc()
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass
        clock.sleep(wait)
        return False


# ---------------------------------------------------------------------------
# Circuit breakers
# ---------------------------------------------------------------------------

class CircuitOpen(Exception):
    """The breaker is open: the dependency is being given time to recover."""


class CircuitBreaker:
    """Closed / open / half-open breaker around one dependency.

    ``failure_threshold`` consecutive failures open the circuit; while
    open, :meth:`allow` refuses instantly (no connect timeouts burned on
    a dead host). After ``reset_timeout`` on the injected clock the
    breaker admits up to ``half_open_max`` concurrent probes: a probe
    success closes the circuit, a probe failure re-opens it and restarts
    the timer. Thread-safe; all transitions are clock-driven, never
    wall-clock-driven, so tests advance a :class:`ManualClock` instead of
    sleeping.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout: float = 30.0, half_open_max: int = 1,
                 clock: Clock = SYSTEM_CLOCK, name: str = ""):
        self.failure_threshold = max(int(failure_threshold), 1)
        self.reset_timeout = float(reset_timeout)
        self.half_open_max = max(int(half_open_max), 1)
        self.clock = clock
        self.name = name
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes = 0
        self.n_opened = 0
        self.n_rejected = 0

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _maybe_half_open_locked(self) -> None:
        if self._state == self.OPEN and \
                self.clock.now() - self._opened_at >= self.reset_timeout:
            self._state = self.HALF_OPEN
            self._probes = 0
            _breaker_event(self.name, self.HALF_OPEN)

    def allow(self) -> bool:
        """May a call proceed right now? Half-open admits a bounded
        number of probes (each must be resolved by record_success /
        record_failure)."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN \
                    and self._probes < self.half_open_max:
                self._probes += 1
                return True
            self.n_rejected += 1
            return False

    def record_success(self) -> None:
        with self._lock:
            was_closed = self._state == self.CLOSED
            self._state = self.CLOSED
            self._failures = 0
            if not was_closed:
                _breaker_event(self.name, self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._trip_locked()     # failed probe: back to open
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._trip_locked()

    def _trip_locked(self) -> None:
        if self._state != self.OPEN:
            self.n_opened += 1
            _breaker_event(self.name, self.OPEN)
        self._state = self.OPEN
        self._opened_at = self.clock.now()
        self._failures = 0

    def call(self, fn: Callable[[], Any]) -> Any:
        """Run ``fn`` through the breaker: :class:`CircuitOpen` when
        refused, success/failure recorded from the outcome."""
        if not self.allow():
            raise CircuitOpen(
                f"circuit {self.name or id(self)} is {self._state}")
        try:
            out = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out


class BreakerBoard:
    """Lazily-created :class:`CircuitBreaker` per key (host, worker url).

    The per-host breaker map the HTTP layers share: hundreds of rows
    targeting one dead host trip its breaker once, and every subsequent
    row is refused in microseconds instead of burning a retry schedule.
    """

    def __init__(self, clock: Clock = SYSTEM_CLOCK, **breaker_kwargs):
        self.clock = clock
        self.breaker_kwargs = breaker_kwargs
        self._breakers: Dict[Any, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def get(self, key: Any) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                br = CircuitBreaker(clock=self.clock, name=str(key),
                                    **self.breaker_kwargs)
                self._breakers[key] = br
            return br

    def states(self) -> Dict[Any, str]:
        with self._lock:
            items = list(self._breakers.items())
        return {k: b.state for k, b in items}

    def reset(self) -> None:
        with self._lock:
            self._breakers.clear()
