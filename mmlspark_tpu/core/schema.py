"""Column metadata: categorical levels and ML column roles.

Capability parity with the reference's column-metadata machinery
(`core/schema/src/main/scala/Categoricals.scala`, `SparkSchema.scala`,
`SchemaConstants.scala`): categorical levels ride along with columns, and
trained models tag their score columns with roles so downstream evaluators
can autodetect them (`ComputeModelStatistics.scala:57`).

Metadata here is a plain JSON-able dict attached per column on a
:class:`~mmlspark_tpu.core.dataframe.DataFrame`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

# ---------------------------------------------------------------------------
# Schema constants (parity: core/schema/src/main/scala/SchemaConstants.scala)
# ---------------------------------------------------------------------------

SCORES_KIND = "scores"
SCORED_LABELS_KIND = "scored_labels"
SCORED_PROBABILITIES_KIND = "scored_probabilities"
LABEL_KIND = "label"

CLASSIFICATION = "classification"
REGRESSION = "regression"

MML_TAG = "mml"  # namespace key inside column metadata


# ---------------------------------------------------------------------------
# Categorical metadata (parity: Categoricals.scala:16,178,295)
# ---------------------------------------------------------------------------

def make_categorical_meta(levels: Sequence[Any], ordinal: bool = False,
                          has_null_level: bool = False) -> Dict[str, Any]:
    """Build categorical metadata recording the distinct levels of a column."""
    return {
        "categorical": True,
        "levels": list(levels),
        "ordinal": bool(ordinal),
        "has_null_level": bool(has_null_level),
    }


def is_categorical(meta: Optional[Dict[str, Any]]) -> bool:
    return bool(meta) and bool(meta.get("categorical"))


def categorical_levels(meta: Optional[Dict[str, Any]]) -> Optional[List[Any]]:
    if not is_categorical(meta):
        return None
    return meta.get("levels")


# ---------------------------------------------------------------------------
# Score-column roles (parity: SparkSchema.scala set/get*ColumnName)
# ---------------------------------------------------------------------------

def make_role_meta(kind: str, model_uid: str, task: Optional[str] = None) -> Dict[str, Any]:
    """Tag a column with an ML role produced by a given model."""
    meta: Dict[str, Any] = {"role": kind, "model_uid": model_uid}
    if task is not None:
        meta["task"] = task
    return meta


def column_role(meta: Optional[Dict[str, Any]]) -> Optional[str]:
    return meta.get("role") if meta else None


def find_column_by_role(df, kind: str, model_uid: Optional[str] = None) -> Optional[str]:
    """Find a column tagged with the given role (optionally for a given model)."""
    for name in df.columns:
        meta = df.get_metadata(name)
        if not meta:
            continue
        if meta.get("role") != kind:
            continue
        if model_uid is not None and meta.get("model_uid") != model_uid:
            continue
        return name
    return None


def find_unused_column_name(prefix: str, df) -> str:
    """Parity: DatasetExtensions.findUnusedColumnName."""
    name = prefix
    i = 0
    existing = set(df.columns)
    while name in existing:
        i += 1
        name = f"{prefix}_{i}"
    return name


# ---------------------------------------------------------------------------
# Feature-vector slot names (parity: vector-assembler attribute metadata)
# ---------------------------------------------------------------------------

def make_features_meta(slot_names: Sequence[str],
                       categorical_slots: Optional[Dict[str, List[Any]]] = None) -> Dict[str, Any]:
    """Metadata for an assembled feature-vector column.

    ``categorical_slots`` maps slot name -> levels, preserving categorical
    information through assembly (parity: FastVectorAssembler keeping
    categorical metadata up front, `FastVectorAssembler.scala:23`).
    """
    return {
        "feature_names": list(slot_names),
        "categorical_slots": dict(categorical_slots or {}),
    }


def categorical_slot_indexes(meta: Optional[Dict[str, Any]]) -> List[int]:
    """Indexes of categorical slots inside an assembled feature vector."""
    if not meta:
        return []
    names = meta.get("feature_names") or []
    cats = meta.get("categorical_slots") or {}
    return [i for i, n in enumerate(names) if n in cats]
