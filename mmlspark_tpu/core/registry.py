"""Global stage registry.

Every concrete stage class auto-registers by qualified name when defined.
This powers (a) persistence — ``load`` resolves the class to instantiate —
and (b) generic fuzzing-style test sweeps over all stages, the role
reflection over ``Wrappable`` classes plays in the reference
(`core/utils/src/main/scala/JarLoadingUtils.scala`, `Fuzzing.scala`).
"""

from __future__ import annotations

import importlib
from typing import Dict, Type

STAGE_REGISTRY: Dict[str, Type] = {}


def register(cls: Type) -> None:
    STAGE_REGISTRY[f"{cls.__module__}.{cls.__qualname__}"] = cls


def resolve(qualname: str) -> Type:
    if qualname not in STAGE_REGISTRY:
        module = qualname.rsplit(".", 1)[0]
        importlib.import_module(module)
    if qualname not in STAGE_REGISTRY:
        raise KeyError(f"unknown stage class {qualname!r}")
    return STAGE_REGISTRY[qualname]


def all_stages() -> Dict[str, Type]:
    """Import the full framework, then return every public registered stage."""
    import mmlspark_tpu.all  # noqa: F401  (imports every stage module)
    return {k: v for k, v in STAGE_REGISTRY.items()
            if not v.__name__.startswith("_")
            and v.__module__.startswith("mmlspark_tpu")}
