"""Namespaced runtime configuration.

Parity: `core/env/src/main/scala/Configuration.scala:18-50` — the
reference layers typesafe-config namespaces (``mmlspark.sdk``, ``.cntk``,
``.tlc``) over defaults. Here three layers, lowest to highest
precedence:

1. code defaults registered via :func:`register_defaults`,
2. a JSON file named by ``$MMLSPARK_TPU_CONFIG``,
3. environment variables ``MMLSPARK_TPU_<NAMESPACE>_<KEY>`` (upper-case,
   values parsed as JSON when possible, else kept as strings).

Usage::

    from mmlspark_tpu.core.config import MMLConfig
    cfg = MMLConfig.get("serving")      # the namespace dict
    port = cfg.get("port", 8890)
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict

_lock = threading.Lock()
_defaults: Dict[str, Dict[str, Any]] = {}
_ENV_PREFIX = "MMLSPARK_TPU_"
_RESERVED = {"CONFIG", "NATIVE", "TEST", "EXAMPLE", "DRYRUN"}  # non-config vars


def register_defaults(namespace: str, values: Dict[str, Any]) -> None:
    """Layer-1 defaults for a namespace (later calls merge over earlier)."""
    with _lock:
        _defaults.setdefault(namespace, {}).update(values)


def _file_layer() -> Dict[str, Dict[str, Any]]:
    path = os.environ.get(_ENV_PREFIX + "CONFIG")
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    return {str(ns): dict(vals) for ns, vals in data.items()}


def _env_layer(namespace: str) -> Dict[str, Any]:
    if namespace.upper() in _RESERVED:
        # framework control variables (MMLSPARK_TPU_NATIVE_DIR,
        # MMLSPARK_TPU_TEST_TPU, ...) are not user config
        return {}
    prefix = _ENV_PREFIX + namespace.upper() + "_"
    out: Dict[str, Any] = {}
    for key, raw in os.environ.items():
        if not key.startswith(prefix):
            continue
        name = key[len(prefix):].lower()
        try:
            out[name] = json.loads(raw)
        except ValueError:
            out[name] = raw
    return out


class MMLConfig:
    """Read-side API (parity: ``MMLConfig.get()``)."""

    @staticmethod
    def get(namespace: str) -> Dict[str, Any]:
        """The merged config dict for ``namespace``."""
        with _lock:
            out = dict(_defaults.get(namespace, {}))
        out.update(_file_layer().get(namespace, {}))
        out.update(_env_layer(namespace))
        return out
