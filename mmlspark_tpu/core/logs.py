"""Namespaced logger factory.

Parity: `core/env/src/main/scala/Logging.scala:14-22` — per-namespace
log4j2 loggers under one root. Here stdlib logging under the
``mmlspark_tpu`` root, with the level configurable via the ``logging``
config namespace (``MMLSPARK_TPU_LOGGING_LEVEL=DEBUG`` or the config
file — see ``core/config.py``).

Observability extensions:

* ``MMLSPARK_TPU_LOGGING_FORMAT=json`` (config key ``logging.format``)
  switches every record to one structured JSON object per line — the
  shape log pipelines (Loki, Stackdriver, `jq`) ingest without a parse
  regex.
* every record carries the ambient trace id
  (:func:`mmlspark_tpu.core.telemetry.current_trace_id`) and span name
  (:func:`mmlspark_tpu.core.tracing.current_span_name`): a handler
  filter stamps ``record.trace_id`` / ``record.span_name``, the JSON
  format emits both as fields, and the plain format appends
  ``trace=<id> span=<name>`` only when actually bound — grep one
  serving request's id across ingress, dispatch, and egress log lines
  and see which stage each line came from.
"""

from __future__ import annotations

import json as _json
import logging as _logging
import threading as _threading
import time as _time
from collections import deque as _deque

_ROOT = "mmlspark_tpu"
_configured = False


class _TraceFilter(_logging.Filter):
    """Stamp the ambient trace id AND span name onto every record at
    emit time — a log line inside a serving dispatch reads
    ``trace=<id> span=dispatch``, so grep finds not just the request
    but the stage it was in."""

    def filter(self, record: _logging.LogRecord) -> bool:
        from mmlspark_tpu.core.telemetry import current_trace_id
        from mmlspark_tpu.core.tracing import current_span_name
        record.trace_id = current_trace_id() or "-"
        record.span_name = current_span_name() or "-"
        return True


def _record_trace_id(record: _logging.LogRecord):
    tid = getattr(record, "trace_id", None)
    if tid is None:
        # formatter used without the handler filter (tests formatting a
        # bare record): resolve directly
        from mmlspark_tpu.core.telemetry import current_trace_id
        tid = current_trace_id() or "-"
    return tid


def _record_span_name(record: _logging.LogRecord):
    name = getattr(record, "span_name", None)
    if name is None:
        from mmlspark_tpu.core.tracing import current_span_name
        name = current_span_name() or "-"
    return name


class _PlainFormatter(_logging.Formatter):
    """The historical plain format, plus ``trace=<id>`` / ``span=<name>``
    when bound (no trailing noise for untraced records)."""

    def __init__(self):
        super().__init__("%(asctime)s %(name)s %(levelname)s: %(message)s")

    def format(self, record: _logging.LogRecord) -> str:
        out = super().format(record)
        tid = _record_trace_id(record)
        if tid and tid != "-":
            out += f" trace={tid}"
        span = _record_span_name(record)
        if span and span != "-":
            out += f" span={span}"
        return out


class _JsonFormatter(_logging.Formatter):
    """One JSON object per line: ts/level/logger/message/trace_id/span
    (+ exc when an exception rode the record)."""

    def format(self, record: _logging.LogRecord) -> str:
        out = {
            "ts": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
            "trace_id": _record_trace_id(record),
            "span": _record_span_name(record),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return _json.dumps(out, default=str)


def make_formatter(fmt: str = "plain") -> _logging.Formatter:
    """The formatter for a ``logging.format`` config value (``plain``
    or ``json``; unknown values fall back to plain)."""
    return _JsonFormatter() if str(fmt).lower() == "json" \
        else _PlainFormatter()


def _ensure_root() -> None:
    global _configured
    if _configured:
        return
    from mmlspark_tpu.core.config import MMLConfig
    cfg = MMLConfig.get("logging")
    root = _logging.getLogger(_ROOT)
    if not root.handlers:
        handler = _logging.StreamHandler()
        handler.setFormatter(make_formatter(cfg.get("format", "plain")))
        handler.addFilter(_TraceFilter())
        root.addHandler(handler)
        root.propagate = False
    level = str(cfg.get("level", "INFO")).upper()
    root.setLevel(getattr(_logging, level, _logging.INFO))
    _configured = True


def reconfigure() -> None:
    """Re-read the ``logging`` config namespace (level + format) so a
    long-lived process can flip to JSON logs without a restart. The
    installed handler's formatter is swapped IN PLACE (one attribute
    assignment) rather than removed-and-readded — concurrent request
    threads never hit a handler-less, non-propagating root logger, so
    no record is dropped mid-flip."""
    global _configured
    root = _logging.getLogger(_ROOT)
    if not root.handlers:
        _configured = False      # nothing installed: next get_logger runs
        return                   # the full _ensure_root
    from mmlspark_tpu.core.config import MMLConfig
    cfg = MMLConfig.get("logging")
    for h in root.handlers:
        h.setFormatter(make_formatter(cfg.get("format", "plain")))
    level = str(cfg.get("level", "INFO")).upper()
    root.setLevel(getattr(_logging, level, _logging.INFO))


def get_logger(namespace: str) -> _logging.Logger:
    """Logger at ``mmlspark_tpu.<namespace>`` (created on first use)."""
    _ensure_root()
    return _logging.getLogger(f"{_ROOT}.{namespace}")


class LogRing(_logging.Handler):
    """Bounded in-memory ring of the last N log records.

    The postmortem plane's log surface: a worker serves the ring at
    ``GET /logs?trace=<id>&level=<name>`` and the incident bundle
    snapshots the *same* ring — what the operator greps and what the
    bundle preserves are one buffer, not two codepaths.

    Records are stored as plain dicts (``ts``/``level``/``levelno``/
    ``logger``/``message``/``trace``/``span``) at emit time, so reading
    the ring never touches live ``LogRecord`` objects. ``level`` is the
    handler's severity floor (records below it never enter the ring);
    :meth:`records` can filter further by trace id and level name.
    ``emit`` swallows its own errors — a broken record loses one line,
    never the caller.
    """

    def __init__(self, capacity: int = 2048,
                 level: int = _logging.INFO):
        super().__init__(level=level)
        self.capacity = int(capacity)
        self._ring = _deque(maxlen=self.capacity)
        self._rlock = _threading.Lock()
        self.n_emitted = 0
        self.addFilter(_TraceFilter())

    def emit(self, record: _logging.LogRecord) -> None:
        try:
            entry = {
                "ts": getattr(record, "created", None) or _time.time(),
                "level": record.levelname,
                "levelno": record.levelno,
                "logger": record.name,
                "message": record.getMessage(),
                "trace": _record_trace_id(record),
                "span": _record_span_name(record),
            }
            if record.exc_info:
                try:
                    entry["exc"] = _logging.Formatter().formatException(
                        record.exc_info)
                except Exception:
                    pass
            with self._rlock:
                self._ring.append(entry)
                self.n_emitted += 1
        except Exception:       # pragma: no cover - defensive
            pass

    def records(self, trace: str = None, level: str = None,
                limit: int = None) -> list:
        """Newest-last snapshot, optionally filtered by trace id and/or
        minimum level name; ``limit`` keeps only the newest N."""
        floor = None
        if level:
            floor = getattr(_logging, str(level).upper(), None)
        with self._rlock:
            out = list(self._ring)
        if trace:
            out = [r for r in out if r.get("trace") == trace]
        if floor is not None:
            out = [r for r in out if r.get("levelno", 0) >= floor]
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def status(self) -> dict:
        with self._rlock:
            return {"capacity": self.capacity, "len": len(self._ring),
                    "emitted": self.n_emitted,
                    "floor": _logging.getLevelName(self.level)}


_log_ring: LogRing = None
_ring_lock = _threading.Lock()


def install_log_ring(capacity: int = 2048,
                     level: int = _logging.INFO) -> LogRing:
    """Attach one process-wide :class:`LogRing` to the ``mmlspark_tpu``
    root logger (idempotent — repeated calls return the same ring, so
    every :class:`~mmlspark_tpu.serving.server.ServingServer` in a
    process shares one buffer, matching the shared stream handler)."""
    global _log_ring
    with _ring_lock:
        if _log_ring is None:
            _ensure_root()
            ring = LogRing(capacity=capacity, level=level)
            _logging.getLogger(_ROOT).addHandler(ring)
            _log_ring = ring
        return _log_ring


def get_log_ring() -> LogRing:
    """The installed ring, or ``None`` before :func:`install_log_ring`."""
    return _log_ring
