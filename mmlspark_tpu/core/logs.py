"""Namespaced logger factory.

Parity: `core/env/src/main/scala/Logging.scala:14-22` — per-namespace
log4j2 loggers under one root. Here stdlib logging under the
``mmlspark_tpu`` root, with the level configurable via the ``logging``
config namespace (``MMLSPARK_TPU_LOGGING_LEVEL=DEBUG`` or the config
file — see ``core/config.py``).
"""

from __future__ import annotations

import logging as _logging

_ROOT = "mmlspark_tpu"
_configured = False


def _ensure_root() -> None:
    global _configured
    if _configured:
        return
    from mmlspark_tpu.core.config import MMLConfig
    root = _logging.getLogger(_ROOT)
    if not root.handlers:
        handler = _logging.StreamHandler()
        handler.setFormatter(_logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s: %(message)s"))
        root.addHandler(handler)
        root.propagate = False
    level = str(MMLConfig.get("logging").get("level", "INFO")).upper()
    root.setLevel(getattr(_logging, level, _logging.INFO))
    _configured = True


def get_logger(namespace: str) -> _logging.Logger:
    """Logger at ``mmlspark_tpu.<namespace>`` (created on first use)."""
    _ensure_root()
    return _logging.getLogger(f"{_ROOT}.{namespace}")
