"""Always-on sampling CPU profiler: the first half of the postmortem plane.

PRs 18-19 built the *detectors* (SLO burn-rate alerts, EWMA+MAD anomaly
detection over the embedded TSDB); this module captures the *evidence*.
A background daemon thread samples ``sys._current_frames()`` at a
configurable rate (default 50 hz) into a bounded ring of collapsed
stacks, so that when something fires the CPU history around the firing
instant is already in memory — no "reproduce it with a profiler
attached" step.

Design constraints, in order:

* **Bounded and cheap.** Stacks are interned (each distinct collapsed
  stack is stored once; the ring holds small integer ids), the ring is
  a ``deque(maxlen=...)`` sized to ``hz * retention_s`` samples, and the
  intern table is capped — a pathological workload degrades to an
  ``<overflow>`` bucket, never to unbounded memory. The per-sample cost
  is perf-gated in ``tests/test_postmortem.py`` and the end-to-end rps
  overhead in ``bench.py profiler_overhead_v1`` (<3%).
* **Stage attribution.** The serving data plane names its threads
  (``serving-collector``, ``serving-executor``, ``serving-encoder-N``,
  ``decode-scheduler``, ``tsdb-recorder``, ...); samples are bucketed
  into pipeline *stages* by thread-name prefix, so a profile answers
  "which stage is burning CPU" before you read a single frame.
* **Windowed queries.** Every sample is timestamped by an injectable
  :class:`~mmlspark_tpu.core.resilience.Clock`, so ``GET
  /profile/cpu?window_s=N`` aggregates exactly the last N seconds, the
  incident bundle can ask for [firing-60s, firing+30s], and tests
  drive a :class:`~mmlspark_tpu.core.resilience.ManualClock` through
  deterministic goldens.
* **Differential profiles.** ``?baseline_s=M`` diffs the last
  ``window_s`` against the ``baseline_s`` immediately before it and
  ranks frames by how much *hotter* they got (share-of-samples delta) —
  the question an operator actually has during a regression is not
  "what is hot" but "what is hot *now* that wasn't".

Exports: collapsed flamegraph text (one ``stack count`` line per
distinct stack, the format every flamegraph renderer ingests), Chrome
``trace_event`` JSON (consecutive identical stacks coalesced into
duration slices per thread lane — load in Perfetto next to the request
traces from :mod:`mmlspark_tpu.core.tracing`), and a JSON top-table for
terminals (``tools/trace_dump.py --profile``).
"""

from __future__ import annotations

import json
import sys
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from mmlspark_tpu.core.resilience import Clock, SYSTEM_CLOCK

# Thread-name prefix -> pipeline stage. Ordered: first match wins, so
# more specific prefixes go first. Anything unmatched lands in "other"
# (and the main thread in "main") — attribution degrades, never errors.
STAGE_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("serving-collector", "collector"),
    ("serving-executor", "dispatch"),
    ("serving-encoder", "encoder"),
    ("serving-journal", "journal"),
    ("decode-scheduler", "decode-step"),
    ("rollout-", "rollout"),
    ("tsdb-recorder", "recorder"),
    ("slo-notify", "alerting"),
    ("incident-capture", "incidents"),
    ("-frontend-", "frontend"),
    ("ThreadPoolExecutor", "pool"),
    ("MainThread", "main"),
)


def stage_for_thread(name: str) -> str:
    """Pipeline stage for a thread name (prefix/substring match against
    :data:`STAGE_PREFIXES`; unmatched names attribute to ``other``)."""
    for prefix, stage in STAGE_PREFIXES:
        if name.startswith(prefix) or (prefix[0] == "-" and prefix in name):
            return stage
    return "other"


def _frame_label(frame) -> str:
    """One collapsed-stack frame: ``<module-ish path>:<func>:<line>``.

    The path is trimmed to the last two components — enough to
    disambiguate (``serving/server.py`` vs ``core/tsdb.py``) without
    bloating the intern table with absolute prefixes.
    """
    code = frame.f_code
    fn = code.co_filename.replace("\\", "/")
    parts = fn.rsplit("/", 2)
    short = "/".join(parts[-2:]) if len(parts) >= 2 else fn
    return f"{short}:{code.co_name}:{frame.f_lineno}"


class SamplingProfiler:
    """Bounded ring of timestamped, interned, collapsed stacks.

    ``start()`` launches the sampling daemon; with a real clock each
    tick calls :meth:`sample_once`. Tests bypass the thread entirely
    and feed :meth:`record_stacks` under a ``ManualClock``.
    """

    def __init__(self, hz: float = 50.0, retention_s: float = 180.0,
                 max_depth: int = 48, max_stacks: int = 8192,
                 clock: Clock = SYSTEM_CLOCK):
        self.hz = max(0.5, float(hz))
        self.retention_s = float(retention_s)
        self.max_depth = int(max_depth)
        self.max_stacks = int(max_stacks)
        self.clock = clock
        self._lock = threading.Lock()
        # sample = (ts, ((tid, stack_id), ...))
        cap = max(16, int(self.hz * self.retention_s))
        self._ring: deque = deque(maxlen=cap)
        self._stack_ids: Dict[str, int] = {}      # collapsed str -> id
        self._stacks: List[str] = []              # id -> collapsed str
        self._thread_names: Dict[int, str] = {}   # ident -> last name
        self._overflow_id: Optional[int] = None
        self.n_samples = 0
        self.n_overflow = 0
        self.ewma_sample_ms = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- capture ------------------------------------------------------

    def _intern(self, collapsed: str) -> int:
        sid = self._stack_ids.get(collapsed)
        if sid is not None:
            return sid
        if len(self._stacks) >= self.max_stacks:
            # Intern table full: every new distinct stack degrades to
            # one shared overflow bucket instead of growing memory.
            self.n_overflow += 1
            if self._overflow_id is None:
                self._overflow_id = len(self._stacks)
                self._stacks.append("<overflow>")
                self._stack_ids["<overflow>"] = self._overflow_id
            return self._overflow_id
        sid = len(self._stacks)
        self._stacks.append(collapsed)
        self._stack_ids[collapsed] = sid
        return sid

    def record_stacks(self, now: float,
                      stacks: Sequence[Tuple[int, str, Sequence[str]]]
                      ) -> None:
        """Append one sample: ``stacks`` is ``[(tid, thread_name,
        (root_frame, ..., leaf_frame)), ...]``. Public so tests can
        script deterministic timelines without a sampling thread."""
        with self._lock:
            entry = []
            for tid, name, frames in stacks:
                self._thread_names[tid] = name
                collapsed = ";".join(frames) if frames else "<idle>"
                entry.append((tid, self._intern(collapsed)))
            self._ring.append((now, tuple(entry)))
            self.n_samples += 1

    def sample_once(self) -> float:
        """Take one sample of every live thread; returns the sample
        cost in milliseconds (feeds the EWMA the perf gate reads)."""
        t0 = self.clock.now()
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()
                 if t.ident is not None}
        stacks: List[Tuple[int, str, Sequence[str]]] = []
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            frames: List[str] = []
            depth = 0
            f = frame
            while f is not None and depth < self.max_depth:
                frames.append(_frame_label(f))
                f = f.f_back
                depth += 1
            frames.reverse()          # root-first, flamegraph order
            stacks.append((tid, names.get(tid, f"tid-{tid}"), frames))
        self.record_stacks(t0, stacks)
        cost_ms = (self.clock.now() - t0) * 1000.0
        self.ewma_sample_ms += 0.05 * (cost_ms - self.ewma_sample_ms)
        return cost_ms

    def _run(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.is_set():
            try:
                self.sample_once()
            except Exception:
                # Sampling must never take the process down; a corrupt
                # frame walk loses one tick, not the profiler.
                pass
            self._stop.wait(interval)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cpu-profiler")
        self._thread.start()

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=5.0)
        self._thread = None

    # -- queries ------------------------------------------------------

    def _window(self, t0: float, t1: float):
        """Samples with t0 <= ts <= t1 (snapshot under the lock)."""
        with self._lock:
            return [s for s in self._ring if t0 <= s[0] <= t1], \
                list(self._stacks), dict(self._thread_names)

    def _bounds(self, window_s: float, now: Optional[float]
                ) -> Tuple[float, float]:
        end = self.clock.now() if now is None else now
        return end - float(window_s), end

    def collapsed_between(self, t0: float, t1: float,
                          by_stage: bool = True) -> Dict[str, int]:
        """``{collapsed_stack: sample_count}`` over [t0, t1]. With
        ``by_stage`` each stack is prefixed ``<stage>;`` so flamegraphs
        show one lane per pipeline stage."""
        samples, stacks, names = self._window(t0, t1)
        counts: Dict[str, int] = {}
        for _, entries in samples:
            for tid, sid in entries:
                stack = stacks[sid]
                if by_stage:
                    stage = stage_for_thread(names.get(tid, ""))
                    stack = f"{stage};{stack}"
                counts[stack] = counts.get(stack, 0) + 1
        return counts

    def render_collapsed(self, window_s: float,
                         now: Optional[float] = None) -> str:
        """Folded flamegraph text: one ``stack count`` line per
        distinct stack, count-descending."""
        t0, t1 = self._bounds(window_s, now)
        counts = self.collapsed_between(t0, t1)
        lines = [f"{stack} {n}" for stack, n in
                 sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))]
        return "\n".join(lines) + ("\n" if lines else "")

    def profile_between(self, t0: float, t1: float,
                        top: int = 30) -> Dict:
        """Structured window summary: totals, per-stage sample counts,
        and the top collapsed stacks — the JSON shape ``GET
        /profile/cpu`` serves by default."""
        samples, stacks, names = self._window(t0, t1)
        stage_counts: Dict[str, int] = {}
        stack_counts: Dict[str, int] = {}
        total = 0
        for _, entries in samples:
            for tid, sid in entries:
                total += 1
                stage = stage_for_thread(names.get(tid, ""))
                stage_counts[stage] = stage_counts.get(stage, 0) + 1
                stack_counts[stacks[sid]] = stack_counts.get(
                    stacks[sid], 0) + 1
        top_stacks = sorted(stack_counts.items(),
                            key=lambda kv: (-kv[1], kv[0]))[:top]
        return {
            "window": {"start": t0, "end": t1,
                       "seconds": max(0.0, t1 - t0)},
            "hz": self.hz,
            "samples": len(samples),
            "thread_samples": total,
            "stages": dict(sorted(stage_counts.items(),
                                  key=lambda kv: -kv[1])),
            "top_stacks": [{"stack": s, "count": n,
                            "share": (n / total) if total else 0.0}
                           for s, n in top_stacks],
        }

    def profile(self, window_s: float, now: Optional[float] = None,
                top: int = 30) -> Dict:
        t0, t1 = self._bounds(window_s, now)
        return self.profile_between(t0, t1, top=top)

    # -- differential -------------------------------------------------

    def _frame_shares(self, t0: float, t1: float) -> Tuple[Dict[str, int],
                                                           int]:
        """Inclusive per-frame counts: a frame is counted once per
        thread-sample it appears in, so shares are comparable across
        windows regardless of stack depth."""
        samples, stacks, _ = self._window(t0, t1)
        counts: Dict[str, int] = {}
        total = 0
        for _, entries in samples:
            for _, sid in entries:
                total += 1
                for frame in set(stacks[sid].split(";")):
                    counts[frame] = counts.get(frame, 0) + 1
        return counts, total

    def diff(self, window_s: float, baseline_s: float,
             now: Optional[float] = None, top: int = 20) -> Dict:
        """Differential profile: the last ``window_s`` vs the
        ``baseline_s`` immediately before it. Frames ranked by
        share-of-samples delta — "which frames got hotter"."""
        end = self.clock.now() if now is None else now
        cur0, cur1 = end - float(window_s), end
        base0, base1 = cur0 - float(baseline_s), cur0
        cur, cur_total = self._frame_shares(cur0, cur1)
        base, base_total = self._frame_shares(base0, base1)
        rows = []
        for frame in set(cur) | set(base):
            cs = (cur.get(frame, 0) / cur_total) if cur_total else 0.0
            bs = (base.get(frame, 0) / base_total) if base_total else 0.0
            rows.append({"frame": frame,
                         "cur_count": cur.get(frame, 0),
                         "base_count": base.get(frame, 0),
                         "cur_share": cs, "base_share": bs,
                         "delta_share": cs - bs})
        rows.sort(key=lambda r: -r["delta_share"])
        return {
            "window": {"start": cur0, "end": cur1},
            "baseline": {"start": base0, "end": base1},
            "cur_samples": cur_total, "base_samples": base_total,
            "hotter": [r for r in rows if r["delta_share"] > 0][:top],
            "colder": [r for r in reversed(rows)
                       if r["delta_share"] < 0][:top],
        }

    # -- chrome trace-event export ------------------------------------

    def chrome_trace_between(self, t0: float, t1: float) -> Dict:
        """Chrome ``trace_event`` JSON: per-thread lanes, consecutive
        identical stacks coalesced into one duration slice named after
        the leaf frame (full stack in args). Loads in Perfetto /
        chrome://tracing next to the request traces."""
        samples, stacks, names = self._window(t0, t1)
        events: List[Dict] = []
        tick_us = 1e6 / self.hz
        # Per thread: run-length encode (stack_id) over time.
        open_slices: Dict[int, Dict] = {}  # tid -> {sid, start, last}
        seen_tids: Dict[int, bool] = {}

        def _close(tid: int) -> None:
            sl = open_slices.pop(tid, None)
            if sl is None:
                return
            stack = stacks[sl["sid"]]
            leaf = stack.rsplit(";", 1)[-1]
            events.append({
                "name": leaf, "ph": "X", "cat": "cpu",
                "ts": sl["start"] * 1e6,
                "dur": max(tick_us, (sl["last"] - sl["start"]) * 1e6
                           + tick_us),
                "pid": 1, "tid": tid,
                "args": {"stack": stack,
                         "stage": stage_for_thread(names.get(tid, ""))},
            })

        for ts, entries in samples:
            live = {}
            for tid, sid in entries:
                live[tid] = sid
                seen_tids[tid] = True
                sl = open_slices.get(tid)
                if sl is not None and sl["sid"] == sid:
                    sl["last"] = ts
                else:
                    if sl is not None:
                        _close(tid)
                    open_slices[tid] = {"sid": sid, "start": ts,
                                        "last": ts}
            for tid in [t for t in open_slices if t not in live]:
                _close(tid)
        for tid in list(open_slices):
            _close(tid)
        for tid in seen_tids:
            events.append({"name": "thread_name", "ph": "M", "pid": 1,
                           "tid": tid,
                           "args": {"name": names.get(tid,
                                                      f"tid-{tid}")}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def chrome_trace(self, window_s: float,
                     now: Optional[float] = None) -> Dict:
        t0, t1 = self._bounds(window_s, now)
        return self.chrome_trace_between(t0, t1)

    # -- introspection ------------------------------------------------

    def status(self) -> Dict:
        with self._lock:
            return {
                "running": self._thread is not None,
                "hz": self.hz,
                "retention_s": self.retention_s,
                "samples": self.n_samples,
                "ring_len": len(self._ring),
                "ring_cap": self._ring.maxlen,
                "distinct_stacks": len(self._stacks),
                "max_stacks": self.max_stacks,
                "overflow": self.n_overflow,
                "ewma_sample_ms": round(self.ewma_sample_ms, 4),
            }

    def render_json(self, payload: Dict) -> bytes:
        return json.dumps(payload).encode("utf-8")
