"""Span tracing + flight recorder: per-request span trees, tail capture.

PR 3's trace ids made a request *correlatable* (one ``X-Trace-Id``
across logs, journal lines, egress headers); this module makes it
*inspectable*. The TPU-pod scaling literature (MLPerf on TPU-v3 pods,
arxiv 1909.09756; TensorFlow's timeline-driven performance work, arxiv
1605.08695) is unambiguous that step- and op-level *timelines*, not
aggregate counters, are what make straggler and pipeline-bubble
diagnosis tractable — so every layer that already carries a trace id
now also records :class:`Span` s into a per-process **flight
recorder**:

* a :class:`Span` is name + start/end (on an injectable
  :class:`~mmlspark_tpu.core.resilience.Clock`) + attributes + status,
  nested parent->child; the ambient span rides a contextvar next to
  the trace-id one, and (exactly like trace ids) is handed across the
  serving stage threads on the work item, never through the contextvar;
* finished spans land in a **lock-striped ring buffer**
  (:class:`FlightRecorder`): recording is a clock read + one striped
  append (~hundreds of ns, budget-tested like the metrics hot path),
  and the stripe is chosen by trace id so one trace's spans colocate
  and gathering them scans a single stripe;
* **tail-based capture**: when a ROOT span finishes, the completed
  trace is retained in a bounded LRU store only if it was slow (root
  duration over the per-route threshold) or ended non-ok
  (error/shed/deadline/timeout) — everything else ages out of the ring
  unexamined. ``GET /trace/<id>`` serves a retained trace's span tree,
  ``GET /traces`` lists the store, and :func:`to_perfetto` renders any
  retained trace as Chrome ``trace_event`` JSON for
  ``chrome://tracing`` / Perfetto (``tools/trace_dump.py``).

Histogram exemplars close the loop from the *other* direction: every
:class:`~mmlspark_tpu.core.telemetry.Histogram` bucket remembers the
last traced observation's trace id and exposes it in the Prometheus
exposition (OpenMetrics ``# {trace_id="..."}`` syntax), so a p99
outlier bucket links straight to its captured trace.

Usage::

    from mmlspark_tpu.core.tracing import TRACER

    with TRACER.span("load", route="batch") as sp:
        with TRACER.span("parse", rows=1000):
            parse()

    TRACER.get_trace(sp.trace_id)       # retained iff slow or non-ok

Caveat — trace ids are the correlation key everywhere here (ring
stripe, gather, capture store), and serving adopts inbound
``X-Trace-Id`` headers verbatim (the PR 3 contract): a buggy client
that reuses one id across many requests will colocate all of them on
one stripe and, when any of them is captured, produce a merged tree of
every same-id span still in the ring. Ids must be unique per logical
request — that is the protocol, not something this layer can repair.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import re
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from mmlspark_tpu.core.resilience import Clock, SYSTEM_CLOCK
from mmlspark_tpu.core.telemetry import (
    TRACE_HEADER, current_trace_id, new_trace_id, sanitize_trace_id,
)
# the clean-id regex itself (not just the sanitize wrapper): ingress
# extraction fast-paths already-clean ids with one fullmatch
from mmlspark_tpu.core.telemetry import _TRACE_ID_OK_RE
# the raw trace-id contextvar (not the trace_context contextmanager):
# span scopes bind trace + span together on the hot path, and a
# generator-contextmanager pair per span would triple the span budget
from mmlspark_tpu.core.telemetry import _trace_id

__all__ = [
    "Span", "FlightRecorder", "Tracer", "TRACER",
    "current_span", "current_span_name", "ambient_tracer",
    "span_tree", "to_perfetto", "dump_perfetto",
    "PARENT_SPAN_HEADER", "format_span_id", "parse_span_id",
    "inject_span_context", "extract_span_context",
    "merge_traces", "AdaptiveThreshold",
]

_SPAN_COUNTER = itertools.count(1)

# span ids must stay unambiguous when traces MERGE across processes
# (the coordinator stitches N workers' span lists into one tree, and a
# worker root's parent_id names a span in the CALLER's process): plain
# per-process counters would collide at 1, so every process draws its
# ids from a random 63-bit base + the counter — still one integer add
# per span, still monotonic within the process, collision probability
# across a fleet ~2^-39 even at a billion spans per worker
_SPAN_ID_BASE = uuid.uuid4().int & 0x7FFF_FFFF_FF00_0000

_current_span: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("mmlspark_tpu_span", default=None)

# the tracer that bound the ambient span: layers that record spans from
# arbitrary call sites (pipeline stages, HTTP egress, trainer) resolve
# it via ambient_tracer(), so a server wired with a PRIVATE tracer
# captures its model-internal spans too — recording those through the
# global TRACER would parent them correctly but land them in the wrong
# recorder, and the private capture would silently miss them
_current_tracer: "contextvars.ContextVar[Optional[Tracer]]" = \
    contextvars.ContextVar("mmlspark_tpu_tracer", default=None)


def current_span() -> Optional["Span"]:
    """The span bound to this context, or None outside any span."""
    return _current_span.get()


def current_span_name() -> Optional[str]:
    sp = _current_span.get()
    return sp.name if sp is not None else None


def ambient_tracer() -> "Tracer":
    """The tracer that bound the ambient span, falling back to the
    process-wide :data:`TRACER` — what framework layers record
    through."""
    return _current_tracer.get() or TRACER


class Span:
    """One timed operation in a trace.

    ``t0``/``t1`` are seconds on the owning tracer's clock (monotonic
    by default); ``thread`` is the recording thread's ident, so the
    Perfetto export lays the serving pipeline's collector/executor/
    encoder work out on separate lanes. Spans are plain mutable records
    — the tracer, not the span, owns lifecycle (:meth:`Tracer.finish`).

    Hot-path notes (the <2 us/span bench budget, ``tracing_overhead_v1``):
    span ids are plain process-unique ints (no per-span string format),
    and ``attrs`` stays ``None`` until someone actually attaches one —
    most child spans never allocate a dict.
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "t0", "t1", "status", "attrs", "thread", "remote",
                 "force")

    def __init__(self, name: str, trace_id: str,
                 parent_id: Optional[int], t0: float,
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _SPAN_ID_BASE + next(_SPAN_COUNTER)
        self.parent_id = parent_id
        self.t0 = t0
        self.t1: Optional[float] = None
        self.status = "ok"
        self.attrs: Optional[Dict[str, Any]] = attrs
        self.thread = threading.get_ident()
        # True when parent_id names a span in ANOTHER process (adopted
        # from an inbound header): the span is still a capture root
        # locally — its real parent finishes elsewhere
        self.remote = False
        # force-capture (the X-Capture wire hint): a forced ROOT is
        # retained regardless of the route's slow-trace threshold, and
        # the flag inherits parent -> child so egress spans know to
        # propagate the hint on the wire
        self.force = False

    @property
    def duration_ms(self) -> float:
        return ((self.t1 or self.t0) - self.t0) * 1000.0

    def set_attr(self, key: str, value: Any) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def to_dict(self, origin: float = 0.0) -> Dict[str, Any]:
        """JSON-able record; times relative to ``origin`` (the trace's
        first span start) so exported trees read from 0."""
        d = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ms": round((self.t0 - origin) * 1000.0, 3),
            "duration_ms": round(self.duration_ms, 3),
            "status": self.status,
            "attrs": self.attrs or {},
            "thread": self.thread,
        }
        if self.remote:
            d["remote"] = True
        if self.force:
            d["forced"] = True
        return d

    def __repr__(self) -> str:
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"id={self.span_id}, parent={self.parent_id}, "
                f"status={self.status})")


class _SpanScope:
    """``with tracer.span(...)``: binds the span + its trace id + its
    tracer on enter, finishes (status ``error`` on exception) on
    exit."""

    __slots__ = ("_tracer", "span", "_tok_span", "_tok_trace",
                 "_tok_tracer")

    def __init__(self, tracer: "Tracer", span: "Span"):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> "Span":
        self._tok_span = _current_span.set(self.span)
        self._tok_trace = _trace_id.set(self.span.trace_id)
        self._tok_tracer = _current_tracer.set(self._tracer)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        _current_tracer.reset(self._tok_tracer)
        _trace_id.reset(self._tok_trace)
        _current_span.reset(self._tok_span)
        self._tracer.finish(self.span,
                            status="error" if exc_type is not None
                            else None)
        return False


class _BindScope:
    """``with tracer.bind(span)``: ambient span + trace id + tracer
    for the block; ``None`` span binds nothing (no-op)."""

    __slots__ = ("_tracer", "span", "_tok_span", "_tok_trace",
                 "_tok_tracer")

    def __init__(self, tracer: "Tracer", span: Optional["Span"]):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> Optional["Span"]:
        if self.span is not None:
            self._tok_span = _current_span.set(self.span)
            self._tok_trace = _trace_id.set(self.span.trace_id)
            self._tok_tracer = _current_tracer.set(self._tracer)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self.span is not None:
            _current_tracer.reset(self._tok_tracer)
            _trace_id.reset(self._tok_trace)
            _current_span.reset(self._tok_span)
        return False


class FlightRecorder:
    """Per-process lock-striped ring buffer of finished spans.

    Stripes are keyed by trace id, so (a) two busy traces almost never
    contend on a lock and (b) gathering one trace's spans scans exactly
    one stripe's ring, not the whole recorder. Each stripe is a
    fixed-size list used circularly — recording is one store + one
    index bump under the stripe lock, and old spans are overwritten in
    place (a flight recorder, not a log: history exists to be *seized*
    at capture time, not kept)."""

    def __init__(self, capacity: int = 8192, stripes: int = 16):
        self.stripes = max(int(stripes), 1)
        per = max(int(capacity) // self.stripes, 16)
        self.capacity = per * self.stripes
        self._rings: List[List[Optional[Span]]] = [
            [None] * per for _ in range(self.stripes)]
        self._idx = [0] * self.stripes
        self._locks = [threading.Lock() for _ in range(self.stripes)]
        self._per = per

    def _stripe(self, trace_id: str) -> int:
        return hash(trace_id) % self.stripes

    def record(self, span: Span) -> None:
        s = hash(span.trace_id) % self.stripes
        with self._locks[s]:
            self._rings[s][self._idx[s] % self._per] = span
            self._idx[s] += 1

    def gather(self, trace_id: str) -> List[Span]:
        """Every recorded span of ``trace_id`` still in its ring,
        sorted by start time. Best-effort by design: spans evicted by
        ring wraparound are simply absent from the capture."""
        s = self._stripe(trace_id)
        with self._locks[s]:
            found = [sp for sp in self._rings[s]
                     if sp is not None and sp.trace_id == trace_id]
        found.sort(key=lambda sp: sp.t0)
        return found


class Tracer:
    """Span factory + flight recorder + tail-sampled slow-trace store.

    One process-wide :data:`TRACER` serves every layer (the per-route
    thresholds keep serving/trainer/pipeline captures independently
    tuned); tests build private tracers with a
    :class:`~mmlspark_tpu.core.resilience.ManualClock` to drive span
    durations deterministically.
    """

    def __init__(self, clock: Clock = SYSTEM_CLOCK,
                 capacity: int = 8192, store_capacity: int = 128,
                 default_slow_ms: Optional[float] = 250.0):
        self.clock = clock
        self.recorder = FlightRecorder(capacity)
        self.store_capacity = int(store_capacity)
        self.default_slow_ms = default_slow_ms
        self._thresholds: Dict[str, float] = {}
        self._store: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._store_lock = threading.Lock()
        # hot-path bindings (one attribute + descriptor resolve saved
        # per call — real money at <2 us/span)
        self._now = clock.now
        self._record = self.recorder.record

    # -- thresholds ---------------------------------------------------------

    def set_threshold(self, route: str, slow_ms: Optional[float]) -> None:
        """Per-route tail-capture threshold (ms). ``<= 0`` retains every
        completed trace on that route (trace-everything mode for
        harnesses); ``None`` retains only non-ok traces."""
        self._thresholds[route] = slow_ms

    def threshold(self, route: str) -> Optional[float]:
        return self._thresholds.get(route, self.default_slow_ms)

    # -- span lifecycle -----------------------------------------------------

    def start(self, name: str, trace_id: Optional[str] = None,
              parent: Optional[Span] = None,
              remote_parent: Optional[int] = None, **attrs) -> Span:
        """Begin a span. Parent defaults to the ambient span; the trace
        id resolves explicit > parent's > ambient trace id > fresh.
        ``remote_parent`` is a span id adopted from an inbound header
        (:func:`extract_span_context`): the new span records that
        cross-process parent link but is still a LOCAL capture root —
        its real parent finishes in the caller's process."""
        if parent is None:
            parent = _current_span.get()
        if parent is not None:
            tid = trace_id or parent.trace_id
            pid = parent.span_id
        else:
            tid = trace_id or current_trace_id() or new_trace_id()
            pid = remote_parent
        sp = Span(name, tid, pid, self._now(), attrs or None)
        if parent is None and remote_parent is not None:
            sp.remote = True
        if parent is not None and parent.force:
            sp.force = True
        return sp

    def finish(self, span: Span, status: Optional[str] = None,
               capture: bool = True, **attrs) -> None:
        """End + record a span; a finishing ROOT span (no parent) runs
        the tail-capture decision for its whole trace. ``capture=False``
        suppresses that for spans that are parentless only because the
        ambient span did not cross a boundary (e.g. an HTTP egress
        attempt inside a client's ``trace_context``): they belong to a
        larger trace whose real root will run the decision."""
        if span.t1 is not None:
            return                       # double-finish: first one wins
        if attrs:
            if span.attrs is None:
                span.attrs = attrs
            else:
                span.attrs.update(attrs)
        if status is not None:
            span.status = status
        span.t1 = self._now()
        self._record(span)
        if capture and (span.parent_id is None or span.remote):
            self._maybe_capture(span)

    def add(self, name: str, t0: float, t1: float, parent: Span,
            status: str = "ok", **attrs) -> Span:
        """Record an already-completed child span with explicit
        timestamps — the shape the serving pipeline needs, where one
        batch-level measurement (assemble, dispatch, encode) becomes a
        child of every live request's root without re-running clocks
        per request."""
        sp = Span(name, parent.trace_id, parent.span_id, t0, attrs or None)
        sp.t1 = t1
        sp.status = status
        self._record(sp)
        return sp

    def event(self, name: str, t: float, parent: Span,
              **attrs) -> Span:
        """Record an instant (zero-duration) event under ``parent`` —
        a point on the timeline rather than an interval: a decode
        request's first emitted token, an alert transition. Renders as
        an ordinary span with ``t0 == t1``."""
        return self.add(name, t, t, parent, **attrs)

    def span(self, name: str, **attrs) -> "_SpanScope":
        """Scoped span: nests under the ambient span, binds itself (and
        its trace id) for the block, finishes on exit — with status
        ``error`` when the block raises. A class-based context manager,
        not a generator one: two generator frames per span would eat
        most of the <2 us budget by themselves."""
        return _SpanScope(self, self.start(name, **attrs))

    def bind(self, span: Optional[Span]) -> "_BindScope":
        """Re-bind an existing span (and its trace id, and this tracer)
        as the ambient parent — the cross-thread handoff: contextvars
        do not follow the serving pipeline's stage threads, so each
        stage re-binds from the span carried on the work item. ``None``
        is a no-op (synthetic warmup work records nothing)."""
        return _BindScope(self, span)

    # -- tail-based capture -------------------------------------------------

    def _maybe_capture(self, root: Span) -> None:
        route = str((root.attrs or {}).get("route") or root.name)
        dur = root.duration_ms
        if root.status != "ok":
            reason = root.status
        elif root.force:
            # the X-Capture wire hint: this request asked to be kept,
            # threshold or not (one-request debugging in production)
            reason = "forced"
        else:
            thr = self.threshold(route)
            if thr is None or dur < thr:
                return                   # the tail-sampling drop path
            reason = "slow"
        spans = self.recorder.gather(root.trace_id)
        if not spans:
            spans = [root]
        origin = spans[0].t0
        wall = time.time()
        trace = {
            "trace_id": root.trace_id,
            "root": root.name,
            "route": route,
            "duration_ms": round(dur, 3),
            "status": root.status,
            "reason": reason,
            "captured_at": round(wall, 3),
            # wall-clock anchor of the trace's first local span: span
            # t0/t1 are per-process monotonic and NOT comparable across
            # workers, so a distributed merge aligns each part by this
            # anchor instead (best-effort — as good as the hosts' NTP)
            "origin_unix": round(wall - max(self._now() - origin, 0.0), 6),
            "n_spans": len(spans),
            "spans": [sp.to_dict(origin) for sp in spans],
        }
        with self._store_lock:
            self._store.pop(root.trace_id, None)
            self._store[root.trace_id] = trace
            # per-reason quota: an overload storm produces THOUSANDS of
            # identical shed/error captures per second, and pure global
            # LRU would churn out the genuinely interesting slow traces
            # within seconds of an incident starting — exactly when the
            # operator needs them. Each reason evicts its own oldest
            # first; the global cap still bounds the store.
            quota = max(self.store_capacity // 4, 8)
            same = [t["trace_id"] for t in self._store.values()
                    if t["reason"] == trace["reason"]]
            if len(same) > quota:
                self._store.pop(same[0], None)
            while len(self._store) > self.store_capacity:
                self._store.popitem(last=False)

    # -- read side ----------------------------------------------------------

    def get_trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """A retained trace (summary + flat span list), or None if it
        was never captured / already evicted."""
        with self._store_lock:
            return self._store.get(trace_id)

    def traces(self, slow_only: bool = False) -> List[Dict[str, Any]]:
        """Summaries of every retained trace, most recent first.
        ``slow_only`` filters to threshold-retained captures (drops the
        error/shed/deadline ones)."""
        with self._store_lock:
            items = list(self._store.values())
        items.reverse()
        return [{k: t[k] for k in ("trace_id", "root", "route",
                                   "duration_ms", "status", "reason",
                                   "captured_at", "n_spans")}
                for t in items
                if not slow_only or t["reason"] == "slow"]

    def clear(self) -> None:
        """Drop every retained trace (tests; the ring is left alone —
        it self-overwrites)."""
        with self._store_lock:
            self._store.clear()


# ---------------------------------------------------------------------------
# Cross-process span context (the distributed-tracing wire contract)
# ---------------------------------------------------------------------------

#: W3C traceparent-style parent link, split across two headers so the
#: existing ``X-Trace-Id`` contract is untouched: the trace id rides
#: ``X-Trace-Id`` (sanitized, PR 3 semantics) and the CALLER's span id
#: rides ``X-Parent-Span-Id`` as lowercase hex. A worker that adopts
#: the pair parents its root "request" span under the caller's egress
#: span, so a client's whole failover schedule and every worker-side
#: tree stitch into one distributed trace.
PARENT_SPAN_HEADER = "X-Parent-Span-Id"

#: force-capture wire hint: a request carrying ``X-Capture: 1`` is
#: retained end to end regardless of slow-trace thresholds — honored at
#: every ingress (the root span is flagged ``force``) and re-emitted on
#: every egress whose span inherited the flag, so one marked request
#: leaves a capture on every worker it touched.
CAPTURE_HEADER = "X-Capture"

_SPAN_ID_RE = re.compile(r"^[0-9a-fA-F]{1,16}$")


def format_span_id(span_id: int) -> str:
    """A span id as it travels on the wire (lowercase hex, <= 16
    chars)."""
    return format(span_id, "x")


def parse_span_id(raw: Optional[str]) -> Optional[int]:
    """Parse an inbound ``X-Parent-Span-Id``. Strict by design — the
    value becomes a parent link in retained trees and a key in merged
    exports, so anything malformed (non-hex, overlong, zero, empty) is
    REJECTED to ``None`` rather than sanitized into a wrong link.
    (The int() fallback path never runs: the regex admits only plain
    hex, rejecting the whitespace/sign/underscore forms int() itself
    would accept.)"""
    if not raw:
        return None
    if type(raw) is not str:
        raw = str(raw)
    if not _SPAN_ID_RE.match(raw):          # clean wire value: one
        raw = raw.strip()                   # C-speed match, no strip
        if not _SPAN_ID_RE.match(raw):
            return None
    return int(raw, 16) or None


def inject_span_context(headers: Dict[str, str], span: Span,
                        _trace: str = TRACE_HEADER,
                        _parent: str = PARENT_SPAN_HEADER
                        ) -> Dict[str, str]:
    """Headers + the span's trace context (``X-Trace-Id`` +
    ``X-Parent-Span-Id``). Caller-supplied headers win (names compared
    case-insensitively — two conflicting trace headers would fork
    downstream correlation); the input dict is never mutated."""
    # the scan runs on every egress attempt: a length prefilter skips
    # unrelated keys on one int compare, and only length-10/-16 keys
    # (candidate context headers) pay an equality or lower() check
    trace_val = None
    has_trace = has_parent = False
    for k in headers:
        lk = len(k)
        if lk == 10:
            if k == _trace or k.lower() == "x-trace-id":
                has_trace = True
                trace_val = headers[k]
        elif lk == 16:
            if k == _parent or k.lower() == "x-parent-span-id":
                has_parent = True
    if has_trace and has_parent:
        return (_with_capture_hint(headers, span) if span.force
                else headers)
    if has_trace and trace_val != span.trace_id:
        # the caller aimed this request at a DIFFERENT trace: our span
        # id would be a cross-trace parent link — worse than no link
        # (the receiver would forever hold a dangling parent). Leave
        # the caller's context alone.
        return headers
    out = dict(headers)
    if not has_trace:
        out[_trace] = span.trace_id
    if not has_parent:
        out[_parent] = format(span.span_id, "x")
    if span.force:
        return _with_capture_hint(out, span, copied=out is not headers)
    return out


def _with_capture_hint(headers: Dict[str, str], span: Span,
                       copied: bool = False) -> Dict[str, str]:
    """Add ``X-Capture: 1`` to a forced span's egress headers. Callers
    gate on ``span.force`` BEFORE calling (the check is inlined at the
    call sites: a function call per hop is real money against the 2 us
    propagation budget)."""
    for k in headers:
        if len(k) == 9 and (k == CAPTURE_HEADER
                            or k.lower() == "x-capture"):
            return headers               # caller's hint wins
    out = headers if copied else dict(headers)
    out[CAPTURE_HEADER] = "1"
    return out


def capture_hint(headers) -> bool:
    """True iff the inbound request carries the force-capture hint
    (``X-Capture: 1``; any other value is ignored — the hint is a
    boolean, not a knob)."""
    if headers is None:
        return False
    return headers.get(CAPTURE_HEADER) == "1"


def extract_span_context(headers,
                         _tid_ok=_TRACE_ID_OK_RE.fullmatch,
                         _sid_ok=_SPAN_ID_RE.match,
                         _th: str = TRACE_HEADER,
                         _ph: str = PARENT_SPAN_HEADER
                         ) -> Tuple[str, Optional[int]]:
    """Adopt inbound trace context: ``(trace_id, parent_span_id)``.

    The trace id is sanitized exactly like
    :func:`~mmlspark_tpu.core.telemetry.trace_id_from_headers` (or
    minted fresh when absent/empty); the parent span id is parsed
    strictly (:func:`parse_span_id`) and is honored ONLY when the trace
    id itself was adopted — a parent link without the trace it belongs
    to is meaningless and is dropped. Runs at every ingress: a clean
    inbound pair costs two C-speed regex checks (the 2 us/hop
    ``trace_propagation_overhead_v1`` budget)."""
    # bound-method/constant defaults: the fast paths resolve with zero
    # per-call global or attribute lookups — this runs at every ingress
    raw = headers.get(_th) if headers is not None else None
    if not raw:
        return new_trace_id(), None
    if type(raw) is str and _tid_ok(raw):
        tid = raw                            # clean id: no scrub pass
    else:
        tid = sanitize_trace_id(raw)
        if tid is None:
            return new_trace_id(), None
    sid = headers.get(_ph)
    if not sid:
        return tid, None
    if type(sid) is str and _sid_ok(sid):    # clean wire value:
        return tid, int(sid, 16) or None     # parse_span_id inlined
    return tid, parse_span_id(sid)


def merge_traces(parts: List[Tuple[str, Dict[str, Any]]]
                 ) -> Optional[Dict[str, Any]]:
    """Stitch one logical trace's per-process captures into a single
    span list: ``parts`` is ``[(worker_label, captured_trace), ...]``
    for ONE trace id (e.g. the client's capture plus every worker's,
    fetched via ``GET /trace/<id>?format=raw``).

    Each part's spans carry per-process monotonic-relative times, so
    parts are aligned by their ``origin_unix`` wall-clock anchors
    (best-effort: as accurate as the hosts' clock sync) and re-zeroed
    to the earliest span. Every merged span gains a ``worker`` label
    (its originating part) for per-worker attribution and Perfetto
    lanes; span ids are globally unique, so cross-process
    ``parent_id`` links resolve and :func:`span_tree` nests worker
    roots under the caller's egress spans."""
    parts = [(lbl, t) for lbl, t in parts if t]
    if not parts:
        return None
    origins = [t.get("origin_unix") for _, t in parts
               if t.get("origin_unix") is not None]
    zero = min(origins) if origins else 0.0
    spans: List[Dict[str, Any]] = []
    seen: set = set()
    workers: List[str] = []
    owner_of: Dict[int, int] = {}        # span_id -> part index
    for pi, (lbl, t) in enumerate(parts):
        off_ms = ((t.get("origin_unix") or zero) - zero) * 1000.0
        if lbl not in workers:
            workers.append(lbl)
        for sp in t.get("spans", ()):
            if sp["span_id"] in seen:
                continue                 # a part polled twice
            seen.add(sp["span_id"])
            owner_of[sp["span_id"]] = pi
            s = dict(sp)
            s["start_ms"] = round(s["start_ms"] + off_ms, 3)
            s["worker"] = lbl
            spans.append(s)
    if not spans:
        return None
    # -- cross-host clock-skew estimation: origin_unix alignment is
    # only as good as the hosts' wall clocks. Every cross-process
    # parent link gives a physical constraint — the callee's remote
    # root must nest inside the caller's egress span (the request was
    # on the wire outside that window). A subtree that nests is left
    # untouched (zero estimated skew: asymmetric network latency must
    # not be "corrected" away); one that escapes its egress window is
    # shifted by the NTP-style midpoint offset
    # ((e0 - s0) + (e1 - s1)) / 2, which splits the RTT evenly.
    # Corrections propagate caller-first (a worker two hops out is
    # corrected against its already-corrected parent), and the
    # per-worker estimate is reported so merged fleet traces stay
    # honest — and say so — on badly-synced hosts.
    skew_ms = _estimate_clock_skew(spans, owner_of)
    if skew_ms:
        for s in spans:
            shift = skew_ms.get(owner_of[s["span_id"]])
            if shift:
                s["start_ms"] = round(s["start_ms"] + shift, 3)
    spans.sort(key=lambda s: s["start_ms"])
    base = spans[0]["start_ms"]
    if base:
        for s in spans:
            s["start_ms"] = round(s["start_ms"] - base, 3)
    # the distributed root: parentless AND not remote-parented (a
    # worker root's parent finished in another process — it is a root
    # only of its local part); fall back to the earliest span when the
    # caller's part was never captured
    roots = [s for s in spans
             if s["parent_id"] is None and not s.get("remote")]
    root = roots[0] if roots else spans[0]
    owner = parts[owner_of[root["span_id"]]][1]
    end = max(s["start_ms"] + s["duration_ms"] for s in spans)
    return {
        "trace_id": owner["trace_id"],
        "root": root["name"],
        "route": owner.get("route", root["name"]),
        "duration_ms": round(end, 3),
        "status": root["status"],
        "reason": owner.get("reason", root["status"]),
        "captured_at": max(t.get("captured_at", 0.0) for _, t in parts),
        "n_spans": len(spans),
        "workers": workers,
        # estimated wall-clock skew per worker part (ms, the shift
        # applied to that part's spans): 0.0 = link-consistent clocks,
        # absent = no cross-process link to estimate from
        "clock_skew_ms": {parts[pi][0]: round(off, 3)
                          for pi, off in skew_ms.items()},
        "spans": spans,
    }


def _estimate_clock_skew(spans: List[Dict[str, Any]],
                         owner_of: Dict[int, int]) -> Dict[int, float]:
    """Per-part clock corrections from egress/ingress span overlap.

    For every remote-parented span (a worker subtree root) whose
    parent egress span lives in another part: if the subtree escapes
    the egress window, its part is skewed by the midpoint offset;
    inside the window the estimate is 0. Estimates average over a
    part's links and accumulate along the caller chain (BFS from
    parts that are nobody's callee)."""
    by_id = {s["span_id"]: s for s in spans}
    links: Dict[int, list] = {}          # child part -> [(parent, off)]
    for s in spans:
        if not s.get("remote"):
            continue
        e = by_id.get(s["parent_id"])
        if e is None:
            continue
        ci, pi = owner_of[s["span_id"]], owner_of[e["span_id"]]
        if ci == pi:
            continue
        e0, e1 = e["start_ms"], e["start_ms"] + e["duration_ms"]
        s0, s1 = s["start_ms"], s["start_ms"] + s["duration_ms"]
        off = 0.0 if (s0 >= e0 and s1 <= e1) \
            else ((e0 - s0) + (e1 - s1)) / 2.0
        links.setdefault(ci, []).append((pi, off))
    if not links:
        return {}
    resolved: Dict[int, float] = {}
    # caller-first: resolve parts whose parents are all resolved (or
    # are not callees themselves); bounded passes guard cycles
    for _ in range(len(links) + 1):
        progressed = False
        for ci, ls in links.items():
            if ci in resolved:
                continue
            if any(pi in links and pi not in resolved for pi, _ in ls):
                continue
            resolved[ci] = sum(resolved.get(pi, 0.0) + off
                               for pi, off in ls) / len(ls)
            progressed = True
        if not progressed:
            break
    # cycle leftovers: estimate against raw offsets (no propagation)
    for ci, ls in links.items():
        if ci not in resolved:
            resolved[ci] = sum(off for _, off in ls) / len(ls)
    return resolved


# ---------------------------------------------------------------------------
# Adaptive slow-trace thresholds
# ---------------------------------------------------------------------------

class AdaptiveThreshold:
    """Derive a route's ``slow_trace_ms`` from its own latency
    histogram instead of a fixed number.

    A fixed 250 ms threshold captures *everything* on a route whose
    p50 is 300 ms and *nothing* on one whose p99 is 40 ms. This tracks
    the route's observed ``quantile`` (default p95, read from the
    histogram's bucket counts with in-bucket linear interpolation),
    pads it by ``margin``, clamps to ``[floor_ms, ceiling_ms]``, and
    installs the result via :meth:`Tracer.set_threshold` — so tail
    capture always means "slower than this route usually is".

    Off the hot path by construction: :meth:`tick` is one integer
    bump per batch; only every ``refresh_every``-th tick walks the
    histogram's (bounded) bucket counts. Below ``min_count`` total
    observations nothing changes — the configured fixed threshold
    keeps ruling until the route has a believable distribution
    (the warm-up contract).

    ``stats_fn`` returns ``[(edges, counts), ...]`` pairs — one per
    histogram child when the family is labeled (e.g. the serving
    dispatch histogram's per-bucket children merge into one route
    distribution).
    """

    def __init__(self, tracer: "Tracer", route: str, stats_fn,
                 quantile: float = 0.95, margin: float = 1.25,
                 floor_ms: float = 25.0, ceiling_ms: float = 5000.0,
                 min_count: int = 50, refresh_every: int = 32):
        self.tracer = tracer
        self.route = route
        self.stats_fn = stats_fn
        self.quantile = float(quantile)
        self.margin = float(margin)
        self.floor_ms = float(floor_ms)
        self.ceiling_ms = float(ceiling_ms)
        self.min_count = int(min_count)
        self.refresh_every = max(int(refresh_every), 1)
        self.value: Optional[float] = None       # last installed, ms
        self.n_refreshes = 0
        self._since = 0

    def tick(self, n: int = 1) -> Optional[float]:
        """Count ``n`` units of work; refresh when ``refresh_every``
        accumulate. Racy by design (plain int, no lock): a lost tick
        delays a refresh by one batch, which is free compared to a
        lock on the commit path."""
        self._since += n
        if self._since < self.refresh_every:
            return None
        self._since = 0
        return self.refresh()

    def refresh(self) -> Optional[float]:
        """Recompute and install the threshold now; ``None`` when the
        route is still warming up (below ``min_count`` samples)."""
        from mmlspark_tpu.core.telemetry import quantile_from_buckets
        edges = None
        merged: Optional[List[int]] = None
        for e, counts in self.stats_fn():
            if merged is None:
                edges, merged = e, list(counts)
            else:
                merged = [a + b for a, b in zip(merged, counts)]
        if not merged or sum(merged) < self.min_count:
            return None
        q = quantile_from_buckets(edges, merged, self.quantile)
        if q is None:
            return None
        thr = min(max(q * self.margin, self.floor_ms), self.ceiling_ms)
        self.tracer.set_threshold(self.route, thr)
        self.value = thr
        self.n_refreshes += 1
        return thr


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------

def span_tree(trace: Dict[str, Any]) -> Dict[str, Any]:
    """Nest a captured trace's flat span list into its parent->child
    tree. Spans whose parent fell out of the ring before capture attach
    under the root (best-effort flight-recorder semantics, never an
    error); the root is the parentless span, or the earliest span when
    even the root was evicted."""
    spans = [dict(sp) for sp in trace["spans"]]
    for sp in spans:
        sp["children"] = []
    by_id = {sp["span_id"]: sp for sp in spans}
    roots = [sp for sp in spans if sp["parent_id"] is None]
    root = roots[0] if roots else spans[0]
    for sp in spans:
        if sp is root:
            continue
        parent = by_id.get(sp["parent_id"])
        if parent is None or parent is sp:
            parent = root                # orphan: parent left the ring
        parent["children"].append(sp)
    return root


def to_perfetto(trace: Dict[str, Any]) -> Dict[str, Any]:
    """A captured trace as Chrome ``trace_event`` JSON — load the file
    in ``chrome://tracing`` or https://ui.perfetto.dev. Complete
    (``ph: "X"``) events, microsecond timestamps relative to the
    trace's first span, one lane per recording thread (the serving
    pipeline's collector/executor/encoder stages separate visually).

    A MERGED distributed trace (:func:`merge_traces` — its spans carry
    ``worker`` labels) renders each worker as its own *process* lane
    (``pid`` per worker, named via ``process_name`` metadata) with its
    threads nested inside, so the client's failover schedule and every
    worker's stage work read side by side on one timebase."""
    spans = trace["spans"]
    distributed = any("worker" in sp for sp in spans)
    events: List[Dict[str, Any]] = []
    if distributed:
        workers: List[str] = []
        for sp in spans:
            w = sp.get("worker", "")
            if w not in workers:
                workers.append(w)
        wlane = {w: i for i, w in enumerate(workers)}
        lane: Dict[Any, Tuple[int, int]] = {}
        for w in workers:
            pid = wlane[w]
            events.append({"ph": "M", "pid": pid, "tid": 0,
                           "name": "process_name",
                           "args": {"name": w or "local"}})
            threads = sorted({sp["thread"] for sp in spans
                              if sp.get("worker", "") == w})
            for ti, t in enumerate(threads):
                lane[(w, t)] = (pid, ti)
                events.append({"ph": "M", "pid": pid, "tid": ti,
                               "name": "thread_name",
                               "args": {"name": f"thread-{t}"}})
    else:
        pid = os.getpid()
        threads = sorted({sp["thread"] for sp in spans})
        lane = {("", t): (pid, i) for i, t in enumerate(threads)}
        for i, t in enumerate(threads):
            events.append({"ph": "M", "pid": pid, "tid": i,
                           "name": "thread_name",
                           "args": {"name": f"thread-{t}"}})
    for sp in spans:
        args = dict(sp["attrs"])
        args["trace_id"] = trace["trace_id"]
        args["status"] = sp["status"]
        args["span_id"] = sp["span_id"]
        if distributed:
            args["worker"] = sp.get("worker", "")
        epid, etid = lane[(sp.get("worker", "") if distributed else "",
                           sp["thread"])]
        events.append({
            "ph": "X",
            "name": sp["name"],
            "cat": trace["route"],
            "pid": epid,
            "tid": etid,
            "ts": int(round(sp["start_ms"] * 1000.0)),
            "dur": max(int(round(sp["duration_ms"] * 1000.0)), 1),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": trace["trace_id"],
                          "root": trace["root"],
                          "reason": trace["reason"]}}


def dump_perfetto(trace: Dict[str, Any], path: str) -> str:
    """Write :func:`to_perfetto` JSON to ``path`` (any io.fs target)."""
    from mmlspark_tpu.io import fs as _fs
    parent = os.path.dirname(path)
    if parent:
        _fs.makedirs(parent)
    _fs.write_text(path, json.dumps(to_perfetto(trace)))
    return path


#: the process-wide tracer every layer records through. Per-component
#: isolation comes from routes (thresholds) and trace ids, not from
#: separate recorders — one flight recorder per process is the point.
TRACER = Tracer()
