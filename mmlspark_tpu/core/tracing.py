"""Span tracing + flight recorder: per-request span trees, tail capture.

PR 3's trace ids made a request *correlatable* (one ``X-Trace-Id``
across logs, journal lines, egress headers); this module makes it
*inspectable*. The TPU-pod scaling literature (MLPerf on TPU-v3 pods,
arxiv 1909.09756; TensorFlow's timeline-driven performance work, arxiv
1605.08695) is unambiguous that step- and op-level *timelines*, not
aggregate counters, are what make straggler and pipeline-bubble
diagnosis tractable — so every layer that already carries a trace id
now also records :class:`Span` s into a per-process **flight
recorder**:

* a :class:`Span` is name + start/end (on an injectable
  :class:`~mmlspark_tpu.core.resilience.Clock`) + attributes + status,
  nested parent->child; the ambient span rides a contextvar next to
  the trace-id one, and (exactly like trace ids) is handed across the
  serving stage threads on the work item, never through the contextvar;
* finished spans land in a **lock-striped ring buffer**
  (:class:`FlightRecorder`): recording is a clock read + one striped
  append (~hundreds of ns, budget-tested like the metrics hot path),
  and the stripe is chosen by trace id so one trace's spans colocate
  and gathering them scans a single stripe;
* **tail-based capture**: when a ROOT span finishes, the completed
  trace is retained in a bounded LRU store only if it was slow (root
  duration over the per-route threshold) or ended non-ok
  (error/shed/deadline/timeout) — everything else ages out of the ring
  unexamined. ``GET /trace/<id>`` serves a retained trace's span tree,
  ``GET /traces`` lists the store, and :func:`to_perfetto` renders any
  retained trace as Chrome ``trace_event`` JSON for
  ``chrome://tracing`` / Perfetto (``tools/trace_dump.py``).

Histogram exemplars close the loop from the *other* direction: every
:class:`~mmlspark_tpu.core.telemetry.Histogram` bucket remembers the
last traced observation's trace id and exposes it in the Prometheus
exposition (OpenMetrics ``# {trace_id="..."}`` syntax), so a p99
outlier bucket links straight to its captured trace.

Usage::

    from mmlspark_tpu.core.tracing import TRACER

    with TRACER.span("load", route="batch") as sp:
        with TRACER.span("parse", rows=1000):
            parse()

    TRACER.get_trace(sp.trace_id)       # retained iff slow or non-ok

Caveat — trace ids are the correlation key everywhere here (ring
stripe, gather, capture store), and serving adopts inbound
``X-Trace-Id`` headers verbatim (the PR 3 contract): a buggy client
that reuses one id across many requests will colocate all of them on
one stripe and, when any of them is captured, produce a merged tree of
every same-id span still in the ring. Ids must be unique per logical
request — that is the protocol, not something this layer can repair.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from mmlspark_tpu.core.resilience import Clock, SYSTEM_CLOCK
from mmlspark_tpu.core.telemetry import current_trace_id, new_trace_id
# the raw trace-id contextvar (not the trace_context contextmanager):
# span scopes bind trace + span together on the hot path, and a
# generator-contextmanager pair per span would triple the span budget
from mmlspark_tpu.core.telemetry import _trace_id

__all__ = [
    "Span", "FlightRecorder", "Tracer", "TRACER",
    "current_span", "current_span_name", "ambient_tracer",
    "span_tree", "to_perfetto", "dump_perfetto",
]

_SPAN_COUNTER = itertools.count(1)

_current_span: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("mmlspark_tpu_span", default=None)

# the tracer that bound the ambient span: layers that record spans from
# arbitrary call sites (pipeline stages, HTTP egress, trainer) resolve
# it via ambient_tracer(), so a server wired with a PRIVATE tracer
# captures its model-internal spans too — recording those through the
# global TRACER would parent them correctly but land them in the wrong
# recorder, and the private capture would silently miss them
_current_tracer: "contextvars.ContextVar[Optional[Tracer]]" = \
    contextvars.ContextVar("mmlspark_tpu_tracer", default=None)


def current_span() -> Optional["Span"]:
    """The span bound to this context, or None outside any span."""
    return _current_span.get()


def current_span_name() -> Optional[str]:
    sp = _current_span.get()
    return sp.name if sp is not None else None


def ambient_tracer() -> "Tracer":
    """The tracer that bound the ambient span, falling back to the
    process-wide :data:`TRACER` — what framework layers record
    through."""
    return _current_tracer.get() or TRACER


class Span:
    """One timed operation in a trace.

    ``t0``/``t1`` are seconds on the owning tracer's clock (monotonic
    by default); ``thread`` is the recording thread's ident, so the
    Perfetto export lays the serving pipeline's collector/executor/
    encoder work out on separate lanes. Spans are plain mutable records
    — the tracer, not the span, owns lifecycle (:meth:`Tracer.finish`).

    Hot-path notes (the <2 us/span bench budget, ``tracing_overhead_v1``):
    span ids are plain process-unique ints (no per-span string format),
    and ``attrs`` stays ``None`` until someone actually attaches one —
    most child spans never allocate a dict.
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "t0", "t1", "status", "attrs", "thread")

    def __init__(self, name: str, trace_id: str,
                 parent_id: Optional[int], t0: float,
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = next(_SPAN_COUNTER)
        self.parent_id = parent_id
        self.t0 = t0
        self.t1: Optional[float] = None
        self.status = "ok"
        self.attrs: Optional[Dict[str, Any]] = attrs
        self.thread = threading.get_ident()

    @property
    def duration_ms(self) -> float:
        return ((self.t1 or self.t0) - self.t0) * 1000.0

    def set_attr(self, key: str, value: Any) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def to_dict(self, origin: float = 0.0) -> Dict[str, Any]:
        """JSON-able record; times relative to ``origin`` (the trace's
        first span start) so exported trees read from 0."""
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ms": round((self.t0 - origin) * 1000.0, 3),
            "duration_ms": round(self.duration_ms, 3),
            "status": self.status,
            "attrs": self.attrs or {},
            "thread": self.thread,
        }

    def __repr__(self) -> str:
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"id={self.span_id}, parent={self.parent_id}, "
                f"status={self.status})")


class _SpanScope:
    """``with tracer.span(...)``: binds the span + its trace id + its
    tracer on enter, finishes (status ``error`` on exception) on
    exit."""

    __slots__ = ("_tracer", "span", "_tok_span", "_tok_trace",
                 "_tok_tracer")

    def __init__(self, tracer: "Tracer", span: "Span"):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> "Span":
        self._tok_span = _current_span.set(self.span)
        self._tok_trace = _trace_id.set(self.span.trace_id)
        self._tok_tracer = _current_tracer.set(self._tracer)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        _current_tracer.reset(self._tok_tracer)
        _trace_id.reset(self._tok_trace)
        _current_span.reset(self._tok_span)
        self._tracer.finish(self.span,
                            status="error" if exc_type is not None
                            else None)
        return False


class _BindScope:
    """``with tracer.bind(span)``: ambient span + trace id + tracer
    for the block; ``None`` span binds nothing (no-op)."""

    __slots__ = ("_tracer", "span", "_tok_span", "_tok_trace",
                 "_tok_tracer")

    def __init__(self, tracer: "Tracer", span: Optional["Span"]):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> Optional["Span"]:
        if self.span is not None:
            self._tok_span = _current_span.set(self.span)
            self._tok_trace = _trace_id.set(self.span.trace_id)
            self._tok_tracer = _current_tracer.set(self._tracer)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self.span is not None:
            _current_tracer.reset(self._tok_tracer)
            _trace_id.reset(self._tok_trace)
            _current_span.reset(self._tok_span)
        return False


class FlightRecorder:
    """Per-process lock-striped ring buffer of finished spans.

    Stripes are keyed by trace id, so (a) two busy traces almost never
    contend on a lock and (b) gathering one trace's spans scans exactly
    one stripe's ring, not the whole recorder. Each stripe is a
    fixed-size list used circularly — recording is one store + one
    index bump under the stripe lock, and old spans are overwritten in
    place (a flight recorder, not a log: history exists to be *seized*
    at capture time, not kept)."""

    def __init__(self, capacity: int = 8192, stripes: int = 16):
        self.stripes = max(int(stripes), 1)
        per = max(int(capacity) // self.stripes, 16)
        self.capacity = per * self.stripes
        self._rings: List[List[Optional[Span]]] = [
            [None] * per for _ in range(self.stripes)]
        self._idx = [0] * self.stripes
        self._locks = [threading.Lock() for _ in range(self.stripes)]
        self._per = per

    def _stripe(self, trace_id: str) -> int:
        return hash(trace_id) % self.stripes

    def record(self, span: Span) -> None:
        s = hash(span.trace_id) % self.stripes
        with self._locks[s]:
            self._rings[s][self._idx[s] % self._per] = span
            self._idx[s] += 1

    def gather(self, trace_id: str) -> List[Span]:
        """Every recorded span of ``trace_id`` still in its ring,
        sorted by start time. Best-effort by design: spans evicted by
        ring wraparound are simply absent from the capture."""
        s = self._stripe(trace_id)
        with self._locks[s]:
            found = [sp for sp in self._rings[s]
                     if sp is not None and sp.trace_id == trace_id]
        found.sort(key=lambda sp: sp.t0)
        return found


class Tracer:
    """Span factory + flight recorder + tail-sampled slow-trace store.

    One process-wide :data:`TRACER` serves every layer (the per-route
    thresholds keep serving/trainer/pipeline captures independently
    tuned); tests build private tracers with a
    :class:`~mmlspark_tpu.core.resilience.ManualClock` to drive span
    durations deterministically.
    """

    def __init__(self, clock: Clock = SYSTEM_CLOCK,
                 capacity: int = 8192, store_capacity: int = 128,
                 default_slow_ms: Optional[float] = 250.0):
        self.clock = clock
        self.recorder = FlightRecorder(capacity)
        self.store_capacity = int(store_capacity)
        self.default_slow_ms = default_slow_ms
        self._thresholds: Dict[str, float] = {}
        self._store: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._store_lock = threading.Lock()
        # hot-path bindings (one attribute + descriptor resolve saved
        # per call — real money at <2 us/span)
        self._now = clock.now
        self._record = self.recorder.record

    # -- thresholds ---------------------------------------------------------

    def set_threshold(self, route: str, slow_ms: Optional[float]) -> None:
        """Per-route tail-capture threshold (ms). ``<= 0`` retains every
        completed trace on that route (trace-everything mode for
        harnesses); ``None`` retains only non-ok traces."""
        self._thresholds[route] = slow_ms

    def threshold(self, route: str) -> Optional[float]:
        return self._thresholds.get(route, self.default_slow_ms)

    # -- span lifecycle -----------------------------------------------------

    def start(self, name: str, trace_id: Optional[str] = None,
              parent: Optional[Span] = None, **attrs) -> Span:
        """Begin a span. Parent defaults to the ambient span; the trace
        id resolves explicit > parent's > ambient trace id > fresh."""
        if parent is None:
            parent = _current_span.get()
        if parent is not None:
            tid = trace_id or parent.trace_id
            pid = parent.span_id
        else:
            tid = trace_id or current_trace_id() or new_trace_id()
            pid = None
        return Span(name, tid, pid, self._now(), attrs or None)

    def finish(self, span: Span, status: Optional[str] = None,
               capture: bool = True, **attrs) -> None:
        """End + record a span; a finishing ROOT span (no parent) runs
        the tail-capture decision for its whole trace. ``capture=False``
        suppresses that for spans that are parentless only because the
        ambient span did not cross a boundary (e.g. an HTTP egress
        attempt inside a client's ``trace_context``): they belong to a
        larger trace whose real root will run the decision."""
        if span.t1 is not None:
            return                       # double-finish: first one wins
        if attrs:
            if span.attrs is None:
                span.attrs = attrs
            else:
                span.attrs.update(attrs)
        if status is not None:
            span.status = status
        span.t1 = self._now()
        self._record(span)
        if capture and span.parent_id is None:
            self._maybe_capture(span)

    def add(self, name: str, t0: float, t1: float, parent: Span,
            status: str = "ok", **attrs) -> Span:
        """Record an already-completed child span with explicit
        timestamps — the shape the serving pipeline needs, where one
        batch-level measurement (assemble, dispatch, encode) becomes a
        child of every live request's root without re-running clocks
        per request."""
        sp = Span(name, parent.trace_id, parent.span_id, t0, attrs or None)
        sp.t1 = t1
        sp.status = status
        self._record(sp)
        return sp

    def span(self, name: str, **attrs) -> "_SpanScope":
        """Scoped span: nests under the ambient span, binds itself (and
        its trace id) for the block, finishes on exit — with status
        ``error`` when the block raises. A class-based context manager,
        not a generator one: two generator frames per span would eat
        most of the <2 us budget by themselves."""
        return _SpanScope(self, self.start(name, **attrs))

    def bind(self, span: Optional[Span]) -> "_BindScope":
        """Re-bind an existing span (and its trace id, and this tracer)
        as the ambient parent — the cross-thread handoff: contextvars
        do not follow the serving pipeline's stage threads, so each
        stage re-binds from the span carried on the work item. ``None``
        is a no-op (synthetic warmup work records nothing)."""
        return _BindScope(self, span)

    # -- tail-based capture -------------------------------------------------

    def _maybe_capture(self, root: Span) -> None:
        route = str((root.attrs or {}).get("route") or root.name)
        dur = root.duration_ms
        if root.status != "ok":
            reason = root.status
        else:
            thr = self.threshold(route)
            if thr is None or dur < thr:
                return                   # the tail-sampling drop path
            reason = "slow"
        spans = self.recorder.gather(root.trace_id)
        if not spans:
            spans = [root]
        origin = spans[0].t0
        trace = {
            "trace_id": root.trace_id,
            "root": root.name,
            "route": route,
            "duration_ms": round(dur, 3),
            "status": root.status,
            "reason": reason,
            "captured_at": round(time.time(), 3),
            "n_spans": len(spans),
            "spans": [sp.to_dict(origin) for sp in spans],
        }
        with self._store_lock:
            self._store.pop(root.trace_id, None)
            self._store[root.trace_id] = trace
            # per-reason quota: an overload storm produces THOUSANDS of
            # identical shed/error captures per second, and pure global
            # LRU would churn out the genuinely interesting slow traces
            # within seconds of an incident starting — exactly when the
            # operator needs them. Each reason evicts its own oldest
            # first; the global cap still bounds the store.
            quota = max(self.store_capacity // 4, 8)
            same = [t["trace_id"] for t in self._store.values()
                    if t["reason"] == trace["reason"]]
            if len(same) > quota:
                self._store.pop(same[0], None)
            while len(self._store) > self.store_capacity:
                self._store.popitem(last=False)

    # -- read side ----------------------------------------------------------

    def get_trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """A retained trace (summary + flat span list), or None if it
        was never captured / already evicted."""
        with self._store_lock:
            return self._store.get(trace_id)

    def traces(self, slow_only: bool = False) -> List[Dict[str, Any]]:
        """Summaries of every retained trace, most recent first.
        ``slow_only`` filters to threshold-retained captures (drops the
        error/shed/deadline ones)."""
        with self._store_lock:
            items = list(self._store.values())
        items.reverse()
        return [{k: t[k] for k in ("trace_id", "root", "route",
                                   "duration_ms", "status", "reason",
                                   "captured_at", "n_spans")}
                for t in items
                if not slow_only or t["reason"] == "slow"]

    def clear(self) -> None:
        """Drop every retained trace (tests; the ring is left alone —
        it self-overwrites)."""
        with self._store_lock:
            self._store.clear()


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------

def span_tree(trace: Dict[str, Any]) -> Dict[str, Any]:
    """Nest a captured trace's flat span list into its parent->child
    tree. Spans whose parent fell out of the ring before capture attach
    under the root (best-effort flight-recorder semantics, never an
    error); the root is the parentless span, or the earliest span when
    even the root was evicted."""
    spans = [dict(sp) for sp in trace["spans"]]
    for sp in spans:
        sp["children"] = []
    by_id = {sp["span_id"]: sp for sp in spans}
    roots = [sp for sp in spans if sp["parent_id"] is None]
    root = roots[0] if roots else spans[0]
    for sp in spans:
        if sp is root:
            continue
        parent = by_id.get(sp["parent_id"])
        if parent is None or parent is sp:
            parent = root                # orphan: parent left the ring
        parent["children"].append(sp)
    return root


def to_perfetto(trace: Dict[str, Any]) -> Dict[str, Any]:
    """A captured trace as Chrome ``trace_event`` JSON — load the file
    in ``chrome://tracing`` or https://ui.perfetto.dev. Complete
    (``ph: "X"``) events, microsecond timestamps relative to the
    trace's first span, one lane per recording thread (the serving
    pipeline's collector/executor/encoder stages separate visually)."""
    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    threads = sorted({sp["thread"] for sp in trace["spans"]})
    lane = {t: i for i, t in enumerate(threads)}
    for i, t in enumerate(threads):
        events.append({"ph": "M", "pid": pid, "tid": i,
                       "name": "thread_name",
                       "args": {"name": f"thread-{t}"}})
    for sp in trace["spans"]:
        args = dict(sp["attrs"])
        args["trace_id"] = trace["trace_id"]
        args["status"] = sp["status"]
        args["span_id"] = sp["span_id"]
        events.append({
            "ph": "X",
            "name": sp["name"],
            "cat": trace["route"],
            "pid": pid,
            "tid": lane[sp["thread"]],
            "ts": int(round(sp["start_ms"] * 1000.0)),
            "dur": max(int(round(sp["duration_ms"] * 1000.0)), 1),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": trace["trace_id"],
                          "root": trace["root"],
                          "reason": trace["reason"]}}


def dump_perfetto(trace: Dict[str, Any], path: str) -> str:
    """Write :func:`to_perfetto` JSON to ``path`` (any io.fs target)."""
    from mmlspark_tpu.io import fs as _fs
    parent = os.path.dirname(path)
    if parent:
        _fs.makedirs(parent)
    _fs.write_text(path, json.dumps(to_perfetto(trace)))
    return path


#: the process-wide tracer every layer records through. Per-component
#: isolation comes from routes (thresholds) and trace ids, not from
#: separate recorders — one flight recorder per process is the point.
TRACER = Tracer()
