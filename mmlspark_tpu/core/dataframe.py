"""Columnar host-side DataFrame: the data currency of the framework.

Where the reference passes Spark ``DataFrame``s between pipeline stages, this
framework passes a lightweight columnar frame: a dict of numpy arrays (first
axis = rows; trailing axes allowed for tensors such as NHWC images or feature
vectors) plus per-column JSON-able metadata (categorical levels, ML roles —
see :mod:`mmlspark_tpu.core.schema`).

Device placement is explicit and late: stages move the columns they compute
on to TPU as a pytree (``df.device_batch([...])``) and bring results back as
columns. This is the TPU-native replacement for the reference's
``df.mapPartitions { rows => nativeEngine(rows) }`` idiom
(`CNTKModel.scala:497`, `LightGBMBase.scala:65-68`): the per-host columnar
batch is the unit of device work instead of the per-partition row iterator.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Iterable, Iterator, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

ColumnLike = Union[np.ndarray, Sequence[Any]]


def py_scalar(v):
    """Numpy scalar -> plain python (JSON-able, dict-key stable)."""
    return v.item() if isinstance(v, np.generic) else v


def is_null(v) -> bool:
    """None or float NaN (the framework-wide notion of a missing cell)."""
    if v is None:
        return True
    if isinstance(v, (float, np.floating)) and np.isnan(v):
        return True
    return False


def obj_col(items) -> np.ndarray:
    """Sequence -> 1D object array (immune to numpy's 2D inference)."""
    arr = np.empty(len(items), dtype=object)
    for i, v in enumerate(items):
        arr[i] = v
    return arr


def _as_column(values: ColumnLike) -> np.ndarray:
    if isinstance(values, np.ndarray):
        return values
    values = list(values)
    if values and isinstance(values[0], str):
        return np.array(values, dtype=object)
    try:
        arr = np.asarray(values)
        if arr.dtype == np.dtype("O") or arr.dtype.kind in "US":
            return np.array(values, dtype=object)
        return arr
    except (ValueError, TypeError):
        return np.array(values, dtype=object)


class DataFrame:
    """An immutable-ish columnar frame: ordered ``{name: ndarray}`` + metadata."""

    def __init__(self,
                 columns: Mapping[str, ColumnLike],
                 metadata: Optional[Mapping[str, Dict[str, Any]]] = None):
        self._data: Dict[str, np.ndarray] = {}
        n_rows: Optional[int] = None
        for name, values in columns.items():
            col = _as_column(values)
            if n_rows is None:
                n_rows = len(col)
            elif len(col) != n_rows:
                raise ValueError(
                    f"column {name!r} has {len(col)} rows, expected {n_rows}")
            self._data[name] = col
        self._n_rows = n_rows or 0
        self._meta: Dict[str, Dict[str, Any]] = {
            k: dict(v) for k, v in (metadata or {}).items() if k in self._data
        }

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_rows(rows: Sequence[Mapping[str, Any]]) -> "DataFrame":
        if not rows:
            return DataFrame({})
        names = list(rows[0].keys())
        return DataFrame({n: [r[n] for r in rows] for n in names})

    @staticmethod
    def from_pandas(pdf) -> "DataFrame":
        import pandas as pd
        cols = {}
        for name in pdf.columns:
            s = pdf[name]
            if s.dtype == object or str(s.dtype).startswith(("string", "category")):
                cols[str(name)] = np.array(
                    [None if pd.isna(v) else v for v in s.tolist()], dtype=object)
            else:
                cols[str(name)] = s.to_numpy()
        return DataFrame(cols)

    def to_pandas(self):
        import pandas as pd
        out = {}
        for name, col in self._data.items():
            out[name] = list(col) if col.ndim > 1 else col
        return pd.DataFrame(out)

    # -- basic accessors ----------------------------------------------------

    @property
    def columns(self) -> List[str]:
        return list(self._data.keys())

    @property
    def num_rows(self) -> int:
        return self._n_rows

    def __len__(self) -> int:
        return self._n_rows

    def __contains__(self, name: str) -> bool:
        return name in self._data

    def __getitem__(self, name: str) -> np.ndarray:
        if name not in self._data:
            raise KeyError(f"no column {name!r}; have {self.columns}")
        return self._data[name]

    def column(self, name: str) -> np.ndarray:
        return self[name]

    def get_metadata(self, name: str) -> Dict[str, Any]:
        return dict(self._meta.get(name, {}))

    def schema(self) -> Dict[str, Tuple[Tuple[int, ...], str]]:
        return {n: (c.shape[1:], str(c.dtype)) for n, c in self._data.items()}

    def rows(self) -> Iterator[Dict[str, Any]]:
        for i in range(self._n_rows):
            yield {n: c[i] for n, c in self._data.items()}

    def to_dict(self) -> Dict[str, np.ndarray]:
        return dict(self._data)

    # -- transformations (all return new frames) ----------------------------

    def _derive(self, data: Dict[str, np.ndarray],
                meta: Optional[Dict[str, Dict[str, Any]]] = None,
                n_rows: Optional[int] = None) -> "DataFrame":
        out = DataFrame.__new__(DataFrame)
        out._data = data
        if data:
            out._n_rows = len(next(iter(data.values())))
        else:
            out._n_rows = n_rows if n_rows is not None else self._n_rows
        out._meta = meta if meta is not None else {
            k: dict(v) for k, v in self._meta.items() if k in data}
        return out

    def select(self, names: Sequence[str]) -> "DataFrame":
        missing = [n for n in names if n not in self._data]
        if missing:
            raise KeyError(f"no columns {missing}; have {self.columns}")
        return self._derive({n: self._data[n] for n in names})

    def drop(self, *names: str) -> "DataFrame":
        return self._derive({n: c for n, c in self._data.items() if n not in names})

    def with_column(self, name: str, values: ColumnLike,
                    metadata: Optional[Dict[str, Any]] = None) -> "DataFrame":
        col = _as_column(values)
        if (self._data or self._n_rows) and len(col) != self._n_rows:
            raise ValueError(
                f"column {name!r} has {len(col)} rows, expected {self._n_rows}")
        data = dict(self._data)
        data[name] = col
        meta = {k: dict(v) for k, v in self._meta.items() if k in data}
        if metadata is not None:
            meta[name] = dict(metadata)
        elif name in meta:
            meta.pop(name)  # new values invalidate old metadata
        return self._derive(data, meta)

    def with_metadata(self, name: str, metadata: Dict[str, Any]) -> "DataFrame":
        if name not in self._data:
            raise KeyError(name)
        meta = {k: dict(v) for k, v in self._meta.items()}
        meta[name] = dict(metadata)
        return self._derive(dict(self._data), meta)

    def rename(self, mapping: Mapping[str, str]) -> "DataFrame":
        data = {mapping.get(n, n): c for n, c in self._data.items()}
        meta = {mapping.get(n, n): dict(v) for n, v in self._meta.items()}
        return self._derive(data, meta)

    def filter(self, mask: ColumnLike) -> "DataFrame":
        mask = np.asarray(mask, dtype=bool)
        data = {n: c[mask] for n, c in self._data.items()}
        return self._derive(data, n_rows=int(mask.sum()))

    def take(self, indices: ColumnLike) -> "DataFrame":
        idx = np.asarray(indices)
        if idx.size == 0:
            idx = np.zeros(0, dtype=np.int64)
        return self._derive({n: c[idx] for n, c in self._data.items()},
                            n_rows=len(idx))

    def head(self, n: int) -> "DataFrame":
        return self._derive({k: c[:n] for k, c in self._data.items()},
                            n_rows=min(n, self._n_rows))

    def sort_by(self, name: str, ascending: bool = True) -> "DataFrame":
        order = np.argsort(self._data[name], kind="stable")
        if not ascending:
            order = order[::-1]
        return self.take(order)

    def sample(self, fraction: float, seed: int = 0,
               replacement: bool = False) -> "DataFrame":
        rng = np.random.default_rng(seed)
        k = int(round(self._n_rows * fraction))
        idx = rng.choice(self._n_rows, size=k, replace=replacement)
        if not replacement:
            idx = np.sort(idx)
        return self.take(idx)

    def random_split(self, fractions: Sequence[float], seed: int = 0) -> List["DataFrame"]:
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self._n_rows)
        total = float(sum(fractions))
        splits = []
        start = 0
        for i, f in enumerate(fractions):
            end = self._n_rows if i == len(fractions) - 1 else \
                start + int(round(self._n_rows * f / total))
            splits.append(self.take(np.sort(perm[start:end])))
            start = end
        return splits

    def drop_nulls(self, subset: Optional[Sequence[str]] = None) -> "DataFrame":
        """Drop rows with NaN (float cols) or None (object cols).

        When nothing drops (the common serving/featurizer case) the
        frame is returned AS IS: filtering with an all-true mask would
        fancy-index a full copy of every column, and a copied column
        carries a new identity — which silently defeats every
        downstream cache keyed on column identity (NNModel's
        device-resident frame cache re-uploads the whole frame per
        pass; on a tunneled chip that re-upload, not compute, was the
        transfer-learning bench's warm-path cost)."""
        names = list(subset) if subset is not None else self.columns
        keep = np.ones(self._n_rows, dtype=bool)
        for n in names:
            c = self._data[n]
            if c.dtype == np.dtype("O"):
                keep &= np.array([v is not None for v in c])
            elif np.issubdtype(c.dtype, np.floating):
                # isnan runs natively on every float dtype: casting to
                # float64 first allocated a 2x copy of image-sized
                # columns just to scan them
                flat = c.reshape(len(c), -1) if c.ndim > 1 else c[:, None]
                keep &= ~np.isnan(flat).any(axis=1)
        if keep.all():
            return self
        return self.filter(keep)

    @staticmethod
    def concat(frames: Sequence["DataFrame"]) -> "DataFrame":
        frames = [f for f in frames if f.num_rows > 0 or f.columns]
        if not frames:
            return DataFrame({})
        names = frames[0].columns
        for f in frames[1:]:
            if f.columns != names:
                raise ValueError(f"column mismatch: {f.columns} vs {names}")
        data = {n: np.concatenate([f._data[n] for f in frames]) for n in names}
        meta: Dict[str, Dict[str, Any]] = {}
        for f in frames:  # later frames' metadata wins where present
            for k, v in f._meta.items():
                meta[k] = dict(v)
        return frames[0]._derive(data, meta)

    def map_column(self, name: str, fn: Callable[[Any], Any],
                   output: Optional[str] = None) -> "DataFrame":
        out_name = output or name
        values = [fn(v) for v in self._data[name]]
        return self.with_column(out_name, values)

    # -- batching / device --------------------------------------------------

    def iter_batches(self, batch_size: int,
                     columns: Optional[Sequence[str]] = None) -> Iterator["DataFrame"]:
        names = list(columns) if columns is not None else self.columns
        for start in range(0, self._n_rows, batch_size):
            end = min(start + batch_size, self._n_rows)
            yield self._derive({n: self._data[n][start:end] for n in names})

    def device_batch(self, columns: Sequence[str], dtype=None,
                     sharding=None) -> Dict[str, Any]:
        """Move the named numeric columns to device as a pytree of jax arrays."""
        import jax
        import jax.numpy as jnp
        out = {}
        for n in columns:
            c = self._data[n]
            if c.dtype == np.dtype("O"):
                c = np.stack([np.asarray(v) for v in c])
            arr = jnp.asarray(c, dtype=dtype)
            if sharding is not None:
                arr = jax.device_put(arr, sharding)
            out[n] = arr
        return out

    # -- persistence ---------------------------------------------------------

    def save(self, path: str) -> None:
        """Persist to ``<path>`` (.npz columns + .meta.json sidecar)."""
        np.savez_compressed(path if path.endswith(".npz") else path + ".npz",
                            **self._data)
        base = path[:-4] if path.endswith(".npz") else path
        from mmlspark_tpu.core.serialize import _json_default
        with open(base + ".meta.json", "w") as f:
            json.dump({"metadata": self._meta, "n_rows": self._n_rows}, f,
                      default=_json_default)

    @staticmethod
    def load(path: str) -> "DataFrame":
        npz_path = path if path.endswith(".npz") else path + ".npz"
        base = path[:-4] if path.endswith(".npz") else path
        with np.load(npz_path, allow_pickle=True) as z:
            data = {k: z[k] for k in z.files}
        meta: Dict[str, Dict[str, Any]] = {}
        n_rows = None
        try:
            with open(base + ".meta.json") as f:
                side = json.load(f)
            meta = side.get("metadata", {})
            n_rows = side.get("n_rows")
        except FileNotFoundError:
            pass
        out = DataFrame(data, metadata=meta)
        if not data and n_rows:
            out._n_rows = n_rows
        return out

    # -- misc ----------------------------------------------------------------

    def __repr__(self) -> str:
        parts = ", ".join(f"{n}:{str(c.dtype)}{list(c.shape[1:]) or ''}"
                          for n, c in self._data.items())
        return f"DataFrame[{self._n_rows} rows; {parts}]"

    def show(self, n: int = 10) -> str:
        lines = ["\t".join(self.columns)]
        for row in self.head(n).rows():
            lines.append("\t".join(str(v) for v in row.values()))
        text = "\n".join(lines)
        print(text)
        return text
