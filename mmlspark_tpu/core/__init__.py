from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param, Params
from mmlspark_tpu.core.stage import (
    PipelineStage,
    Transformer,
    Estimator,
    Model,
    Evaluator,
)
from mmlspark_tpu.core.pipeline import Pipeline, PipelineModel
from mmlspark_tpu.core.resilience import (
    BreakerBoard,
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
    ManualClock,
    RetryPolicy,
)
from mmlspark_tpu.core.telemetry import (
    REGISTRY,
    MetricsRegistry,
    current_trace_id,
    trace_context,
)
from mmlspark_tpu.core import schema

__all__ = [
    "REGISTRY",
    "MetricsRegistry",
    "current_trace_id",
    "trace_context",
    "BreakerBoard",
    "CircuitBreaker",
    "CircuitOpen",
    "Deadline",
    "DeadlineExceeded",
    "ManualClock",
    "RetryPolicy",
    "DataFrame",
    "Param",
    "Params",
    "PipelineStage",
    "Transformer",
    "Estimator",
    "Model",
    "Evaluator",
    "Pipeline",
    "PipelineModel",
    "schema",
]
