"""The retrospective plane: an embedded, bounded metrics TSDB.

PR 18's SLO engine can *alert* but the stack cannot *remember*: every
sample older than the burn windows is gone, so "what did decode TTFT
p95 look like over the last hour, per tenant, before the alert fired?"
needed an external Prometheus. This module closes that gap with four
pieces, all in-process and all bounded:

* :func:`take_scrape` / :class:`Scrape` — ONE pass over a set of
  :class:`~mmlspark_tpu.core.telemetry.MetricsRegistry` instances,
  capturing names, kinds, bucket edges, and child values together.
  From one scrape you can render the text exposition (the ``.prom``
  dumper), flatten ingest rows for the store, and build the SLO
  engine's snapshot dict — so the dumper, the TSDB, and the SLO
  history all ride a single scrape per interval instead of three.
* :class:`TimeSeriesStore` — series keyed by ``(name, labels)`` with
  tiered downsampling rings (raw -> 10s -> 60s by default), per-tier
  retention eviction, and counter-reset-aware ingest: every point
  carries both its raw value and a monotonic *adjusted* value whose
  deltas are clamped exactly like the SLO engine's (a worker restart
  reads as "no traffic", never negative traffic), so ``rate()`` is
  exact across resets.
* a query plane — :meth:`TimeSeriesStore.query` (instant) and
  :meth:`TimeSeriesStore.query_range` (series) over a small PromQL-
  shaped grammar: label matchers (``=``, ``!=``, ``=~``, ``!~``),
  ``rate()``/``increase()`` over counters, and
  ``quantile(q, hist[window])`` over histogram buckets (reusing
  :func:`~mmlspark_tpu.core.telemetry.quantile_from_buckets`). The
  serving worker serves this at ``GET /query`` / ``GET /query_range``
  and the coordinator fans out + merges per-worker series under
  ``worker=host:port`` labels.
* baseline-relative regression detection — :class:`RecordingRule`
  precomputes hot series (per-bucket dispatch p95, decode TTFT/TPOT,
  tokens/s, recompile rate, per-tenant shed + device-time rates) each
  tick, and :class:`AnomalyDetector` runs an EWMA + MAD z-score over
  the recorded series: warm-up guarded (no verdict before
  ``min_samples``), baseline frozen while violated (a sustained
  regression cannot teach itself normal), hysteresis via the same
  ``ok -> pending -> firing -> resolved`` state machine the SLO
  engine uses, transitions delivered through the same
  :class:`~mmlspark_tpu.serving.slo.AlertNotifier`.

Everything is fed by a background :class:`Recorder` on the
MetricsSnapshot cadence at a perf-gated ingest budget
(``bench.py tsdb_overhead_v1`` enforces it). Nothing here touches a
request hot path: the recorder scrapes exposition-time views, exactly
like ``GET /metrics`` does.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from mmlspark_tpu.core.resilience import Clock, SYSTEM_CLOCK
from mmlspark_tpu.core.telemetry import (
    MetricsRegistry, quantile_from_buckets,
    _escape_help, _escape_label, _fmt,
)

__all__ = [
    "Scrape", "take_scrape", "TimeSeriesStore", "DEFAULT_TIERS",
    "QueryError", "parse_duration", "parse_expr",
    "RecordingRule", "default_serving_rules",
    "AnomalyWatch", "AnomalyDetector", "default_serving_watches",
    "Recorder",
]


# ---------------------------------------------------------------------------
# One scrape, three consumers
# ---------------------------------------------------------------------------

class Scrape:
    """One captured pass over a set of registries.

    ``entries`` is a list of ``(name, kind, help, label_names, edges,
    children)`` in exposition order (per-registry, families sorted by
    name); ``children`` is a sorted list of ``(label_key, value)``
    where value is a float (counter/gauge) or ``(buckets, sum, count)``
    (histogram, per-bucket counts with the +Inf overflow last).
    """

    __slots__ = ("at", "entries")

    def __init__(self, at: float, entries: List[tuple]):
        self.at = float(at)
        self.entries = entries

    # -- consumer 1: the .prom dumper ----------------------------------------

    def render(self) -> str:
        """The Prometheus text exposition of this scrape — the same
        bytes :meth:`MetricsRegistry.render` would emit (no
        exemplars), produced WITHOUT touching the registries again."""
        lines: List[str] = []
        for name, kind, help_, label_names, edges, children in \
                self.entries:
            lines.append(f"# HELP {name} {_escape_help(help_)}")
            lines.append(f"# TYPE {name} {kind}")
            for key, val in children:
                label_str = _label_str(label_names, key)
                if kind != "histogram":
                    lines.append(f"{name}{label_str} {_fmt(val)}")
                    continue
                buckets, total, count = val
                cum = 0
                for edge, n in zip(edges, buckets):
                    cum += n
                    lines.append(
                        f"{name}_bucket"
                        f"{_label_str(label_names, key, ('le', _fmt(edge)))}"
                        f" {cum}")
                cum += buckets[-1]
                lines.append(
                    f"{name}_bucket"
                    f"{_label_str(label_names, key, ('le', '+Inf'))}"
                    f" {cum}")
                lines.append(f"{name}_sum{label_str} {_fmt(total)}")
                lines.append(f"{name}_count{label_str} {count}")
        return "\n".join(lines) + ("\n" if lines else "")

    # -- consumer 2: TSDB ingest rows ----------------------------------------

    def rows(self) -> Iterable[Tuple[str, Tuple[Tuple[str, str], ...],
                                     float, str]]:
        """Flat ``(name, labels, value, kind)`` ingest rows, kind in
        ``{"c", "g"}``. Histograms expand to the standard cumulative
        ``_bucket``/``_sum``/``_count`` series (all counters), exactly
        mirroring the exposition — so a ``quantile()`` query reads the
        same numbers a Prometheus scraping ``/metrics`` would."""
        for name, kind, _help, label_names, edges, children in \
                self.entries:
            k = "g" if kind == "gauge" else "c"
            for key, val in children:
                labels = tuple(zip(label_names, key))
                if kind != "histogram":
                    yield name, labels, float(val), k
                    continue
                buckets, total, count = val
                cum = 0
                for edge, n in zip(edges, buckets):
                    cum += n
                    yield (f"{name}_bucket",
                           labels + (("le", _fmt(edge)),), float(cum),
                           "c")
                cum += buckets[-1]
                yield (f"{name}_bucket", labels + (("le", "+Inf"),),
                       float(cum), "c")
                yield f"{name}_sum", labels, float(total), "c"
                yield f"{name}_count", labels, float(count), "c"

    # -- consumer 3: the SLO engine's snapshot history -----------------------

    def slo_snapshot(self, wanted: Iterable[str]) -> dict:
        """The exact dict shape :meth:`SLOEngine._collect` builds —
        ``{metric: (kind, edges, label_names, {key: value})}`` with
        histogram values as per-bucket count lists — restricted to
        ``wanted`` metric names, so the engine's history can be fed
        from this scrape instead of taking its own."""
        wanted = set(wanted)
        snap: dict = {}
        for name, kind, _help, label_names, edges, children in \
                self.entries:
            if name not in wanted:
                continue
            if kind == "histogram":
                snap[name] = ("h", edges, label_names,
                              {key: list(val[0])
                               for key, val in children})
            else:
                snap[name] = ("c", None, label_names,
                              {key: float(val) for key, val in children})
        return snap


def _label_str(label_names: Tuple[str, ...], key: Tuple[str, ...],
               extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [f'{n}="{_escape_label(v)}"'
             for n, v in zip(label_names, key)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{_escape_label(extra[1])}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


def take_scrape(*registries: MetricsRegistry,
                at: Optional[float] = None) -> Scrape:
    """One pass over ``registries`` capturing every family's kind,
    edges, and child values — the single scrape the dumper, the TSDB,
    and the SLO history share. ``at`` stamps the scrape (the
    recorder's clock); defaults to ``time.monotonic()``."""
    entries: List[tuple] = []
    for reg in registries:
        for fam in reg.families():
            if fam.kind == "histogram":
                children = []
                for key, c in sorted(fam.children()):
                    s = c.stats()
                    children.append(
                        (key, (s["buckets"], s["sum"], s["count"])))
                entries.append((fam.name, "histogram", fam.help,
                                fam.label_names, fam.buckets, children))
            else:
                children = [(key, float(c.value))
                            for key, c in sorted(fam.children())]
                entries.append((fam.name, fam.kind, fam.help,
                                fam.label_names, None, children))
    return Scrape(time.monotonic() if at is None else at, entries)


# ---------------------------------------------------------------------------
# The store: tiered rings, counter-reset-aware
# ---------------------------------------------------------------------------

#: default tiers as ``(resolution_s, retention_s)``: raw points for
#: 5 min, one point per 10 s for 30 min, one point per 60 s for 6 h.
#: Resolution 0 means "every scrape" (the raw ring).
DEFAULT_TIERS: Tuple[Tuple[float, float], ...] = (
    (0.0, 300.0),
    (10.0, 1800.0),
    (60.0, 21600.0),
)


class _Series:
    """One ``(name, labels)`` series: the reset-adjusted accumulator
    plus one ring per tier. Every stored point is ``(ts, raw,
    adjusted)`` — instant queries return ``raw``; ``rate()`` /
    ``increase()`` difference ``adjusted``, which only ever grows for
    counters (resets clamped at ingest, the SLOEngine delta idiom)."""

    __slots__ = ("name", "labels", "kind", "last_raw", "adjusted",
                 "rings", "cur_bucket", "pending")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 kind: str, n_tiers: int):
        self.name = name
        self.labels = labels
        self.kind = kind                      # "c" or "g"
        self.last_raw: Optional[float] = None
        self.adjusted = 0.0
        self.rings: List[deque] = [deque() for _ in range(n_tiers)]
        # per COARSE tier (index 1..): the open downsample bucket id
        # and its last-sample-wins pending point
        self.cur_bucket: List[Optional[int]] = [None] * n_tiers
        self.pending: List[Optional[tuple]] = [None] * n_tiers


class QueryError(ValueError):
    """A malformed query expression (HTTP callers get a 400)."""


_DURATION_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(ms|s|m|h)?\s*$")
_DUR_SCALE = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, None: 1.0}


def parse_duration(text: str) -> float:
    """``"150ms" | "10s" | "5m" | "1h" | "30"`` -> seconds."""
    m = _DURATION_RE.match(str(text))
    if not m:
        raise QueryError(f"bad duration {text!r}")
    return float(m.group(1)) * _DUR_SCALE[m.group(2)]


_FUNC_RE = re.compile(
    r"^\s*(rate|increase)\s*\(\s*(.+?)\s*\[\s*([^\]]+)\s*\]\s*\)\s*$")
_QUANT_RE = re.compile(
    r"^\s*quantile\s*\(\s*(\d*\.?\d+)\s*,\s*(.+?)"
    r"\s*\[\s*([^\]]+)\s*\]\s*\)\s*$")
_SEL_RE = re.compile(
    r"^\s*([a-zA-Z_:][a-zA-Z0-9_:]*)\s*(\{.*\})?\s*$")
_MATCHER_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)\s*(=~|!~|!=|=)\s*"((?:[^"\\]|\\.)*)"')
_MATCHERS_OK_RE = re.compile(
    r'^\{\s*(?:[a-zA-Z_][a-zA-Z0-9_]*\s*(?:=~|!~|!=|=)\s*'
    r'"(?:[^"\\]|\\.)*"\s*,?\s*)*\}$')


class _Matcher:
    __slots__ = ("label", "op", "value", "_re")

    def __init__(self, label: str, op: str, value: str):
        self.label = label
        self.op = op
        self.value = value
        self._re = None
        if op in ("=~", "!~"):
            try:
                # anchored like PromQL: the pattern must match the
                # WHOLE label value
                self._re = re.compile(value)
            except re.error as e:
                raise QueryError(f"bad regex {value!r}: {e}") from e

    def match(self, have: Dict[str, str]) -> bool:
        v = have.get(self.label, "")
        if self.op == "=":
            return v == self.value
        if self.op == "!=":
            return v != self.value
        hit = self._re.fullmatch(v) is not None
        return hit if self.op == "=~" else not hit


def _parse_selector(text: str) -> Tuple[str, List[_Matcher]]:
    m = _SEL_RE.match(text)
    if not m:
        raise QueryError(f"bad selector {text!r}")
    name, raw = m.groups()
    matchers: List[_Matcher] = []
    if raw:
        if not _MATCHERS_OK_RE.match(raw):
            raise QueryError(f"bad label matchers {raw!r}")
        for label, op, value in _MATCHER_RE.findall(raw):
            matchers.append(_Matcher(label, op,
                                     value.replace('\\"', '"')
                                          .replace("\\\\", "\\")))
    return name, matchers


def parse_expr(expr: str) -> tuple:
    """Parse one query expression into its evaluation form:

    * ``name{label="v",other=~"re"}``      -> ``("instant", ...)``
    * ``rate(sel[window])``                -> ``("rate", ...)``
    * ``increase(sel[window])``            -> ``("increase", ...)``
    * ``quantile(0.95, hist[window])``     -> ``("quantile", ...)``

    Raises :class:`QueryError` on anything else."""
    m = _QUANT_RE.match(expr)
    if m:
        q = float(m.group(1))
        if not 0.0 <= q <= 1.0:
            raise QueryError(f"quantile must be in [0, 1], got {q}")
        name, matchers = _parse_selector(m.group(2))
        return "quantile", q, name, matchers, parse_duration(m.group(3))
    m = _FUNC_RE.match(expr)
    if m:
        name, matchers = _parse_selector(m.group(2))
        return m.group(1), name, matchers, parse_duration(m.group(3))
    name, matchers = _parse_selector(expr)
    return "instant", name, matchers


class TimeSeriesStore:
    """Bounded in-process time-series storage with tiered
    downsampling.

    ``tiers`` is ``((resolution_s, retention_s), ...)`` finest first;
    resolution 0 = the raw ring (one point per scrape). Downsampling
    is last-sample-wins per resolution bucket — correct for the
    cumulative counters and gauges the exposition carries (a counter's
    last sample in a window IS its state at the window's edge), and it
    keeps the adjusted accumulator exact across tiers. Retention is
    enforced at ingest from each series' newest timestamp, so memory
    is bounded by ``retention / resolution`` points per tier per
    series and ``max_series`` series overall (past the cap new series
    are dropped and counted, never grown without bound)."""

    def __init__(self,
                 tiers: Tuple[Tuple[float, float], ...] = DEFAULT_TIERS,
                 max_series: int = 8192,
                 lookback_s: float = 300.0,
                 raw_max_points: int = 4096):
        tiers = tuple((float(r), float(ret)) for r, ret in tiers)
        if not tiers or tiers[0][0] != 0.0:
            raise ValueError(
                "tiers must start with the raw ring (resolution 0), "
                f"got {tiers!r}")
        if any(a[0] >= b[0] for a, b in zip(tiers[1:], tiers[2:])):
            raise ValueError(
                f"tier resolutions must be increasing: {tiers!r}")
        self.tiers = tiers
        self.max_series = int(max_series)
        self.lookback_s = float(lookback_s)
        self.raw_max_points = int(raw_max_points)
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           _Series] = {}
        self._lock = threading.Lock()
        self._last_ts: Optional[float] = None
        self.n_points = 0
        self.n_dropped_series = 0

    # -- ingest --------------------------------------------------------------

    def ingest(self, scrape: Scrape) -> int:
        """Ingest one :class:`Scrape`; returns points written."""
        return self.ingest_rows(scrape.at, scrape.rows())

    def ingest_rows(self, ts: float,
                    rows: Iterable[Tuple[str, tuple, float, str]]
                    ) -> int:
        ts = float(ts)
        n = 0
        with self._lock:
            for name, labels, value, kind in rows:
                if self._write_locked(ts, name, tuple(labels), value,
                                      kind):
                    n += 1
            if self._last_ts is None or ts > self._last_ts:
                self._last_ts = ts
        return n

    def write(self, ts: float, name: str, labels: Any, value: float,
              kind: str = "g") -> bool:
        """One derived point (recording rules, tests). ``labels`` is a
        dict or a tuple of pairs."""
        if isinstance(labels, dict):
            labels = tuple(sorted(labels.items()))
        with self._lock:
            ok = self._write_locked(float(ts), name, tuple(labels),
                                    float(value), kind)
            if ok and (self._last_ts is None or ts > self._last_ts):
                self._last_ts = float(ts)
            return ok

    def _write_locked(self, ts: float, name: str, labels: tuple,
                      value: float, kind: str) -> bool:
        key = (name, labels)
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= self.max_series:
                self.n_dropped_series += 1
                return False
            s = _Series(name, labels, kind, len(self.tiers))
            self._series[key] = s
        # counter-reset-aware adjustment (the SLOEngine delta clamp): a
        # value below its predecessor is a restart — the delta is the
        # post-reset count, never negative
        if s.kind == "c":
            prev = s.last_raw
            if prev is None:
                s.adjusted = value
            else:
                s.adjusted += (value - prev) if value >= prev else value
        else:
            s.adjusted = value
        s.last_raw = value
        point = (ts, value, s.adjusted)
        raw = s.rings[0]
        raw.append(point)
        raw_keep = self.tiers[0][1]
        while raw and (ts - raw[0][0] > raw_keep
                       or len(raw) > self.raw_max_points):
            raw.popleft()
        # roll into the coarser tiers: last sample wins inside a
        # resolution bucket; the bucket flushes when a sample lands in
        # a NEWER bucket (queries read the open bucket via `pending`)
        for i in range(1, len(self.tiers)):
            res, keep = self.tiers[i]
            b = int(ts // res)
            if s.cur_bucket[i] is None or b == s.cur_bucket[i]:
                s.cur_bucket[i] = b
                s.pending[i] = point
                continue
            if s.pending[i] is not None:
                ring = s.rings[i]
                ring.append(s.pending[i])
                while ring and ts - ring[0][0] > keep:
                    ring.popleft()
            s.cur_bucket[i] = b
            s.pending[i] = point
        self.n_points += 1
        return True

    # -- selection -----------------------------------------------------------

    def _select(self, name: str, matchers: List[_Matcher]
                ) -> List[_Series]:
        out = []
        for (n, _labels), s in self._series.items():
            if n != name:
                continue
            have = dict(s.labels)
            if all(m.match(have) for m in matchers):
                out.append(s)
        return out

    @staticmethod
    def _window_points(s: _Series, t0: float, t1: float) -> List[tuple]:
        """Every retained point in ``[t0, t1]``, merged across tiers
        (coarse history + fine recency; duplicate timestamps collapse,
        finest tier wins). Sorted by timestamp."""
        by_ts: Dict[float, tuple] = {}
        for i in range(len(s.rings) - 1, -1, -1):
            for p in s.rings[i]:
                if t0 <= p[0] <= t1:
                    by_ts[p[0]] = p
            if i > 0 and s.pending[i] is not None:
                p = s.pending[i]
                if t0 <= p[0] <= t1:
                    by_ts[p[0]] = p
        return [by_ts[k] for k in sorted(by_ts)]

    def _instant(self, s: _Series, at: float) -> Optional[float]:
        pts = self._window_points(s, at - self.lookback_s, at)
        return pts[-1][1] if pts else None

    def _delta(self, s: _Series, at: float, window: float,
               per_second: bool) -> Optional[float]:
        pts = self._window_points(s, at - window, at)
        if len(pts) < 2:
            return None
        d = pts[-1][2] - pts[0][2]
        if not per_second:
            return d
        span = pts[-1][0] - pts[0][0]
        return d / span if span > 0 else None

    def _quantile_groups(self, name: str, matchers: List[_Matcher]
                         ) -> Dict[tuple, List[Tuple[float, _Series]]]:
        """Histogram ``_bucket`` series grouped by their non-``le``
        labels: ``{group_labels: [(le_float, series), ...]}``."""
        groups: Dict[tuple, List[Tuple[float, _Series]]] = {}
        for s in self._select(name + "_bucket", matchers):
            have = dict(s.labels)
            le = have.pop("le", None)
            if le is None:
                continue
            edge = float("inf") if le == "+Inf" else float(le)
            groups.setdefault(tuple(sorted(have.items())),
                              []).append((edge, s))
        for rows in groups.values():
            rows.sort(key=lambda r: r[0])
        return groups

    def _quantile_at(self, rows: List[Tuple[float, _Series]], q: float,
                     at: float, window: float) -> Optional[float]:
        """One group's quantile over the window: cumulative adjusted
        deltas per ``le``, differenced into per-bucket counts, then
        :func:`quantile_from_buckets`."""
        edges: List[float] = []
        cums: List[float] = []
        for edge, s in rows:
            d = self._delta(s, at, window, per_second=False)
            if d is None:
                return None
            edges.append(edge)
            cums.append(d)
        if not edges or edges[-1] != float("inf"):
            return None
        counts = [cums[0]] + [cums[i] - cums[i - 1]
                              for i in range(1, len(cums))]
        # clamp scrape-skew artifacts: cumulative deltas are
        # monotone in `le` on any single scrape pair
        counts = [max(c, 0.0) for c in counts]
        return quantile_from_buckets(tuple(edges[:-1]), counts, q)

    # -- the query plane -----------------------------------------------------

    def query(self, expr: str, at: Optional[float] = None
              ) -> Dict[str, Any]:
        """Instant query: ``{"expr", "at", "results": [{"labels",
        "value"}, ...]}``. ``at`` defaults to the newest ingested
        timestamp (data-relative, so ManualClock tests and live
        workers read the same way)."""
        parsed = parse_expr(expr)
        with self._lock:
            at = self._resolve_at(at)
            results = self._eval_locked(parsed, at)
        return {"expr": expr, "at": at, "results": results}

    def query_range(self, expr: str, start: Optional[float] = None,
                    end: Optional[float] = None,
                    step: float = 10.0) -> Dict[str, Any]:
        """Range query: the expression evaluated at each ``step`` from
        ``start`` to ``end`` (inclusive), one ``{"labels", "points":
        [[ts, value], ...]}`` entry per series. Defaults: ``end`` =
        newest ingested timestamp, ``start`` = ``end - 300``. A
        NEGATIVE ``start`` is relative to ``end`` (``start=-600`` =
        the trailing 10 minutes) — store timestamps ride a monotonic
        clock a remote caller cannot know, relative windows are the
        usable remote form."""
        parsed = parse_expr(expr)
        step = float(step)
        if step <= 0:
            raise QueryError(f"step must be > 0, got {step}")
        with self._lock:
            end = self._resolve_at(end)
            start = float(start) if start is not None else -300.0
            if start < 0:
                start = end + start
            if end < start:
                raise QueryError(f"end {end} < start {start}")
            n_steps = int((end - start) / step) + 1
            if n_steps > 11_000:
                raise QueryError(
                    f"{n_steps} evaluation steps (max 11000) — raise "
                    "step or narrow the window")
            series: Dict[tuple, List[List[float]]] = {}
            order: List[tuple] = []
            for i in range(n_steps):
                t = start + i * step
                for row in self._eval_locked(parsed, t):
                    key = tuple(sorted(row["labels"].items()))
                    if key not in series:
                        series[key] = []
                        order.append(key)
                    series[key].append([t, row["value"]])
        return {"expr": expr, "start": start, "end": end, "step": step,
                "series": [{"labels": dict(k), "points": series[k]}
                           for k in order]}

    def _resolve_at(self, at: Optional[float]) -> float:
        if at is not None:
            return float(at)
        return self._last_ts if self._last_ts is not None else 0.0

    def _eval_locked(self, parsed: tuple, at: float
                     ) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        if parsed[0] == "quantile":
            _, q, name, matchers, window = parsed
            for key, rows in sorted(
                    self._quantile_groups(name, matchers).items()):
                v = self._quantile_at(rows, q, at, window)
                if v is not None:
                    out.append({"labels": dict(key), "value": v})
            return out
        if parsed[0] in ("rate", "increase"):
            _, name, matchers, window = parsed
            for s in sorted(self._select(name, matchers),
                            key=lambda s: s.labels):
                v = self._delta(s, at, window,
                                per_second=parsed[0] == "rate")
                if v is not None:
                    out.append({"labels": dict(s.labels), "value": v})
            return out
        _, name, matchers = parsed
        for s in sorted(self._select(name, matchers),
                        key=lambda s: s.labels):
            v = self._instant(s, at)
            if v is not None:
                out.append({"labels": dict(s.labels), "value": v})
        return out

    # -- observability of the observer ---------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._lock:
            tier_points = [0] * len(self.tiers)
            for s in self._series.values():
                for i, ring in enumerate(s.rings):
                    tier_points[i] += len(ring)
            return {
                "n_series": len(self._series),
                "max_series": self.max_series,
                "n_points_ingested": self.n_points,
                "n_dropped_series": self.n_dropped_series,
                "last_ts": self._last_ts,
                "tiers": [{"resolution_s": r, "retention_s": keep,
                           "points": tier_points[i]}
                          for i, (r, keep) in enumerate(self.tiers)],
            }


# ---------------------------------------------------------------------------
# Recording rules
# ---------------------------------------------------------------------------

class RecordingRule:
    """Precompute one hot expression per tick into a derived gauge
    series (the Prometheus ``level:metric:operation`` naming
    convention — colons are valid metric-name characters and signal
    "recorded, not scraped"). The rule's instant result rides the same
    tiers/retention as scraped series, so ``/query_range`` answers
    over it directly without re-deriving per step."""

    def __init__(self, record: str, expr: str,
                 labels: Optional[Dict[str, str]] = None):
        self.record = str(record)
        self.expr = str(expr)
        self._parsed = parse_expr(self.expr)   # fail at construction
        self.static = dict(labels or {})
        self.n_errors = 0

    def evaluate(self, store: TimeSeriesStore, now: float) -> int:
        n = 0
        res = store.query(self.expr, at=now)
        for row in res["results"]:
            labels = dict(row["labels"])
            labels.update(self.static)
            store.write(now, self.record, labels, row["value"],
                        kind="g")
            n += 1
        return n

    def to_dict(self) -> Dict[str, Any]:
        out = {"record": self.record, "expr": self.expr}
        if self.static:
            out["labels"] = dict(self.static)
        return out

    @classmethod
    def from_value(cls, value: Any) -> "RecordingRule":
        if isinstance(value, RecordingRule):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise ValueError(
            f"cannot build a RecordingRule from {type(value).__name__}")


def default_serving_rules(has_decoder: bool = False,
                          has_tenancy: bool = False
                          ) -> List[RecordingRule]:
    """The stock per-worker recording rules: the hot series an
    operator asks for first, precomputed every tick."""
    rules = [
        RecordingRule("serving:dispatch_latency_ms:p95",
                      "quantile(0.95, serving_dispatch_latency_ms"
                      "[300s])"),
        RecordingRule("serving:requests:rate1m",
                      "rate(serving_requests_total[60s])"),
        RecordingRule("serving:errors:rate1m",
                      "rate(serving_errors_total[60s])"),
        RecordingRule("serving:recompiles:rate5m",
                      "rate(serving_recompiles_total[300s])"),
        RecordingRule("serving:tenant_device_ms:rate5m",
                      "rate(serving_tenant_device_ms_total[300s])"),
    ]
    if has_decoder:
        rules += [
            RecordingRule("serving:decode_ttft_ms:p95",
                          "quantile(0.95, serving_decode_ttft_ms"
                          "[300s])"),
            RecordingRule("serving:decode_tpot_ms:p95",
                          "quantile(0.95, serving_decode_tpot_ms"
                          "[300s])"),
            RecordingRule("serving:decode_tokens:rate1m",
                          "rate(serving_decode_tokens_total[60s])"),
        ]
    if has_tenancy:
        rules.append(
            RecordingRule("serving:tenant_shed:rate5m",
                          "rate(serving_tenant_shed_total[300s])"))
    return rules


# ---------------------------------------------------------------------------
# Baseline-relative anomaly detection
# ---------------------------------------------------------------------------

class AnomalyWatch:
    """One watched expression: fire when the instant value deviates
    from its own EWMA baseline by more than ``z_threshold`` robust
    z-units (EWMA of absolute deviation, MAD-style, scaled by 1.4826
    to estimate sigma) AND by at least ``min_abs`` in raw units (the
    absolute floor keeps a near-zero-variance baseline from turning
    measurement noise into sigmas). No verdict before ``min_samples``
    baseline points (warm-up guard); hysteresis via ``for_s`` /
    ``resolve_after_s`` exactly like an SLO policy."""

    def __init__(self, name: str, expr: str, direction: str = "high",
                 z_threshold: float = 6.0, min_samples: int = 30,
                 alpha: float = 0.1, min_abs: float = 0.0,
                 for_s: float = 0.0, resolve_after_s: float = 60.0):
        if direction not in ("high", "low", "both"):
            raise ValueError(f"unknown direction {direction!r}")
        self.name = str(name)
        self.expr = str(expr)
        self._parsed = parse_expr(self.expr)   # fail at construction
        self.direction = direction
        self.z_threshold = float(z_threshold)
        self.min_samples = int(min_samples)
        self.alpha = float(alpha)
        self.min_abs = float(min_abs)
        self.for_s = float(for_s)
        self.resolve_after_s = float(resolve_after_s)

    @classmethod
    def from_value(cls, value: Any) -> "AnomalyWatch":
        if isinstance(value, AnomalyWatch):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise ValueError(
            f"cannot build an AnomalyWatch from {type(value).__name__}")

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "expr": self.expr,
                "direction": self.direction,
                "z_threshold": self.z_threshold,
                "min_samples": self.min_samples, "alpha": self.alpha,
                "min_abs": self.min_abs, "for_s": self.for_s,
                "resolve_after_s": self.resolve_after_s}


class _WatchState:
    """Per-(watch, labelset) detector state: the EWMA baseline and an
    alert state machine with the SLO engine's exact lifecycle
    (``ok -> pending --for_s--> firing --quiet resolve_after_s-->
    resolved``, quiet clock counted from the last violated tick)."""

    __slots__ = ("ewma", "mad", "n", "last_value", "last_z",
                 "state", "pending_since", "last_violated", "fired_at",
                 "resolved_at", "n_fired", "n_resolved")

    def __init__(self):
        self.ewma: Optional[float] = None
        self.mad = 0.0
        self.n = 0
        self.last_value: Optional[float] = None
        self.last_z: Optional[float] = None
        self.state = "ok"
        self.pending_since: Optional[float] = None
        self.last_violated: Optional[float] = None
        self.fired_at: Optional[float] = None
        self.resolved_at: Optional[float] = None
        self.n_fired = 0
        self.n_resolved = 0


def _advance_watch(st: _WatchState, violated: bool, now: float,
                   for_s: float, resolve_after_s: float
                   ) -> Optional[str]:
    """Advance one state machine; returns ``"firing"``/``"resolved"``
    on a notifiable transition, None otherwise (mirrors
    ``SLOEngine._advance_alert``)."""
    if violated:
        st.last_violated = now
        if st.state in ("ok", "resolved"):
            st.state = "pending"
            st.pending_since = now
        if st.state == "pending" and \
                now - (st.pending_since or now) >= for_s:
            st.state = "firing"
            st.fired_at = now
            st.n_fired += 1
            return "firing"
        return None
    if st.state == "pending":
        st.state = "ok"
        st.pending_since = None
    elif st.state == "firing":
        ref = st.last_violated if st.last_violated is not None \
            else (st.fired_at or now)
        if now - ref >= resolve_after_s:
            st.state = "resolved"
            st.resolved_at = now
            st.n_resolved += 1
            return "resolved"
    return None


class AnomalyDetector:
    """Baseline-relative regression detection over recorded series.

    Each tick (driven by the :class:`Recorder`), every watch's
    expression is evaluated instantly against the store and each
    resulting labelset is scored against its own EWMA + MAD baseline.
    The baseline is FROZEN while the point violates — a sustained
    regression cannot teach itself normal; it resolves when the cause
    reverts, which is exactly what the chaos drill exercises.
    Transitions flow through the same
    :class:`~mmlspark_tpu.serving.slo.AlertNotifier` the SLO engine
    uses (when one is wired), with the violating series' labels as
    attribution."""

    def __init__(self, store: TimeSeriesStore,
                 watches: Iterable[AnomalyWatch],
                 clock: Clock = SYSTEM_CLOCK, notifier=None,
                 max_states: int = 1024):
        self.store = store
        self.watches = [AnomalyWatch.from_value(w) for w in watches]
        names = [w.name for w in self.watches]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate watch names in {names}")
        self.clock = clock
        self.notifier = notifier
        self.max_states = int(max_states)
        self._states: Dict[Tuple[str, tuple], _WatchState] = {}
        self._lock = threading.Lock()
        self.n_observations = 0
        self.n_states_dropped = 0

    def observe(self, now: Optional[float] = None
                ) -> List[Dict[str, Any]]:
        """One detection pass; returns (and notifies) the
        transitions."""
        now = self.clock.now() if now is None else float(now)
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            self.n_observations += 1
            for watch in self.watches:
                res = self.store.query(watch.expr, at=now)
                for row in res["results"]:
                    key = (watch.name,
                           tuple(sorted(row["labels"].items())))
                    st = self._states.get(key)
                    if st is None:
                        if len(self._states) >= self.max_states:
                            self.n_states_dropped += 1
                            continue
                        st = self._states[key] = _WatchState()
                    ev = self._score(watch, st, row["labels"],
                                     float(row["value"]), now)
                    if ev is not None:
                        transitions.append(ev)
        if self.notifier is not None:
            for ev in transitions:
                self.notifier.notify(ev)
        return transitions

    def _score(self, watch: AnomalyWatch, st: _WatchState,
               labels: Dict[str, str], x: float, now: float
               ) -> Optional[Dict[str, Any]]:
        violated = False
        z = None
        if st.n >= watch.min_samples and st.ewma is not None:
            sigma = 1.4826 * st.mad + 1e-9
            dev = x - st.ewma
            z = dev / sigma
            if watch.direction == "high":
                violated = z > watch.z_threshold and dev >= watch.min_abs
            elif watch.direction == "low":
                violated = (z < -watch.z_threshold
                            and -dev >= watch.min_abs)
            else:
                violated = (abs(z) > watch.z_threshold
                            and abs(dev) >= watch.min_abs)
        st.last_value = x
        st.last_z = z
        if not violated:
            # the baseline learns ONLY from non-violating points: a
            # regression in progress must not drag its own baseline up
            # (it resolves when the cause reverts, not by habituation)
            if st.ewma is None:
                st.ewma = x
            else:
                a = watch.alpha
                st.mad = (1 - a) * st.mad + a * abs(x - st.ewma)
                st.ewma = (1 - a) * st.ewma + a * x
            st.n += 1
        kind = _advance_watch(st, violated, now, watch.for_s,
                              watch.resolve_after_s)
        if kind is None:
            return None
        return {"type": kind, "policy": watch.name,
                "slo_kind": "anomaly", "expr": watch.expr,
                "at_mono": now, "at_unix": time.time(),
                "labels": dict(labels),
                "value": x, "z": round(z, 3) if z is not None else None,
                "baseline": (round(st.ewma, 6)
                             if st.ewma is not None else None),
                "direction": watch.direction}

    # -- views ---------------------------------------------------------------

    def alerts(self) -> Dict[str, Any]:
        """The compact anomaly view merged into ``GET /alerts``: one
        entry per non-ok (or recently resolved) watch state, labels as
        attribution."""
        with self._lock:
            rows = []
            firing = 0
            for (name, labels), st in sorted(self._states.items()):
                if st.state == "firing":
                    firing += 1
                if st.state == "ok" and st.n_fired == 0:
                    continue
                rows.append({
                    "watch": name, "labels": dict(labels),
                    "state": st.state, "value": st.last_value,
                    "z": (round(st.last_z, 3)
                          if st.last_z is not None else None),
                    "baseline": (round(st.ewma, 6)
                                 if st.ewma is not None else None),
                    "fired_at": st.fired_at,
                    "resolved_at": st.resolved_at,
                    "n_fired": st.n_fired,
                })
            return {"firing": firing, "alerts": rows}

    def status(self) -> Dict[str, Any]:
        with self._lock:
            states = list(self._states.values())
            return {
                "n_watches": len(self.watches),
                "n_states": len(states),
                "n_observations": self.n_observations,
                "n_warming": sum(
                    1 for st in states
                    if st.n < max(w.min_samples
                                  for w in self.watches)),
                "firing": sum(1 for st in states
                              if st.state == "firing"),
                "n_fired": sum(st.n_fired for st in states),
            }


def default_serving_watches(has_decoder: bool = False
                            ) -> List[AnomalyWatch]:
    """The stock regression watches over the stock recording rules:
    deliberately conservative (z=6, absolute floors, 30-sample
    warm-up) — the acceptance bar is ZERO steady-state false
    positives; a real latency regression or recompile storm clears
    these thresholds by an order of magnitude."""
    watches = [
        AnomalyWatch("dispatch_p95_regression",
                     "serving:dispatch_latency_ms:p95",
                     direction="high", min_abs=5.0),
        AnomalyWatch("error_rate_regression",
                     "serving:errors:rate1m",
                     direction="high", min_abs=0.5),
        AnomalyWatch("recompile_storm",
                     "serving:recompiles:rate5m",
                     direction="high", min_abs=0.2),
    ]
    if has_decoder:
        watches += [
            AnomalyWatch("decode_ttft_regression",
                         "serving:decode_ttft_ms:p95",
                         direction="high", min_abs=25.0),
            AnomalyWatch("decode_tpot_regression",
                         "serving:decode_tpot_ms:p95",
                         direction="high", min_abs=5.0),
        ]
    return watches


# ---------------------------------------------------------------------------
# The recorder: one scrape per interval, four consumers
# ---------------------------------------------------------------------------

class Recorder:
    """The background pump of the retrospective plane.

    Each tick takes ONE scrape of the configured registries and feeds
    every consumer from it: TSDB ingest, the optional ``.prom`` dump
    (the :class:`~mmlspark_tpu.core.telemetry.MetricsSnapshot` role —
    a server wiring a Recorder with ``snapshot_dir`` must NOT also run
    a MetricsSnapshot, that is exactly the double-scrape this class
    removes), and the optional SLO engine's snapshot history (via
    :meth:`SLOEngine.observe`). Recording rules and the anomaly
    detector then run over the freshly-ingested store.

    The scrape+ingest cost is measured every tick against
    ``ingest_budget_ms`` — ``last_ingest_ms`` / ``ewma_ingest_ms`` /
    ``n_over_budget`` make the observer's own overhead observable, and
    ``bench.py tsdb_overhead_v1`` gates it."""

    def __init__(self, registries: Iterable[MetricsRegistry],
                 store: Optional[TimeSeriesStore] = None,
                 interval_s: float = 10.0,
                 clock: Clock = SYSTEM_CLOCK,
                 snapshot_dir: Optional[str] = None,
                 snapshot_keep: int = 24,
                 snapshot_prefix: str = "metrics",
                 slo=None,
                 rules: Iterable[RecordingRule] = (),
                 detector: Optional[AnomalyDetector] = None,
                 ingest_budget_ms: float = 25.0):
        self.registries = tuple(registries)
        self.store = store if store is not None else TimeSeriesStore()
        self.interval_s = float(interval_s)
        self.clock = clock
        self.snapshot_dir = snapshot_dir
        self.snapshot_keep = int(snapshot_keep)
        self.snapshot_prefix = snapshot_prefix
        self.slo = slo
        self.rules = [RecordingRule.from_value(r) for r in rules]
        self.detector = detector
        self.ingest_budget_ms = float(ingest_budget_ms)
        self.n_scrapes = 0
        self.n_points = 0
        self.n_rule_errors = 0
        self.n_snapshot_errors = 0
        self.n_over_budget = 0
        self.last_ingest_ms = 0.0
        self.ewma_ingest_ms = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def record_now(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One full tick: scrape once, feed every consumer. Never
        raises (telemetry must never kill the process); per-consumer
        failures are counted and logged."""
        now = self.clock.now() if now is None else float(now)
        t0 = time.perf_counter()
        scrape = take_scrape(*self.registries, at=now)
        n = self.store.ingest(scrape)
        # the perf-gated budget covers scrape + ingest — the part that
        # scales with registry size and runs unconditionally
        ms = (time.perf_counter() - t0) * 1000.0
        self.n_scrapes += 1
        self.n_points += n
        self.last_ingest_ms = ms
        self.ewma_ingest_ms = (ms if self.n_scrapes == 1
                               else 0.9 * self.ewma_ingest_ms + 0.1 * ms)
        if ms > self.ingest_budget_ms:
            self.n_over_budget += 1
        if self.slo is not None:
            try:
                self.slo.observe(
                    now, scrape.slo_snapshot(self.slo.wanted_metrics()))
            except Exception:  # noqa: BLE001 — never kill the tick
                from mmlspark_tpu.core.logs import get_logger
                get_logger("tsdb").warning(
                    "SLO snapshot feed failed", exc_info=True)
        for rule in self.rules:
            try:
                rule.evaluate(self.store, now)
            except Exception:  # noqa: BLE001
                rule.n_errors += 1
                self.n_rule_errors += 1
        transitions: List[Dict[str, Any]] = []
        if self.detector is not None:
            try:
                transitions = self.detector.observe(now)
            except Exception:  # noqa: BLE001
                from mmlspark_tpu.core.logs import get_logger
                get_logger("tsdb").warning(
                    "anomaly detection tick failed", exc_info=True)
        if self.snapshot_dir:
            try:
                from mmlspark_tpu.core.telemetry import write_snapshot
                write_snapshot(self.snapshot_dir, scrape.render(),
                               prefix=self.snapshot_prefix,
                               keep=self.snapshot_keep)
            except Exception:  # noqa: BLE001
                self.n_snapshot_errors += 1
                from mmlspark_tpu.core.logs import get_logger
                get_logger("tsdb").warning(
                    "metrics snapshot to %s failed", self.snapshot_dir,
                    exc_info=True)
        return {"at": now, "points": n, "ingest_ms": round(ms, 3),
                "transitions": transitions}

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.record_now()
            except Exception:  # noqa: BLE001 — belt over braces
                from mmlspark_tpu.core.logs import get_logger
                get_logger("tsdb").warning(
                    "recorder tick raised", exc_info=True)

    def start(self) -> "Recorder":
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tsdb-recorder")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the pump and take one final tick, so a clean shutdown
        leaves the terminal counters in the store and (when dumping)
        on disk — the MetricsSnapshot final-flush contract."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self.record_now()
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self) -> "Recorder":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def status(self) -> Dict[str, Any]:
        return {
            "interval_s": self.interval_s,
            "n_scrapes": self.n_scrapes,
            "n_points": self.n_points,
            "last_ingest_ms": round(self.last_ingest_ms, 3),
            "ewma_ingest_ms": round(self.ewma_ingest_ms, 3),
            "ingest_budget_ms": self.ingest_budget_ms,
            "n_over_budget": self.n_over_budget,
            "n_rule_errors": self.n_rule_errors,
            "n_snapshot_errors": self.n_snapshot_errors,
            "n_rules": len(self.rules),
            "snapshot_dir": self.snapshot_dir,
            "store": self.store.status(),
            "anomalies": (self.detector.status()
                          if self.detector is not None else None),
        }
