"""Stage persistence: JSON params + npz arrays in a directory.

Capability parity with the reference's save/load machinery (Spark ML
persistence extended by `ComplexParamsWritable`/`ConstructorWritable`,
`core/serialize/src/main/scala/`): every stage saves to a directory with
``metadata.json`` (class name, version, JSON params) and, when needed,
``arrays.npz`` plus stage-specific extra files written by ``_save_extra``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from mmlspark_tpu.core import registry
from mmlspark_tpu.version import __version__

METADATA_FILE = "metadata.json"
ARRAYS_FILE = "arrays.npz"


def save_stage(stage, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    meta: Dict[str, Any] = {
        "class": f"{type(stage).__module__}.{type(stage).__qualname__}",
        "framework_version": __version__,
        "uid": stage.uid,
        "params": _jsonify(stage._json_params()),
    }
    arrays: Dict[str, np.ndarray] = {}
    stage._save_extra(path, arrays)
    if arrays:
        np.savez_compressed(os.path.join(path, ARRAYS_FILE), **arrays)
    with open(os.path.join(path, METADATA_FILE), "w") as f:
        json.dump(meta, f, indent=2, default=_json_default)


def load_stage(path: str):
    with open(os.path.join(path, METADATA_FILE)) as f:
        meta = json.load(f)
    cls = registry.resolve(meta["class"])
    stage = cls.__new__(cls)
    stage._param_values = {}
    stage._uid = meta.get("uid")
    stage.set(**meta.get("params", {}))
    arrays: Dict[str, np.ndarray] = {}
    npz_path = os.path.join(path, ARRAYS_FILE)
    if os.path.exists(npz_path):
        with np.load(npz_path, allow_pickle=True) as npz:
            arrays = {k: npz[k] for k in npz.files}
    stage._load_extra(path, arrays)
    return stage


def _jsonify(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    return _json_default(obj) if isinstance(obj, (np.generic, np.ndarray)) else obj


def _json_default(obj: Any) -> Any:
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError(f"not JSON serializable: {type(obj)}")
