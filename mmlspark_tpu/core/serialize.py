"""Stage persistence: JSON params + npz arrays in a directory.

Capability parity with the reference's save/load machinery (Spark ML
persistence extended by `ComplexParamsWritable`/`ConstructorWritable`,
`core/serialize/src/main/scala/`): every stage saves to a directory with
``metadata.json`` (class name, version, JSON params) and, when needed,
``arrays.npz`` plus stage-specific extra files written by ``_save_extra``.

Integrity: every save finishes by writing a SHA-256 manifest
(``checkpoint.sha256.json``, :mod:`mmlspark_tpu.io.checkpoint`) over the
whole checkpoint tree; every load verifies it. A corrupted/truncated
checkpoint raises :class:`~mmlspark_tpu.io.checkpoint.
CheckpointIntegrityError` instead of loading garbage weights; a
digest-less legacy checkpoint loads with a warning (backward compat).
The serving rollout path additionally requires a *present and valid*
manifest before a model version becomes flip-eligible.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

import numpy as np

from mmlspark_tpu.core import registry
from mmlspark_tpu.version import __version__

#: nesting depth of in-flight load_stage calls (per thread): the
#: top-level manifest covers the whole tree, so only depth 0 verifies
_LOAD_DEPTH = threading.local()

METADATA_FILE = "metadata.json"
ARRAYS_FILE = "arrays.npz"


def save_stage(stage, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    meta: Dict[str, Any] = {
        "class": f"{type(stage).__module__}.{type(stage).__qualname__}",
        "framework_version": __version__,
        "uid": stage.uid,
        "params": _jsonify(stage._json_params()),
    }
    arrays: Dict[str, np.ndarray] = {}
    stage._save_extra(path, arrays)
    if arrays:
        np.savez_compressed(os.path.join(path, ARRAYS_FILE), **arrays)
    with open(os.path.join(path, METADATA_FILE), "w") as f:
        json.dump(meta, f, indent=2, default=_json_default)
    # the digest manifest goes LAST: an interrupted save leaves a
    # missing/stale manifest, never a valid-looking one over torn files
    from mmlspark_tpu.io import checkpoint as _ckpt
    _ckpt.write_digest(path)


def load_stage(path: str, verify: bool = True):
    # A manifest pins the WHOLE tree under its directory (substage
    # subdirectories included), so the top-level verification already
    # covered every nested checkpoint: nested loads (Pipeline stages,
    # wrapper substages — they re-enter here via PipelineStage.load)
    # skip re-hashing, or a depth-k pipeline would hash its leaves
    # k+1 times. Thread-local so concurrent loads can't cross-talk.
    depth = getattr(_LOAD_DEPTH, "n", 0)
    if verify and depth == 0:
        from mmlspark_tpu.io import checkpoint as _ckpt
        ok, detail = _ckpt.verify_digest(path, strict=False)
        if not ok:
            raise _ckpt.CheckpointIntegrityError(
                f"checkpoint {path} failed integrity verification: "
                f"{detail}")
    _LOAD_DEPTH.n = depth + 1
    try:
        return _load_stage_inner(path)
    finally:
        _LOAD_DEPTH.n = depth


def _load_stage_inner(path: str):
    with open(os.path.join(path, METADATA_FILE)) as f:
        meta = json.load(f)
    cls = registry.resolve(meta["class"])
    stage = cls.__new__(cls)
    stage._param_values = {}
    stage._uid = meta.get("uid")
    stage.set(**meta.get("params", {}))
    arrays: Dict[str, np.ndarray] = {}
    npz_path = os.path.join(path, ARRAYS_FILE)
    if os.path.exists(npz_path):
        with np.load(npz_path, allow_pickle=True) as npz:
            arrays = {k: npz[k] for k in npz.files}
    stage._load_extra(path, arrays)
    return stage


def _jsonify(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    return _json_default(obj) if isinstance(obj, (np.generic, np.ndarray)) else obj


def _json_default(obj: Any) -> Any:
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError(f"not JSON serializable: {type(obj)}")
