"""Platform introspection: chip type, topology, memory, host info.

Capability parity with `core/env/src/main/scala/EnvironmentUtils.scala:41-51`
(GPU discovery by shelling out to ``nvidia-smi -L``; OS detection) — the
TPU equivalent reads everything from the jax backend: device kind,
counts, process topology, per-device HBM stats when the runtime exposes
them. Used to stamp benchmark output and logs so recorded numbers are
interpretable (which chip, how many, which platform).
"""

from __future__ import annotations

import os
import platform as _platform
from typing import Any, Dict, Optional


def environment_info() -> Dict[str, Any]:
    """One JSON-able dict describing the accelerator + host environment.

    Safe to call before or after backend init; initializes the backend.
    """
    import jax

    devices = jax.devices()
    info: Dict[str, Any] = {
        "platform": devices[0].platform if devices else "none",
        "device_kind": devices[0].device_kind if devices else None,
        "n_devices": len(devices),
        "n_local_devices": jax.local_device_count(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "jax_version": jax.__version__,
        "host": {
            "os": _platform.system(),
            "machine": _platform.machine(),
            "python": _platform.python_version(),
            "cpu_count": os.cpu_count(),
        },
    }
    hbm = device_memory_stats(devices[0]) if devices else None
    if hbm:
        info["memory"] = hbm
    return info


def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """Per-device memory stats (bytes) when the runtime exposes them
    (TPU/GPU runtimes do; CPU returns None)."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    stats = getattr(dev, "memory_stats", None)
    if stats is None:
        return None
    try:
        raw = stats()
    except Exception:  # noqa: BLE001 - backend without stats support
        return None
    if not raw:
        return None
    keep = ("bytes_in_use", "bytes_limit", "peak_bytes_in_use",
            "bytes_reserved", "largest_free_block_bytes")
    return {k: int(raw[k]) for k in keep if k in raw}


def accelerator_count() -> int:
    """Parity: `EnvironmentUtils.GPUCount` — the number of accelerator
    devices visible to this process (0 on CPU-only hosts)."""
    import jax

    return sum(1 for d in jax.devices() if d.platform != "cpu")


def describe() -> str:
    """Human-readable one-liner for logs: platform/kind/counts/memory."""
    info = environment_info()
    parts = [f"{info['platform']}:{info['device_kind']}",
             f"{info['n_devices']} device(s)"]
    if info["process_count"] > 1:
        parts.append(f"process {info['process_index']}/"
                     f"{info['process_count']}")
    mem = info.get("memory")
    if mem and "bytes_limit" in mem:
        parts.append(f"{mem['bytes_limit'] / 2**30:.1f} GiB/device")
    return ", ".join(parts)
