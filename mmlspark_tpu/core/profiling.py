"""Profiling hooks: stage timing + device traces.

The reference's observability is wall-clock stage timing (`Timer` stage,
`pipeline-stages/Timer.scala:14-90`; suite timing in `TestBase.scala`).
The TPU build keeps that parity (the ``Timer`` stage in
``stages/basic.py``) and adds what the platform does natively: XLA
device traces viewable in TensorBoard/Perfetto via the jax profiler.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, Iterator, Optional


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax profiler trace (TensorBoard/Perfetto) around a block::

        with device_trace("/tmp/trace"):
            model.transform(df)
    """
    import jax
    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def timed_span(name: str, logger=None) -> Iterator[dict]:
    """Wall-clock span that also annotates the device trace.

    Yields a dict whose ``seconds`` key is filled on exit; logs through
    the framework logger when ``logger`` is None.
    """
    import jax
    out = {"name": name, "seconds": None}
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield out
    out["seconds"] = time.perf_counter() - t0
    if logger is None:
        from mmlspark_tpu.core.logs import get_logger
        logger = get_logger("profiling")
    logger.info("%s: %.3fs", name, out["seconds"])


class StageTimings:
    """Thread-safe per-stage wall-clock accumulator for hot loops.

    Where :func:`timed_span` logs one span, this aggregates millions:
    each ``span(name)`` adds one sample to the named stage's running
    count/total, and :meth:`snapshot` returns a JSON-able summary —
    the backing store for the serving data plane's per-stage timings in
    ``GET /stats``. Pure python (no jax import) so it costs nothing on
    hosts that never touch a device, and cheap enough (~1 us/span) to
    leave on in production.
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._stats: Dict[str, list] = {}   # name -> [count, total_s, last_s]

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            dt = self._clock() - t0
            with self._lock:
                s = self._stats.setdefault(name, [0, 0.0, 0.0])
                s[0] += 1
                s[1] += dt
                s[2] = dt

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """``{stage: {count, total_ms, mean_ms, last_ms}}``, JSON-able."""
        with self._lock:
            return {
                name: {"count": n,
                       "total_ms": round(total * 1000.0, 3),
                       "mean_ms": round(total / n * 1000.0, 4) if n else 0.0,
                       "last_ms": round(last * 1000.0, 3)}
                for name, (n, total, last) in self._stats.items()
            }
