"""Profiling hooks: stage timing + device traces.

The reference's observability is wall-clock stage timing (`Timer` stage,
`pipeline-stages/Timer.scala:14-90`; suite timing in `TestBase.scala`).
The TPU build keeps that parity (the ``Timer`` stage in
``stages/basic.py``) and adds what the platform does natively: XLA
device traces viewable in TensorBoard/Perfetto via the jax profiler.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Iterator, Optional


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax profiler trace (TensorBoard/Perfetto) around a block::

        with device_trace("/tmp/trace"):
            model.transform(df)
    """
    import jax
    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def timed_span(name: str, logger=None) -> Iterator[dict]:
    """Wall-clock span that also annotates the device trace.

    Yields a dict whose ``seconds`` key is filled on exit; logs through
    the framework logger when ``logger`` is None.
    """
    import jax
    out = {"name": name, "seconds": None}
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield out
    out["seconds"] = time.perf_counter() - t0
    if logger is None:
        from mmlspark_tpu.core.logs import get_logger
        logger = get_logger("profiling")
    logger.info("%s: %.3fs", name, out["seconds"])


class StageTimings:
    """Thread-safe per-stage wall-clock accumulator for hot loops.

    Where :func:`timed_span` logs one span, this aggregates millions:
    each ``span(name)`` adds one sample to the named stage's running
    count/total, and :meth:`snapshot` returns a JSON-able summary —
    the backing store for the serving data plane's per-stage timings in
    ``GET /stats``. Pure python (no jax import) so it costs nothing on
    hosts that never touch a device, and cheap enough (~1 us/span) to
    leave on in production.

    Since the unified-telemetry work this is a thin view over a
    :class:`mmlspark_tpu.core.telemetry.MetricsRegistry` histogram (one
    child per stage name, millisecond log-scale buckets): the SAME
    samples back both the ``GET /stats`` snapshot and the Prometheus
    ``GET /metrics`` exposition. Pass ``registry`` to land the spans in
    a shared registry (the serving plane passes its per-server one);
    the default is a private registry, preserving the standalone
    behavior.
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter,
                 registry=None, metric: str = "stage_duration_ms"):
        from mmlspark_tpu.core.telemetry import MetricsRegistry
        self._clock = clock
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._hist = self.registry.histogram(
            metric, "Per-stage wall-clock spans.", labels=("stage",))
        self._children: Dict[str, object] = {}   # stage -> histogram child

    def _child(self, name: str):
        child = self._children.get(name)     # atomic under the GIL
        if child is None:
            child = self._children[name] = self._hist.labels(name)
        return child

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            self._child(name).observe((self._clock() - t0) * 1000.0)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """``{stage: {count, total_ms, mean_ms, last_ms, max_ms}}``,
        JSON-able."""
        out: Dict[str, Dict[str, float]] = {}
        for key, child in self._hist.children():
            s = child.stats()
            n = s["count"]
            out[key[0]] = {
                "count": n,
                "total_ms": round(s["sum"], 3),
                "mean_ms": round(s["sum"] / n, 4) if n else 0.0,
                "last_ms": round(s["last"], 3),
                "max_ms": round(s["max"], 3),
            }
        return out

    def reset(self) -> None:
        """Zero every stage's accumulators (chaos drills diff snapshots
        across restarts; a long-soak harness resets between phases)."""
        for _, child in self._hist.children():
            child.reset()


# -- process vitals (exported via GET /stats so chaos drills can spot
# leaks and confirm restarts) ------------------------------------------------

_PROCESS_START_MONO = time.monotonic()


def process_uptime_s() -> float:
    """Seconds since this module first loaded — effectively process
    uptime; a restarted worker's counter visibly resets."""
    return time.monotonic() - _PROCESS_START_MONO


def process_rss_bytes() -> Optional[int]:
    """Current resident set size. Linux reads ``/proc/self/status``
    (current RSS); elsewhere falls back to ``ru_maxrss`` (PEAK RSS —
    still monotone evidence for leak spotting) or None."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(peak if sys.platform == "darwin" else peak * 1024)
    except Exception:  # noqa: BLE001 — vitals are best-effort
        return None
