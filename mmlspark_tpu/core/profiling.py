"""Profiling hooks: stage timing + device traces.

The reference's observability is wall-clock stage timing (`Timer` stage,
`pipeline-stages/Timer.scala:14-90`; suite timing in `TestBase.scala`).
The TPU build keeps that parity (the ``Timer`` stage in
``stages/basic.py``) and adds what the platform does natively: XLA
device traces viewable in TensorBoard/Perfetto via the jax profiler.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax profiler trace (TensorBoard/Perfetto) around a block::

        with device_trace("/tmp/trace"):
            model.transform(df)
    """
    import jax
    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def timed_span(name: str, logger=None) -> Iterator[dict]:
    """Wall-clock span that also annotates the device trace.

    Yields a dict whose ``seconds`` key is filled on exit; logs through
    the framework logger when ``logger`` is None.
    """
    import jax
    out = {"name": name, "seconds": None}
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield out
    out["seconds"] = time.perf_counter() - t0
    if logger is None:
        from mmlspark_tpu.core.logs import get_logger
        logger = get_logger("profiling")
    logger.info("%s: %.3fs", name, out["seconds"])
