"""Profiling hooks: stage timing + device traces.

The reference's observability is wall-clock stage timing (`Timer` stage,
`pipeline-stages/Timer.scala:14-90`; suite timing in `TestBase.scala`).
The TPU build keeps that parity (the ``Timer`` stage in
``stages/basic.py``) and adds what the platform does natively: XLA
device traces viewable in TensorBoard/Perfetto via the jax profiler.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Iterator, Optional


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax profiler trace (TensorBoard/Perfetto) around a block::

        with device_trace("/tmp/trace"):
            model.transform(df)
    """
    import jax
    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def timed_span(name: str, logger=None) -> Iterator[dict]:
    """Wall-clock span that also annotates the device trace.

    Yields a dict whose ``seconds`` key is filled on exit; logs through
    the framework logger when ``logger`` is None.
    """
    import jax
    out = {"name": name, "seconds": None}
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield out
    out["seconds"] = time.perf_counter() - t0
    if logger is None:
        from mmlspark_tpu.core.logs import get_logger
        logger = get_logger("profiling")
    logger.info("%s: %.3fs", name, out["seconds"])


class StageTimings:
    """Thread-safe per-stage wall-clock accumulator for hot loops.

    Where :func:`timed_span` logs one span, this aggregates millions:
    each ``span(name)`` adds one sample to the named stage's running
    count/total, and :meth:`snapshot` returns a JSON-able summary —
    the backing store for the serving data plane's per-stage timings in
    ``GET /stats``. Pure python (no jax import) so it costs nothing on
    hosts that never touch a device, and cheap enough (~1 us/span) to
    leave on in production.

    Since the unified-telemetry work this is a thin view over a
    :class:`mmlspark_tpu.core.telemetry.MetricsRegistry` histogram (one
    child per stage name, millisecond log-scale buckets): the SAME
    samples back both the ``GET /stats`` snapshot and the Prometheus
    ``GET /metrics`` exposition. Pass ``registry`` to land the spans in
    a shared registry (the serving plane passes its per-server one);
    the default is a private registry, preserving the standalone
    behavior.
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter,
                 registry=None, metric: str = "stage_duration_ms"):
        from mmlspark_tpu.core.telemetry import MetricsRegistry
        self._clock = clock
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._hist = self.registry.histogram(
            metric, "Per-stage wall-clock spans.", labels=("stage",))
        self._children: Dict[str, object] = {}   # stage -> histogram child

    def _child(self, name: str):
        child = self._children.get(name)     # atomic under the GIL
        if child is None:
            child = self._children[name] = self._hist.labels(name)
        return child

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            self._child(name).observe((self._clock() - t0) * 1000.0)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """``{stage: {count, total_ms, mean_ms, last_ms, max_ms}}``,
        JSON-able."""
        out: Dict[str, Dict[str, float]] = {}
        for key, child in self._hist.children():
            s = child.stats()
            n = s["count"]
            out[key[0]] = {
                "count": n,
                "total_ms": round(s["sum"], 3),
                "mean_ms": round(s["sum"] / n, 4) if n else 0.0,
                "last_ms": round(s["last"], 3),
                "max_ms": round(s["max"], 3),
            }
        return out

    def reset(self) -> None:
        """Zero every stage's accumulators (chaos drills diff snapshots
        across restarts; a long-soak harness resets between phases)."""
        for _, child in self._hist.children():
            child.reset()


# -- process vitals (exported via GET /stats so chaos drills can spot
# leaks and confirm restarts) ------------------------------------------------

_PROCESS_START_MONO = time.monotonic()


def process_uptime_s() -> float:
    """Seconds since this module first loaded — effectively process
    uptime; a restarted worker's counter visibly resets."""
    return time.monotonic() - _PROCESS_START_MONO


def process_rss_bytes() -> Optional[int]:
    """Current resident set size. Linux reads ``/proc/self/status``
    (current RSS); elsewhere falls back to ``ru_maxrss`` (PEAK RSS —
    still monotone evidence for leak spotting) or None."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(peak if sys.platform == "darwin" else peak * 1024)
    except Exception:  # noqa: BLE001 — vitals are best-effort
        return None


# ---------------------------------------------------------------------------
# On-demand device profiling + always-on compute accounting (ISSUE 18)
# ---------------------------------------------------------------------------

def device_memory_stats() -> Dict[str, int]:
    """HBM accounting straight from the runtime allocator of local
    device 0: live bytes, the high-water mark since process start, and
    the allocator's limit. Empty dict on backends that do not expose
    ``memory_stats`` (CPU) — callers gauge 0s, they never fail."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — vitals are best-effort
        return {}
    if not stats:
        return {}
    return {"bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes": int(stats.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0))}


class ProfilerBusy(RuntimeError):
    """A capture window is already running (one at a time, by design:
    concurrent jax profiler sessions abort the process)."""


class DeviceProfiler:
    """Guarded one-at-a-time ``jax.profiler`` capture windows.

    ``start_window`` kicks off a background daemon thread that opens a
    trace, sleeps the requested window, and closes it — the caller
    (a ``POST /profile`` handler on the event loop) returns
    immediately with the target directory. A second request while a
    window is open raises :class:`ProfilerBusy` (the route 409s).
    Output loads in TensorBoard / Perfetto / XProf.
    """

    def __init__(self, base_dir: Optional[str] = None):
        import os
        import tempfile
        import threading
        self.base_dir = base_dir or os.path.join(
            tempfile.gettempdir(), "mmlspark_tpu_profiles")
        self._lock = threading.Lock()
        self._active: Optional[Dict[str, object]] = None
        self.last: Optional[Dict[str, object]] = None
        self.n_captures = 0
        self.n_errors = 0

    def start_window(self, duration_s: float = 1.0,
                     log_dir: Optional[str] = None) -> Dict[str, object]:
        """Begin one capture window; returns ``{log_dir, duration_s,
        started_unix}``. Raises :class:`ProfilerBusy` while a prior
        window is open."""
        import os
        import threading
        duration_s = float(duration_s)
        with self._lock:
            if self._active is not None:
                raise ProfilerBusy(
                    f"capture already running: {self._active}")
            if log_dir is None:
                log_dir = os.path.join(
                    self.base_dir,
                    time.strftime("%Y%m%d-%H%M%S"))
            info: Dict[str, object] = {
                "log_dir": log_dir, "duration_s": duration_s,
                "started_unix": time.time()}
            self._active = info
        t = threading.Thread(target=self._run, args=(info,),
                             daemon=True, name="device-profile")
        t.start()
        return dict(info)

    def _run(self, info: Dict[str, object]) -> None:
        try:
            import jax
            jax.profiler.start_trace(str(info["log_dir"]))
            try:
                time.sleep(float(info["duration_s"]))  # the window
            finally:
                jax.profiler.stop_trace()
            info["ok"] = True
            with self._lock:
                self.n_captures += 1
        except Exception as exc:  # noqa: BLE001 — report, don't die
            info["ok"] = False
            info["error"] = f"{type(exc).__name__}: {exc}"
            with self._lock:
                self.n_errors += 1
            from mmlspark_tpu.core.logs import get_logger
            get_logger("profiling").warning(
                "device trace capture failed", exc_info=True)
        finally:
            info["finished_unix"] = time.time()
            with self._lock:
                self.last = dict(info)
                self._active = None

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._active is not None

    def status(self) -> Dict[str, object]:
        with self._lock:
            return {"busy": self._active is not None,
                    "active": dict(self._active) if self._active else None,
                    "last": dict(self.last) if self.last else None,
                    "n_captures": self.n_captures,
                    "n_errors": self.n_errors,
                    "base_dir": self.base_dir}


class CompileLedger:
    """Bounded ring of compile events (a new dispatch shape = a jit
    retrace). One ``note()`` per retrace — by construction off the
    steady-state hot path, since steady state means zero retraces."""

    def __init__(self, cap: int = 64):
        import collections
        import threading
        self._events: "collections.deque" = collections.deque(
            maxlen=int(cap))
        self._lock = threading.Lock()
        self.n_events = 0

    def note(self, kind: str, shape: str, duration_ms: float,
             **extra: object) -> None:
        ev = {"kind": kind, "shape": shape,
              "duration_ms": round(float(duration_ms), 3),
              "at_unix": round(time.time(), 3)}
        ev.update(extra)
        with self._lock:
            self._events.append(ev)
            self.n_events += 1

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"n_events": self.n_events,
                    "events": list(self._events)}


#: peak dense bf16 TFLOP/s per chip, by ``device_kind`` — the MFU
#: denominator (same table as ``bench.py``; unknown kinds report
#: flops/s without a utilization ratio)
_PEAK_BF16_TFLOPS: Dict[str, float] = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


class MfuMeter:
    """Always-on per-bucket MFU estimation.

    ``note(bucket, seconds, flops)`` accumulates dispatch wall-clock
    per shape bucket and, when the model exposes a flops count for the
    bucket (``dispatch_flops(df)`` hook or ``cost_analysis``), keeps an
    EWMA of achieved flops/s and its ratio to the chip's peak. Without
    flops it still reports per-bucket seconds — the time side of the
    accounting is never conditional on the model cooperating.
    """

    def __init__(self, peak_tflops: Optional[float] = None,
                 alpha: float = 0.2):
        import threading
        self._lock = threading.Lock()
        self.alpha = float(alpha)
        self.peak_flops: Optional[float] = (
            peak_tflops * 1e12 if peak_tflops is not None else None)
        self.device_kind: Optional[str] = None
        if peak_tflops is None:
            try:
                from mmlspark_tpu.core.environment import (
                    environment_info,
                )
                kind = environment_info().get("device_kind")
                self.device_kind = kind
                peak = _PEAK_BF16_TFLOPS.get(str(kind))
                if peak is not None:
                    self.peak_flops = peak * 1e12
            except Exception:  # noqa: BLE001 — accounting is optional
                pass
        self._buckets: Dict[object, Dict[str, float]] = {}

    def note(self, bucket: object, seconds: float,
             flops: Optional[float] = None) -> None:
        with self._lock:
            row = self._buckets.get(bucket)
            if row is None:
                row = self._buckets[bucket] = {
                    "count": 0, "seconds": 0.0, "flops_per_s": None}
            row["count"] += 1
            row["seconds"] += float(seconds)
            if flops and seconds > 0:
                achieved = float(flops) / float(seconds)
                prev = row["flops_per_s"]
                row["flops_per_s"] = (
                    achieved if prev is None
                    else prev + self.alpha * (achieved - prev))

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            buckets = {}
            for bucket, row in self._buckets.items():
                out = {"count": int(row["count"]),
                       "seconds": round(row["seconds"], 4)}
                fps = row["flops_per_s"]
                if fps is not None:
                    out["tflops_per_s"] = round(fps / 1e12, 3)
                    if self.peak_flops:
                        out["mfu"] = round(fps / self.peak_flops, 4)
                buckets[str(bucket)] = out
            return {"device_kind": self.device_kind,
                    "peak_tflops": (round(self.peak_flops / 1e12, 1)
                                    if self.peak_flops else None),
                    "buckets": buckets}
