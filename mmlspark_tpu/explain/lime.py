"""LIME model interpretation: tabular + image.

Capability parity with `image-featurizer/src/main/scala/LIME.scala:27,165,250`
(`LIMEBase` / `TabularLIME` / `ImageLIME`): explain any fitted model's
prediction per row by fitting a local weighted linear surrogate over
perturbed samples.

TPU-first design: the reference distributes one least-squares fit per row
over Spark partitions; here every row's perturbed samples are scored in a
single batched ``model.transform`` (the model's own jitted/sharded forward
does the heavy lifting), and the per-row weighted ridge solves are one
``vmap``-batched ``jnp.linalg.solve`` on device — (rows, d, d) batched
solves instead of row-at-a-time Breeze fits.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, obj_col
from mmlspark_tpu.core.params import (
    Param, HasInputCol, HasOutputCol, in_range,
)
from mmlspark_tpu.core.stage import Estimator, Model, PipelineStage, Transformer
from mmlspark_tpu.explain.superpixel import (
    apply_state, slic_segments,
)


_solve_cache = []


def _solve_all(Xb, yb, wb, reg):
    import jax
    import jax.numpy as jnp

    def one(Xi, yi, wi):
        Xw = Xi * wi[:, None]
        A = Xw.T @ Xi + reg * jnp.eye(Xi.shape[1], dtype=Xi.dtype)
        b = Xw.T @ yi
        return jnp.linalg.solve(A, b)
    return jax.vmap(one)(Xb, yb, wb)


def weighted_ridge_fits(X: np.ndarray, y: np.ndarray, w: np.ndarray,
                        reg: float = 1e-3) -> np.ndarray:
    """Batched weighted ridge regressions.

    X: (R, S, D) perturbation designs, y: (R, S) model outputs,
    w: (R, S) locality weights -> (R, D+1) [coefs..., intercept] per row.
    One vmapped solve; the (D+1, D+1) normal matrices batch onto the MXU.
    The jitted solver is module-cached so repeated batches (LIME loops)
    hit the trace cache instead of recompiling.
    """
    import jax
    import jax.numpy as jnp

    if not _solve_cache:
        _solve_cache.append(jax.jit(_solve_all))
    Xb = jnp.concatenate(
        [jnp.asarray(X, jnp.float32),
         jnp.ones(X.shape[:2] + (1,), jnp.float32)], axis=-1)
    return np.asarray(_solve_cache[0](
        Xb, jnp.asarray(y, jnp.float32), jnp.asarray(w, jnp.float32),
        jnp.float32(reg)))


def _model_scores(model: Transformer, df: DataFrame, input_col: str,
                  predict_col: str, class_index: Optional[int]) -> np.ndarray:
    """Run the inner model and pull a scalar score per row."""
    out = model.transform(df)
    col = out[predict_col]
    if col.dtype == np.dtype("O"):
        col = np.stack([np.asarray(v, dtype=np.float64) for v in col])
    col = np.asarray(col, dtype=np.float64)
    if col.ndim == 2:
        idx = class_index if class_index is not None else col.shape[1] - 1
        return col[:, idx]
    return col


class LIMEBase(Estimator, HasInputCol, HasOutputCol):
    """Shared LIME params (parity: LIME.scala:27 LIMEParams)."""

    model = Param(None, "the fitted model to explain", complex=True)
    predict_col = Param("scores", "model output column to explain")
    class_index = Param(None, "which output class to explain (default last)")
    n_samples = Param(512, "perturbed samples per row", in_range(lo=8))
    kernel_width = Param(0.75, "locality kernel width", in_range(lo=1e-6))
    regularization = Param(1e-3, "ridge regularization", in_range(lo=0.0))
    sample_batch = Param(8, "rows explained per device batch",
                         in_range(lo=1))
    seed = Param(0, "perturbation seed")

    def _save_extra(self, path, arrays):
        import os
        if self.model is not None:
            self.model.save(os.path.join(path, "inner"))

    def _load_extra(self, path, arrays):
        import os
        inner = os.path.join(path, "inner")
        if os.path.isdir(inner):
            self.model = PipelineStage.load(inner)


class TabularLIME(LIMEBase):
    """Explain feature-vector rows via Gaussian perturbation.

    Parity: `LIME.scala:165` (TabularLIME fit collects per-column
    mean/std; its model perturbs around each row with those stats).
    ``fit`` learns column statistics; the model emits one coefficient
    vector per row in ``output_col``.
    """

    input_col = Param("features", "feature-vector column")
    output_col = Param("lime_weights", "per-feature coefficients out")

    def fit(self, df: DataFrame) -> "TabularLIMEModel":
        X = np.stack([np.asarray(v, dtype=np.float64)
                      for v in df[self.input_col]])
        means = X.mean(axis=0)
        stds = X.std(axis=0)
        stds = np.where(stds > 0, stds, 1.0)
        return TabularLIMEModel(
            **self.get_param_values(),
            feature_means=means, feature_stds=stds)


class TabularLIMEModel(TabularLIME, Model):
    feature_means = Param(None, "per-feature means", complex=True)
    feature_stds = Param(None, "per-feature stds", complex=True)

    def _save_extra(self, path, arrays):
        super()._save_extra(path, arrays)
        arrays["feature_means"] = np.asarray(self.feature_means)
        arrays["feature_stds"] = np.asarray(self.feature_stds)

    def _load_extra(self, path, arrays):
        super()._load_extra(path, arrays)
        self.feature_means = arrays["feature_means"]
        self.feature_stds = arrays["feature_stds"]

    def transform(self, df: DataFrame) -> DataFrame:
        rng = np.random.default_rng(self.seed)
        X = np.stack([np.asarray(v, dtype=np.float64)
                      for v in df[self.input_col]])
        n_rows, d = X.shape
        S = self.n_samples
        coefs = np.zeros((n_rows, d), dtype=np.float64)

        for start in range(0, n_rows, self.sample_batch):
            rows = X[start:start + self.sample_batch]
            r = len(rows)
            noise = rng.standard_normal((r, S, d))
            samples = rows[:, None, :] + noise * self.feature_stds
            flat = samples.reshape(r * S, d)
            scores = _model_scores(
                self.model, DataFrame({self.input_col: obj_col(list(flat))}),
                self.input_col, self.predict_col, self.class_index
            ).reshape(r, S)
            # locality weight in standardized space
            z = (samples - rows[:, None, :]) / self.feature_stds
            dist = np.sqrt((z ** 2).sum(-1)) / np.sqrt(d)
            w = np.exp(-(dist ** 2) / self.kernel_width ** 2)
            # fit on standardized offsets so coefs are per-feature effects
            fit = weighted_ridge_fits(z, scores, w, self.regularization)
            coefs[start:start + r] = fit[:, :d] / self.feature_stds
        return df.with_column(self.output_col, obj_col(list(coefs)))


class ImageLIME(LIMEBase):
    """Explain image predictions per superpixel.

    Parity: `LIME.scala:250` (ImageLIME = SLIC superpixels + random
    binary state sampling + censored scoring + per-superpixel linear
    fit). ``fit`` is stateless (superpixels are per-image); provided for
    API symmetry with the reference's Estimator.
    """

    input_col = Param("image", "image column (HWC float arrays)")
    output_col = Param("lime_weights", "per-superpixel coefficients out")
    superpixel_col = Param("superpixels", "label-map column (made if absent)")
    cell_size = Param(16.0, "superpixel cell edge, px", in_range(lo=2))
    modifier = Param(130.0, "spatial-vs-color weight", in_range(lo=0))
    censor_fraction = Param(0.3, "P(superpixel off) per sample",
                            in_range(lo=0.0, hi=1.0))
    background = Param(0.0, "fill value for censored superpixels")

    def fit(self, df: DataFrame) -> "ImageLIMEModel":
        return ImageLIMEModel(**self.get_param_values())


class ImageLIMEModel(ImageLIME, Model):

    def transform(self, df: DataFrame) -> DataFrame:
        rng = np.random.default_rng(self.seed)
        images = [np.asarray(v, dtype=np.float32)
                  for v in df[self.input_col]]
        have_sp = self.superpixel_col in df
        out_weights = []
        out_labels = []
        S = self.n_samples
        for i, img in enumerate(images):
            labels = (np.asarray(df[self.superpixel_col][i])
                      if have_sp else
                      slic_segments(img, self.cell_size, self.modifier))
            k = int(labels.max()) + 1
            states = rng.random((S, k)) >= self.censor_fraction
            states[0] = True  # include the unperturbed image
            masked = np.stack([
                apply_state(img, labels, s, self.background)
                for s in states])
            scores = _model_scores(
                self.model,
                DataFrame({self.input_col: obj_col(list(masked))}),
                self.input_col, self.predict_col, self.class_index)
            frac_on = states.mean(axis=1)
            w = np.exp(-((1.0 - frac_on) ** 2) / self.kernel_width ** 2)
            fit = weighted_ridge_fits(
                states[None].astype(np.float64), scores[None], w[None],
                self.regularization)[0]
            out_weights.append(fit[:k])
            out_labels.append(labels)
        out = df.with_column(self.output_col, obj_col(out_weights))
        if not have_sp:
            out = out.with_column(self.superpixel_col, obj_col(out_labels))
        return out
