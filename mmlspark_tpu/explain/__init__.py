"""Model interpretation: LIME (tabular + image) and SLIC superpixels.

Capability parity with the interpretation half of `src/image-featurizer/`
(`LIME.scala`, `Superpixel.scala`), rebuilt TPU-first: perturbed samples
are scored in batched jitted forwards and the per-row surrogate fits are
vmapped device solves.
"""

from mmlspark_tpu.explain.superpixel import (
    SuperpixelTransformer, slic_segments, segment_masks, apply_state,
)
from mmlspark_tpu.explain.lime import (
    LIMEBase, TabularLIME, TabularLIMEModel, ImageLIME, ImageLIMEModel,
    weighted_ridge_fits,
)

__all__ = [
    "SuperpixelTransformer", "slic_segments", "segment_masks", "apply_state",
    "LIMEBase", "TabularLIME", "TabularLIMEModel", "ImageLIME",
    "ImageLIMEModel", "weighted_ridge_fits",
]
