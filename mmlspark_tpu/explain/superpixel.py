"""SLIC superpixel clustering + superpixel utilities.

Capability parity with `image-featurizer/src/main/scala/Superpixel.scala:141`
(SLIC clustering used by ImageLIME) and `SuperpixelTransformer`. The
reference clusters per image on the JVM; here the iterative assignment step
is vectorized numpy per image (images are small and cluster count is tiny;
the TPU win in LIME comes from batching the *masked inference*, not the
segmentation).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, obj_col
from mmlspark_tpu.core.params import (
    Param, HasInputCol, HasOutputCol, in_range,
)
from mmlspark_tpu.core.stage import Transformer


def slic_segments(image: np.ndarray, cell_size: float = 16.0,
                  modifier: float = 130.0, max_iter: int = 10) -> np.ndarray:
    """SLIC: k-means over (l*color_weight, x, y) with grid-seeded centers.

    Returns an int32 (H, W) label map with contiguous labels [0, K).
    ``cell_size``/``modifier`` mirror the reference Superpixel params
    (`Superpixel.scala:141`): cell edge in pixels, and the color-vs-space
    tradeoff (higher modifier -> spatial proximity dominates).
    """
    img = np.asarray(image, dtype=np.float64)
    if img.ndim == 2:
        img = img[..., None]
    h, w, _ = img.shape
    step = max(int(round(cell_size)), 2)
    ys = np.arange(step // 2, h, step)
    xs = np.arange(step // 2, w, step)
    if len(ys) == 0:
        ys = np.array([h // 2])
    if len(xs) == 0:
        xs = np.array([w // 2])
    # color distance scaled relative to spatial distance (SLIC compactness):
    # dist = ||color||^2 * (modifier/cell)^2-ish; we follow the standard
    # formulation dist = d_color^2 + (d_xy * m / S)^2 with m=modifier/10.
    m = max(modifier, 1e-6) / 10.0
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    centers = []
    for cy in ys:
        for cx in xs:
            centers.append((img[cy, cx], float(cy), float(cx)))
    n_c = len(centers)
    c_color = np.stack([c[0] for c in centers])           # (K, C)
    c_pos = np.array([[c[1], c[2]] for c in centers])     # (K, 2)

    pix_color = img.reshape(-1, img.shape[-1])            # (HW, C)
    pix_pos = np.stack([yy.ravel(), xx.ravel()], axis=1).astype(np.float64)

    labels = np.zeros(h * w, dtype=np.int64)
    for _ in range(max_iter):
        # (HW, K) distances; images are small so the dense form is fine
        d_color = ((pix_color[:, None, :] - c_color[None]) ** 2).sum(-1)
        d_pos = ((pix_pos[:, None, :] - c_pos[None]) ** 2).sum(-1)
        dist = d_color + d_pos * (m / step) ** 2
        new_labels = dist.argmin(axis=1)
        if np.array_equal(new_labels, labels):
            break
        labels = new_labels
        for k in range(n_c):
            mask = labels == k
            if mask.any():
                c_color[k] = pix_color[mask].mean(axis=0)
                c_pos[k] = pix_pos[mask].mean(axis=0)
    # compact to contiguous labels
    uniq, labels = np.unique(labels, return_inverse=True)
    return labels.reshape(h, w).astype(np.int32)


def segment_masks(labels: np.ndarray) -> np.ndarray:
    """(K, H, W) boolean mask per superpixel from a label map."""
    k = int(labels.max()) + 1 if labels.size else 0
    return np.stack([labels == i for i in range(k)]) if k else \
        np.zeros((0,) + labels.shape, dtype=bool)


def apply_state(image: np.ndarray, labels: np.ndarray,
                state: np.ndarray, background: float = 0.0) -> np.ndarray:
    """Censor the superpixels whose ``state`` bit is off.

    Parity: Superpixel.scala's CensoredBufferedImage — off superpixels are
    replaced with ``background``.
    """
    keep = np.asarray(state, dtype=bool)[labels]          # (H, W)
    img = np.asarray(image, dtype=np.float32)
    if img.ndim == 3:
        keep = keep[..., None]
    return np.where(keep, img, np.float32(background))


class SuperpixelTransformer(Transformer, HasInputCol, HasOutputCol):
    """Attach a SLIC label map column for each image row.

    Parity: `image-featurizer` SuperpixelTransformer.
    """

    input_col = Param("image", "image column (HWC float arrays)")
    output_col = Param("superpixels", "label-map output column")
    cell_size = Param(16.0, "superpixel cell edge, px", in_range(lo=2))
    modifier = Param(130.0, "spatial-vs-color weight", in_range(lo=0))

    def transform(self, df: DataFrame) -> DataFrame:
        labels = [slic_segments(img, self.cell_size, self.modifier)
                  for img in df[self.input_col]]
        return df.with_column(self.output_col, obj_col(labels))
