"""AutoML train wrappers: featurize-then-fit any learner.

Capability parity with `src/train` (`AutoTrainer.scala:12`,
`TrainClassifier.scala:50,278`, `TrainRegressor.scala:21,139`): wrap any
Estimator so users hand a raw heterogeneous frame and a label column;
featurization (per-type handling + assembly), label reindexing, fitting,
and score-column metadata all happen inside. The fitted model carries the
featurization so scoring raw frames round-trips.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param, HasLabelCol, in_range
from mmlspark_tpu.core.stage import Estimator, Model, PipelineStage
from mmlspark_tpu.core import schema as S
from mmlspark_tpu.featurize import Featurize


class _AutoTrainer(Estimator, HasLabelCol):
    """Parity: `AutoTrainer.scala:12` (shared model/featurization params)."""

    model = Param(None, "the inner estimator to fit", complex=True)
    features_col = Param("__auto_features", "internal assembled features",
                         ptype=str)
    number_of_features = Param(256, "hash dims for text columns", ptype=int,
                               validator=in_range(lo=1))

    def _featurize(self, df: DataFrame, one_hot: bool):
        feature_cols = [c for c in df.columns if c != self.label_col]
        feat = Featurize(
            feature_columns=feature_cols,
            number_of_features=self.number_of_features,
            one_hot_encode_categoricals=one_hot,
            output_col=self.features_col).fit(df)
        return feat


class TrainClassifier(_AutoTrainer):
    """Featurize + reindex labels + fit a classifier.

    Parity: `TrainClassifier.scala:50` — labels are reindexed to [0, n)
    (`ValueIndexer` role), features assembled from every non-label column,
    and the inner model's score columns get ML-role metadata so evaluators
    can auto-detect them.
    """

    reindex_label = Param(True, "reindex labels to [0, n)", ptype=bool)

    def fit(self, df: DataFrame) -> "TrainedClassifierModel":
        # tree learners keep categorical indexes; others one-hot. We can't
        # introspect arbitrary estimators, so one-hot by default and let
        # GBDT read categorical_slots either way.
        featurizer = self._featurize(df, one_hot=True)
        work = featurizer.transform(df)

        levels: Optional[List[Any]] = None
        y = df[self.label_col]
        if self.reindex_label:
            vals = [v.item() if isinstance(v, np.generic) else v for v in y]
            levels = sorted(set(vals), key=lambda v: (isinstance(v, str), v))
            lookup = {lv: i for i, lv in enumerate(levels)}
            work = work.with_column(
                self.label_col,
                np.array([lookup[v] for v in vals], dtype=np.int64),
                metadata=S.make_categorical_meta(levels))

        inner = self.model.copy(features_col=self.features_col,
                                label_col=self.label_col)
        fitted = inner.fit(work)
        return TrainedClassifierModel(
            label_col=self.label_col, features_col=self.features_col,
            featurizer=featurizer, fitted=fitted,
            levels=levels)


class TrainedClassifierModel(Model, HasLabelCol):
    """Parity: `TrainClassifier.scala:278` (TrainedClassifierModel)."""

    features_col = Param("__auto_features", "internal features", ptype=str)
    featurizer = Param(None, "fitted featurization", complex=True)
    fitted = Param(None, "fitted inner model", complex=True)
    levels = Param(None, "original label levels", ptype=list)

    def transform(self, df: DataFrame) -> DataFrame:
        out = self.fitted.transform(self.featurizer.transform(df))
        if self.levels is not None:
            levels = self.levels
            pred_col = getattr(self.fitted, "prediction_col", "prediction")
            if pred_col in out:
                idx = np.asarray(out[pred_col]).astype(np.int64)
                vals = [levels[i] if 0 <= i < len(levels) else None
                        for i in idx]
                meta = S.make_role_meta(S.SCORED_LABELS_KIND, self.uid)
                meta["levels"] = list(levels)
                out = out.with_column(pred_col, vals, metadata=meta)
            # level order on the probability column tells evaluators which
            # column belongs to which original label (per-instance log-loss)
            prob_col = getattr(self.fitted, "probability_col", "probability")
            if prob_col in out:
                meta = dict(out.get_metadata(prob_col))
                meta["levels"] = list(levels)
                out = out.with_metadata(prob_col, meta)
        return out.drop(self.features_col)

    def _save_extra(self, path, arrays):
        self.featurizer.save(os.path.join(path, "featurizer"))
        self.fitted.save(os.path.join(path, "fitted"))

    def _load_extra(self, path, arrays):
        self.featurizer = PipelineStage.load(os.path.join(path, "featurizer"))
        self.fitted = PipelineStage.load(os.path.join(path, "fitted"))


class TrainRegressor(_AutoTrainer):
    """Featurize + fit a regressor (parity: `TrainRegressor.scala:21`)."""

    def fit(self, df: DataFrame) -> "TrainedRegressorModel":
        featurizer = self._featurize(df, one_hot=True)
        work = featurizer.transform(df)
        work = work.with_column(
            self.label_col,
            np.asarray(df[self.label_col], dtype=np.float64))
        inner = self.model.copy(features_col=self.features_col,
                                label_col=self.label_col)
        fitted = inner.fit(work)
        return TrainedRegressorModel(
            label_col=self.label_col, features_col=self.features_col,
            featurizer=featurizer, fitted=fitted)


class TrainedRegressorModel(Model, HasLabelCol):
    """Parity: `TrainRegressor.scala:139`."""

    features_col = Param("__auto_features", "internal features", ptype=str)
    featurizer = Param(None, "fitted featurization", complex=True)
    fitted = Param(None, "fitted inner model", complex=True)

    def transform(self, df: DataFrame) -> DataFrame:
        out = self.fitted.transform(self.featurizer.transform(df))
        return out.drop(self.features_col)

    def _save_extra(self, path, arrays):
        self.featurizer.save(os.path.join(path, "featurizer"))
        self.fitted.save(os.path.join(path, "fitted"))

    def _load_extra(self, path, arrays):
        self.featurizer = PipelineStage.load(os.path.join(path, "featurizer"))
        self.fitted = PipelineStage.load(os.path.join(path, "fitted"))
