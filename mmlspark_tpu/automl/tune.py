"""TuneHyperparameters: randomized/grid search with k-fold CV.

Capability parity with `src/tune-hyperparameters`
(`TuneHyperparameters.scala:33`): a param space (grid or random dists,
`ParamSpace.scala:25,34`, `HyperparamBuilder.scala:17-98`) is evaluated
with k-fold cross-validation; trials run concurrently on a driver thread
pool (`TuneHyperparameters.scala:80-94`). On TPU the thread pool overlaps
host-side featurization/binning with device steps; with
``trial_devices=True`` each trial is additionally pinned to its own chip
(round-robin over ``jax.local_devices()``), so single-chip fits run
device-parallel across the mesh instead of contending for one device —
the TPU-first upgrade of the reference's driver-side thread pool
(SURVEY §2.9 row 6).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, py_scalar as _scalar
from mmlspark_tpu.core.params import Param, HasLabelCol, in_range, in_set
from mmlspark_tpu.core.stage import Estimator, Model, PipelineStage
from mmlspark_tpu.automl.metrics import ComputeModelStatistics
from mmlspark_tpu.automl.best import metric_higher_is_better


# ---------------------------------------------------------------------------
# Hyperparameter distributions (parity: HyperparamBuilder.scala:17-98)
# ---------------------------------------------------------------------------

class DiscreteHyperParam:
    """A finite set of values (uniform when sampled randomly)."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def grid(self) -> List[Any]:
        return list(self.values)

    def sample(self, rng: np.random.Generator) -> Any:
        return self.values[int(rng.integers(len(self.values)))]


class RangeHyperParam:
    """A continuous or integer range [lo, hi); optionally log-uniform.

    ``is_int=None`` (the default) samples continuously — integer bounds do
    NOT silently switch to integer sampling (``RangeHyperParam(0, 1)`` means
    uniform [0, 1), not a coin flip). Use ``is_int=True`` or
    :class:`IntRangeHyperParam` for integer params (parity: the reference
    has typed IntRangeHyperParam / DoubleRangeHyperParam,
    `HyperparamBuilder.scala:17-98`).
    """

    def __init__(self, lo, hi, is_int: Optional[bool] = None,
                 log: bool = False):
        if isinstance(lo, bool) or isinstance(hi, bool):
            raise TypeError("bool bounds make no sense for a range; "
                            "use DiscreteHyperParam([False, True])")
        self.lo, self.hi = lo, hi
        self.is_int = bool(is_int)
        self.log = log

    def grid(self, n: int = 3) -> List[Any]:
        if self.log:
            vals = np.geomspace(self.lo, self.hi, n)
        else:
            vals = np.linspace(self.lo, self.hi, n)
        return [int(round(v)) if self.is_int else float(v) for v in vals]

    def sample(self, rng: np.random.Generator) -> Any:
        if self.log:
            v = float(np.exp(rng.uniform(np.log(self.lo), np.log(self.hi))))
        else:
            v = float(rng.uniform(self.lo, self.hi))
        return int(round(v)) if self.is_int else v


class IntRangeHyperParam(RangeHyperParam):
    def __init__(self, lo: int, hi: int, log: bool = False):
        super().__init__(lo, hi, is_int=True, log=log)


class DoubleRangeHyperParam(RangeHyperParam):
    def __init__(self, lo: float, hi: float, log: bool = False):
        super().__init__(lo, hi, is_int=False, log=log)


class HyperparamBuilder:
    """Collects (param name -> dist) pairs (parity: HyperparamBuilder)."""

    def __init__(self):
        self._dists: Dict[str, Any] = {}

    def add_hyperparam(self, name: str, dist) -> "HyperparamBuilder":
        self._dists[name] = dist
        return self

    def build(self) -> Dict[str, Any]:
        return dict(self._dists)


class GridSpace:
    """Cartesian product of every dist's grid (parity: GridSpace)."""

    def __init__(self, dists: Dict[str, Any]):
        self.dists = dists

    def param_maps(self) -> Iterator[Dict[str, Any]]:
        names = list(self.dists)
        grids = [d.grid() if hasattr(d, "grid") else list(d)
                 for d in self.dists.values()]
        def rec(i: int, acc: Dict[str, Any]):
            if i == len(names):
                yield dict(acc)
                return
            for v in grids[i]:
                acc[names[i]] = v
                yield from rec(i + 1, acc)
        yield from rec(0, {})


class RandomSpace:
    """Random samples from every dist (parity: RandomSpace)."""

    def __init__(self, dists: Dict[str, Any], seed: int = 0):
        self.dists = dists
        self.seed = seed

    def sample(self, n: int) -> Iterator[Dict[str, Any]]:
        rng = np.random.default_rng(self.seed)
        for _ in range(n):
            yield {k: d.sample(rng) for k, d in self.dists.items()}


class DefaultHyperparams:
    """Reasonable default search spaces per estimator class
    (parity: `DefaultHyperparams.scala:12`)."""

    @staticmethod
    def for_estimator(est) -> Dict[str, Any]:
        name = type(est).__name__
        if name.startswith("GBDT"):
            return {
                "num_leaves": DiscreteHyperParam([15, 31, 63]),
                "learning_rate": RangeHyperParam(0.01, 0.3, log=True),
                "num_iterations": DiscreteHyperParam([50, 100, 200]),
            }
        if name == "NNLearner":
            return {
                "learning_rate": RangeHyperParam(1e-4, 1e-1, log=True),
                "batch_size": DiscreteHyperParam([64, 128, 256]),
            }
        return {}


# ---------------------------------------------------------------------------
# The tuner
# ---------------------------------------------------------------------------

class TuneHyperparameters(Estimator, HasLabelCol):
    """Search a param space with k-fold CV, thread-pool parallel trials.

    Parity: `TuneHyperparameters.scala:33` (executor at `:80-94`, fit at
    `:113`). ``models`` may hold several heterogeneous estimators; each
    gets its own space (``param_space`` maps estimator index -> dists, or
    one shared dict).
    """

    models = Param(None, "candidate estimators", complex=True)
    param_space = Param(None, "dists dict or list of dicts per model",
                        complex=True)
    evaluation_metric = Param("accuracy", "metric to optimize", ptype=str)
    num_folds = Param(3, "k-fold CV folds", ptype=int,
                      validator=in_range(lo=2))
    num_runs = Param(8, "random samples per model (random mode)", ptype=int)
    parallelism = Param(4, "concurrent trials", ptype=int,
                        validator=in_range(lo=1))
    search_mode = Param("random", "random | grid", ptype=str)
    seed = Param(0, "sampling/fold seed", ptype=int)
    trial_devices = Param("auto", "assign each trial its own chip "
                          "(round-robin over jax.local_devices()) so "
                          "trials run device-parallel instead of "
                          "contending for one; parallelism should be "
                          ">= the device count. auto = enabled whenever "
                          "the host has more than one device | True | "
                          "False", validator=in_set("auto", True, False))

    def _spaces(self) -> List[Dict[str, Any]]:
        models = self.models or []
        ps = self.param_space
        if ps is None:
            return [DefaultHyperparams.for_estimator(m) for m in models]
        if isinstance(ps, dict):
            return [ps for _ in models]
        return list(ps)

    def fit(self, df: DataFrame) -> "TuneHyperparametersModel":
        models = self.models or []
        spaces = self._spaces()
        metric = self.evaluation_metric
        higher = metric_higher_is_better(metric)

        # trial list: (model_idx, param_map)
        trials: List[Tuple[int, Dict[str, Any]]] = []
        for mi, space in enumerate(spaces):
            if not space:
                trials.append((mi, {}))
            elif self.search_mode == "grid":
                trials.extend((mi, pm) for pm in GridSpace(space).param_maps())
            else:
                trials.extend(
                    (mi, pm)
                    for pm in RandomSpace(space, self.seed).sample(self.num_runs))

        # k-fold split indexes
        n = df.num_rows
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        folds = np.array_split(perm, self.num_folds)

        evaluator = ComputeModelStatistics(label_col=self.label_col,
                                           evaluation_metric="all")

        # per-trial device assignment (SURVEY §2.9 row 6: the reference's
        # driver thread pool contends for shared executors; the TPU-first
        # version gives each trial its own chip so single-chip fits run
        # device-parallel across the mesh)
        devices = None
        use_devices = self.trial_devices
        if use_devices == "auto":
            import jax
            use_devices = len(jax.local_devices()) > 1
        if use_devices:
            import jax
            devices = jax.local_devices()

        def run_trial(ti_trial: Tuple[int, Tuple[int, Dict[str, Any]]]
                      ) -> float:
            ti, (mi, pm) = ti_trial
            from contextlib import ExitStack
            with ExitStack() as stack:
                if devices is not None:
                    import jax
                    from mmlspark_tpu.parallel.topology import \
                        single_device_scope
                    stack.enter_context(
                        jax.default_device(devices[ti % len(devices)]))
                    # framework estimators must not build full-mesh
                    # shardings inside a pinned trial: concurrent
                    # threads interleaving multi-device collective
                    # launches can deadlock on real chips
                    stack.enter_context(single_device_scope())
                vals = []
                for f in range(self.num_folds):
                    test_idx = folds[f]
                    train_idx = np.concatenate(
                        [folds[j] for j in range(self.num_folds) if j != f])
                    est = _apply_params(models[mi], pm)
                    fitted = est.fit(df.take(train_idx))
                    scored = fitted.transform(df.take(test_idx))
                    m = evaluator.evaluate(scored)
                    vals.append(float(m[metric][0]))
            return float(np.mean(vals))

        # the user's parallelism cap is respected in both modes (trials
        # can dominate host RAM; silently raising it to the device count
        # could OOM the host) — set parallelism >= len(devices) to keep
        # every chip busy
        workers = max(1, min(self.parallelism, len(trials)))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            results = list(pool.map(run_trial, enumerate(trials)))

        best_i = int(np.argmax(results) if higher else np.argmin(results))
        best_mi, best_pm = trials[best_i]
        best_model = _apply_params(models[best_mi], best_pm).fit(df)

        rows = [{"model": type(models[mi]).__name__,
                 **{k: _scalar(v) for k, v in pm.items()},
                 metric: res}
                for (mi, pm), res in zip(trials, results)]
        return TuneHyperparametersModel(
            best_model=best_model,
            best_metric=float(results[best_i]),
            best_params={k: _scalar(v) for k, v in best_pm.items()},
            history=DataFrame.from_rows(rows))


def _apply_params(est, pm: Dict[str, Any]):
    """Copy ``est`` with the param map, routing params the estimator does
    not declare to its wrapped inner estimator (``model`` param) — so a
    search space over e.g. GBDT params works on a TrainClassifier wrapper
    (the reference's ParamSpace binds params to stages the same way)."""
    declared = type(est).params()
    own = {k: v for k, v in pm.items() if k in declared}
    rest = {k: v for k, v in pm.items() if k not in declared}
    out = est.copy(**own)
    if rest:
        inner = getattr(out, "model", None)
        if inner is None:
            raise KeyError(
                f"{type(est).__name__} has no params {sorted(rest)} and no "
                f"inner 'model' estimator to route them to")
        out.set(model=inner.copy(**rest))
    return out


class TuneHyperparametersModel(Model):
    """The winning refitted model + search history."""

    best_model = Param(None, "winner refit on full data", complex=True)
    best_metric = Param(None, "winner's CV metric", ptype=float)
    best_params = Param(None, "winner's param map", ptype=dict)
    history = Param(None, "all trials frame", complex=True)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.best_model.transform(df)

    def get_best_model(self):
        return self.best_model

    def get_history(self) -> DataFrame:
        return self.history

    def _save_extra(self, path, arrays):
        import os
        self.best_model.save(os.path.join(path, "best"))

    def _load_extra(self, path, arrays):
        import os
        self.best_model = PipelineStage.load(os.path.join(path, "best"))
