"""Evaluators: model-level and per-instance statistics from scored frames.

Capability parity with `src/compute-model-statistics`
(`ComputeModelStatistics.scala:57`) and `src/compute-per-instance-statistics`
(`ComputePerInstanceStatistics.scala:42`), with the reference's
metadata-driven column auto-detection (score columns found via the ML-role
metadata models stamp on their outputs) and the canonical metric names from
`core/metrics/MetricConstants.scala:9-83`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param, HasLabelCol, in_set
from mmlspark_tpu.core.stage import Evaluator
from mmlspark_tpu.core import schema as S

# canonical names (MetricConstants.scala)
CLASSIFICATION_METRICS = ("accuracy", "precision", "recall", "AUC")
REGRESSION_METRICS = ("mean_squared_error", "root_mean_squared_error",
                      "R^2", "mean_absolute_error")
ALL_METRICS = "all"


def _roc_points(y: np.ndarray, score: np.ndarray) -> np.ndarray:
    """ROC curve points (fpr, tpr): one point per distinct score threshold.

    Grouping by threshold (not by row) makes tied scores contribute a
    single diagonal segment, so the curve — like the AUC below — does not
    depend on row order.
    """
    order = np.argsort(-score, kind="stable")
    y = y[order]
    s = score[order]
    tps = np.cumsum(y == 1)
    fps = np.cumsum(y == 0)
    last_of_threshold = np.flatnonzero(np.diff(s, append=np.nan) != 0)
    tps, fps = tps[last_of_threshold], fps[last_of_threshold]
    n_pos = max(float(tps[-1]) if len(tps) else 0.0, 1e-12)
    n_neg = max(float(fps[-1]) if len(fps) else 0.0, 1e-12)
    tpr = np.concatenate([[0.0], tps / n_pos])
    fpr = np.concatenate([[0.0], fps / n_neg])
    return np.stack([fpr, tpr], axis=1)


def _auc(y: np.ndarray, score: np.ndarray) -> float:
    """Tie-corrected AUC (Mann-Whitney with average ranks).

    Tied scores get half credit, so a constant classifier scores 0.5
    regardless of row order.
    """
    n_pos = int((y == 1).sum())
    n_neg = len(y) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.0
    # average rank within each tie group, fully vectorized
    _, inv, counts = np.unique(score, return_inverse=True,
                               return_counts=True)
    ends = np.cumsum(counts)
    avg_rank = (ends - counts + 1 + ends) / 2.0  # mean of 1-based positions
    ranks = avg_rank[inv]
    rank_sum_pos = float(ranks[y == 1].sum())
    return (rank_sum_pos - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg)


def classification_metrics(y: np.ndarray, pred: np.ndarray,
                           score: Optional[np.ndarray] = None
                           ) -> Dict[str, Any]:
    """Accuracy / macro precision / macro recall / AUC + confusion matrix."""
    classes = np.unique(np.concatenate([y, pred]))
    k = len(classes)
    idx = {c: i for i, c in enumerate(classes)}
    cm = np.zeros((k, k), dtype=np.int64)
    for yi, pi in zip(y, pred):
        cm[idx[yi], idx[pi]] += 1
    tp = np.diag(cm).astype(np.float64)
    col_sums = cm.sum(axis=0).astype(np.float64)
    row_sums = cm.sum(axis=1).astype(np.float64)
    precision = float(np.mean(np.where(col_sums > 0, tp / np.maximum(col_sums, 1), 0.0)))
    recall = float(np.mean(np.where(row_sums > 0, tp / np.maximum(row_sums, 1), 0.0)))
    out: Dict[str, Any] = {
        "accuracy": float(np.mean(y == pred)),
        "precision": precision,
        "recall": recall,
        "confusion_matrix": cm,
    }
    if score is not None and k == 2:
        y_bin = (y == classes[1]).astype(np.int64)
        out["AUC"] = _auc(y_bin, score)
        out["roc_curve"] = _roc_points(y_bin, score)
    return out


def regression_metrics(y: np.ndarray, pred: np.ndarray) -> Dict[str, float]:
    y = np.asarray(y, dtype=np.float64)
    pred = np.asarray(pred, dtype=np.float64)
    mse = float(np.mean((y - pred) ** 2))
    ss_tot = float(np.sum((y - np.mean(y)) ** 2))
    ss_res = float(np.sum((y - pred) ** 2))
    return {
        "mean_squared_error": mse,
        "root_mean_squared_error": float(np.sqrt(mse)),
        "R^2": 1.0 - ss_res / ss_tot if ss_tot > 0 else 0.0,
        "mean_absolute_error": float(np.mean(np.abs(y - pred))),
    }


class ComputeModelStatistics(Evaluator, HasLabelCol):
    """Compute classification or regression metrics from a scored frame.

    Parity: `ComputeModelStatistics.scala:57` — the task and columns are
    auto-detected from ML-role metadata when not set explicitly;
    ``evaluate`` returns a one-row metrics frame (confusion matrix and ROC
    as array-valued cells, as the reference returns them in DataFrame
    cells).
    """

    evaluation_metric = Param("all", "metric set or single metric name",
                              ptype=str)
    scores_col = Param(None, "raw score column", ptype=str)
    scored_labels_col = Param(None, "predicted label column", ptype=str)
    scored_probabilities_col = Param(None, "probability column", ptype=str)

    def _detect(self, df: DataFrame) -> Tuple[str, str, Optional[str], Optional[str]]:
        """-> (task, pred_col, scores_col, prob_col)"""
        pred_col = self.scored_labels_col or \
            S.find_column_by_role(df, S.SCORED_LABELS_KIND)
        scores_col = self.scores_col or \
            S.find_column_by_role(df, S.SCORES_KIND)
        prob_col = self.scored_probabilities_col or \
            S.find_column_by_role(df, S.SCORED_PROBABILITIES_KIND)
        task = None
        if scores_col is not None:
            task = (df.get_metadata(scores_col) or {}).get("task")
        if task is None:
            task = S.CLASSIFICATION if pred_col is not None else S.REGRESSION
        if task == S.REGRESSION and pred_col is None:
            pred_col = scores_col or "prediction"
        return task, pred_col, scores_col, prob_col

    def evaluate(self, df: DataFrame) -> DataFrame:
        task, pred_col, scores_col, prob_col = self._detect(df)
        y = df[self.label_col]
        if task == S.CLASSIFICATION:
            pred = df[pred_col]
            score = None
            if prob_col is not None:
                p = np.asarray(df[prob_col], dtype=np.float64)
                if p.ndim == 2 and p.shape[1] >= 2:
                    score = p[:, 1]
                else:
                    score = p.reshape(len(p))
            elif scores_col is not None:
                s = np.asarray(df[scores_col], dtype=np.float64)
                score = s[:, -1] if s.ndim == 2 else s
            m = classification_metrics(np.asarray(y), np.asarray(pred), score)
        else:
            m = regression_metrics(df[self.label_col], df[pred_col])
        want = self.evaluation_metric
        if want != ALL_METRICS:
            if want not in m:
                raise ValueError(f"metric {want!r} unavailable; have "
                                 f"{sorted(m)}")
            m = {want: m[want]}
        cols: Dict[str, Any] = {}
        for k, v in m.items():
            if isinstance(v, np.ndarray):
                cols[k] = np.empty(1, dtype=object)
                cols[k][0] = v
            else:
                cols[k] = np.array([v])
        return DataFrame(cols)


class ComputePerInstanceStatistics(Evaluator, HasLabelCol):
    """Per-row losses appended as columns.

    Parity: `ComputePerInstanceStatistics.scala:42` — regression: L1/L2
    loss per row; classification: log-loss of the true label's predicted
    probability.
    """

    def evaluate(self, df: DataFrame) -> DataFrame:
        cms = ComputeModelStatistics(label_col=self.label_col)
        task, pred_col, scores_col, prob_col = cms._detect(df)
        y = df[self.label_col]
        if task == S.REGRESSION:
            pred = np.asarray(df[pred_col], dtype=np.float64)
            yv = np.asarray(y, dtype=np.float64)
            df = df.with_column("L1_loss", np.abs(yv - pred))
            return df.with_column("L2_loss", (yv - pred) ** 2)
        if prob_col is None:
            raise ValueError("classification per-instance stats need a "
                             "probability column")
        prob = np.asarray(df[prob_col], dtype=np.float64)
        y_idx = np.asarray(y)
        if y_idx.dtype == np.dtype("O") or y_idx.dtype.kind in "US":
            # Training-time level order rides on the score columns' metadata
            # (stamped by TrainedClassifierModel); the eval frame's own label
            # set can be a subset, so deriving order from it would misalign
            # probability columns.
            levels = None
            for col in (prob_col, pred_col):
                if col is not None:
                    levels = df.get_metadata(col).get("levels")
                    if levels:
                        break
            if not levels:
                levels = sorted(set(y_idx))
            lookup = {v: i for i, v in enumerate(levels)}
            y_idx = np.array([lookup.get(v, -1) for v in y_idx])
        y_idx = y_idx.astype(np.int64)
        unseen = (y_idx < 0) | (y_idx >= prob.shape[1])
        p_true = prob[np.arange(len(prob)), np.clip(y_idx, 0,
                                                    prob.shape[1] - 1)]
        loss = -np.log(np.clip(p_true, 1e-15, 1.0))
        # labels outside the training levels have no probability column
        loss[unseen] = np.nan
        return df.with_column("log_loss", loss)
