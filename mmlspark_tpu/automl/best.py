"""FindBestModel: evaluate candidate models on a validation frame, keep best.

Capability parity with `src/find-best-model` (`FindBestModel.scala:51,149`,
`EvaluationUtils.scala:13`): every candidate is scored + evaluated on the
same frame; the winner (by the chosen metric) becomes ``BestModel``, which
also records all candidates' metrics and the winner's ROC for reporting.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param, HasLabelCol
from mmlspark_tpu.core.stage import Estimator, Model, PipelineStage
from mmlspark_tpu.automl.metrics import ComputeModelStatistics

# metrics where larger is better
_HIGHER_BETTER = {"accuracy", "precision", "recall", "AUC", "R^2"}


def metric_higher_is_better(name: str) -> bool:
    return name in _HIGHER_BETTER


class FindBestModel(Estimator, HasLabelCol):
    """Parity: `FindBestModel.scala:51`."""

    models = Param(None, "candidate fitted models", complex=True)
    evaluation_metric = Param("accuracy", "metric to rank by", ptype=str)

    def fit(self, df: DataFrame) -> "BestModel":
        if not self.models:
            raise ValueError("no candidate models")
        evaluator = ComputeModelStatistics(
            label_col=self.label_col, evaluation_metric="all")
        rows: List[Dict[str, Any]] = []
        best_i, best_val = -1, None
        higher = metric_higher_is_better(self.evaluation_metric)
        all_metrics: List[DataFrame] = []
        for i, model in enumerate(self.models):
            scored = model.transform(df)
            metrics = evaluator.evaluate(scored)
            all_metrics.append(metrics)
            if self.evaluation_metric not in metrics:
                raise ValueError(
                    f"metric {self.evaluation_metric!r} not produced for "
                    f"model {type(model).__name__}; have {metrics.columns}")
            val = float(metrics[self.evaluation_metric][0])
            rows.append({"model": type(model).__name__, "uid": model.uid,
                         self.evaluation_metric: val})
            if best_val is None or (val > best_val if higher else val < best_val):
                best_i, best_val = i, val
        best = self.models[best_i]
        scored = best.transform(df)
        roc = None
        m = all_metrics[best_i]
        if "roc_curve" in m:
            roc = m["roc_curve"][0]
        return BestModel(
            best_model=best,
            best_model_metrics=all_metrics[best_i],
            all_model_metrics=DataFrame.from_rows(rows),
            roc_curve=roc,
            scored_frame=scored)


class BestModel(Model):
    """Parity: `FindBestModel.scala:149` — winner + evaluation artifacts."""

    best_model = Param(None, "the winning fitted model", complex=True)
    best_model_metrics = Param(None, "winner's metrics frame", complex=True)
    all_model_metrics = Param(None, "per-candidate metrics frame",
                              complex=True)
    roc_curve = Param(None, "winner's ROC points", complex=True)
    scored_frame = Param(None, "validation frame scored by winner",
                         complex=True)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.best_model.transform(df)

    def get_evaluated_data(self) -> DataFrame:
        return self.scored_frame

    def get_best_model_metrics(self) -> DataFrame:
        return self.best_model_metrics

    def get_all_model_metrics(self) -> DataFrame:
        return self.all_model_metrics

    def get_roc_curve(self):
        return self.roc_curve

    def _save_extra(self, path, arrays):
        self.best_model.save(os.path.join(path, "best"))

    def _load_extra(self, path, arrays):
        self.best_model = PipelineStage.load(os.path.join(path, "best"))
