"""AutoML layer: train wrappers, evaluators, model selection, tuning.

Capability parity with the reference's L4 meta-algorithms: `src/train`
(TrainClassifier/TrainRegressor), `src/compute-model-statistics`,
`src/compute-per-instance-statistics`, `src/find-best-model`,
`src/tune-hyperparameters`.
"""

from mmlspark_tpu.automl.train import (
    TrainClassifier, TrainedClassifierModel,
    TrainRegressor, TrainedRegressorModel,
)
from mmlspark_tpu.automl.metrics import (
    ComputeModelStatistics, ComputePerInstanceStatistics,
    classification_metrics, regression_metrics,
)
from mmlspark_tpu.automl.best import FindBestModel, BestModel
from mmlspark_tpu.automl.tune import (
    TuneHyperparameters, TuneHyperparametersModel,
    HyperparamBuilder, DiscreteHyperParam, RangeHyperParam,
    IntRangeHyperParam, DoubleRangeHyperParam,
    GridSpace, RandomSpace, DefaultHyperparams,
)

__all__ = [
    "TrainClassifier", "TrainedClassifierModel",
    "TrainRegressor", "TrainedRegressorModel",
    "ComputeModelStatistics", "ComputePerInstanceStatistics",
    "classification_metrics", "regression_metrics",
    "FindBestModel", "BestModel",
    "TuneHyperparameters", "TuneHyperparametersModel",
    "HyperparamBuilder", "DiscreteHyperParam", "RangeHyperParam",
    "IntRangeHyperParam", "DoubleRangeHyperParam",
    "GridSpace", "RandomSpace", "DefaultHyperparams",
]
