"""Seeded, deterministic fault injection for chaos tests.

None of the resilience behaviors (:mod:`mmlspark_tpu.core.resilience`)
can be *proven* without a way to make the stack fail on demand, the same
way every time. A :class:`FaultPlan` is that instrument: a per-site
schedule of injected faults, either scripted exactly (``["drop", "503",
"ok"]``) or drawn from seeded probabilities — both fully reproducible,
so a chaos test that passes once passes always.

Wrappers put the plan in front of each layer's failure surface:

* :class:`FaultySession` — a ``requests.Session``-compatible shim for
  the HTTP handlers (:mod:`mmlspark_tpu.io.http`): connection drops,
  resets, injected 5xx/429 replies, slow responses.
* :class:`FaultyModel` — wraps a serving model's ``transform`` so batch
  inference fails or stalls on schedule
  (:class:`mmlspark_tpu.serving.ServingServer`).
* :class:`FaultyCheckpointManager` — wraps a checkpoint manager so
  checkpoint writes fail on schedule.
* :meth:`FaultPlan.step_fault` — a trainer hook that raises at chosen
  global steps, driving ``NNLearner``'s bounded-restart fit loop.

Process-kill schedules are for multi-process harnesses
(``tools/chaos_serving.py``): the plan only *decides* when to kill; the
harness owns the actual signal.

Rollout fault points: a
:class:`~mmlspark_tpu.serving.rollout.ModelVersionManager` constructed
with ``fault_plan=`` consults the sites ``rollout_load``,
``rollout_verify``, ``rollout_warmup``, and ``rollout_flip`` (via
:meth:`FaultPlan.raise_at`), so chaos tests can fail a hot-swap at any
stage of the load -> verify -> warmup -> flip machine and prove the
active version keeps serving.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from mmlspark_tpu.core.resilience import Clock, SYSTEM_CLOCK

#: Fault kinds a plan can schedule. ``ok`` passes through; ``drop``
#: raises ConnectionError (connect refused), ``reset`` raises
#: ConnectionResetError (mid-reply), ``status`` injects an HTTP error
#: reply, ``delay`` sleeps the injected clock then passes through,
#: ``fail`` raises InjectedFault (model / checkpoint / train-step
#: faults), ``kill`` tells a process harness to kill the target.
KINDS = ("ok", "drop", "reset", "status", "delay", "fail", "kill")


class InjectedFault(RuntimeError):
    """An error raised on purpose by a fault plan."""


@dataclass(frozen=True)
class Fault:
    kind: str = "ok"
    status: int = 503
    delay: float = 0.0
    retry_after: Optional[float] = None

    @staticmethod
    def parse(spec: Union[str, "Fault"]) -> "Fault":
        """Shorthand: ``"ok"``/``"drop"``/``"reset"``/``"fail"``/
        ``"kill"``, a status code (``"503"``), or ``"delay:0.25"``."""
        if isinstance(spec, Fault):
            return spec
        s = str(spec)
        if s.isdigit():
            return Fault(kind="status", status=int(s))
        if s.startswith("delay:"):
            return Fault(kind="delay", delay=float(s.split(":", 1)[1]))
        if s not in KINDS:
            raise ValueError(f"unknown fault spec {spec!r}; have {KINDS} "
                             f"or a status code or 'delay:<s>'")
        return Fault(kind=s)


class FaultPlan:
    """A deterministic per-site schedule of faults.

    ``script`` sites replay an exact sequence then return ``ok`` forever:

        plan = FaultPlan(script={"http": ["drop", "503", "ok"],
                                 "model": ["fail"]})

    ``rates`` sites draw from seeded probabilities (one shared
    ``random.Random(seed)`` stream, consumed in call order — the same
    seed and call order reproduce the same faults):

        plan = FaultPlan(seed=7, rates={"http": {"drop": 0.1,
                                                 "status": 0.05}})

    Every injected fault is counted in :attr:`injected` (site ->
    kind -> count) so tests and the chaos tool can assert/report what
    actually fired. Thread-safe: serving handlers hit plans from many
    threads.
    """

    def __init__(self, script: Optional[Dict[str, Sequence]] = None,
                 rates: Optional[Dict[str, Dict[str, float]]] = None,
                 seed: int = 0, status: int = 503,
                 delay: float = 0.05):
        self._scripts = {site: [Fault.parse(s) for s in seq]
                         for site, seq in (script or {}).items()}
        self._cursor: Dict[str, int] = {s: 0 for s in self._scripts}
        self._rates = {site: dict(r) for site, r in (rates or {}).items()}
        self._rng = random.Random(seed)
        self.seed = seed
        self._status = int(status)
        self._delay = float(delay)
        self._lock = threading.Lock()
        self.injected: Dict[str, Dict[str, int]] = {}
        self.n_calls: Dict[str, int] = {}

    def at(self, site: str) -> Fault:
        """The next fault for ``site`` (``ok`` when nothing is scheduled)."""
        with self._lock:
            self.n_calls[site] = self.n_calls.get(site, 0) + 1
            fault = Fault()
            if site in self._scripts:
                i = self._cursor[site]
                if i < len(self._scripts[site]):
                    fault = self._scripts[site][i]
                    self._cursor[site] = i + 1
            elif site in self._rates:
                # one draw per configured kind, in sorted-kind order, so
                # the consumed stream is independent of dict ordering
                for kind in sorted(self._rates[site]):
                    if self._rng.random() < self._rates[site][kind]:
                        fault = Fault(kind=kind, status=self._status,
                                      delay=self._delay)
                        break
            if fault.kind != "ok":
                per_site = self.injected.setdefault(site, {})
                per_site[fault.kind] = per_site.get(fault.kind, 0) + 1
            return fault

    def raise_at(self, site: str, clock: Clock = SYSTEM_CLOCK) -> None:
        """Consume one fault for ``site`` and raise/sleep accordingly —
        the one-liner for wrapping non-HTTP call sites."""
        f = self.at(site)
        if f.kind == "delay":
            clock.sleep(f.delay)
        elif f.kind == "drop":
            raise ConnectionError(f"injected connection drop at {site!r}")
        elif f.kind == "reset":
            raise ConnectionResetError(f"injected reset at {site!r}")
        elif f.kind in ("fail", "status", "kill"):
            raise InjectedFault(f"injected {f.kind} at {site!r}")

    def step_fault(self, site: str = "train_step"
                   ) -> Callable[[int], None]:
        """A trainer ``fault_injector`` hook bound to one plan site."""
        def hook(global_step: int) -> None:
            self.raise_at(site)
        return hook

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {"seed": self.seed, "calls": dict(self.n_calls),
                    "injected": {s: dict(k)
                                 for s, k in self.injected.items()}}


# ---------------------------------------------------------------------------
# HTTP session wrapper
# ---------------------------------------------------------------------------

@dataclass
class CannedResponse:
    """The minimal response surface the HTTP handlers read."""

    status_code: int = 200
    reason: str = "OK"
    content: bytes = b"{}"
    headers: Dict[str, str] = field(default_factory=dict)


class FaultySession:
    """``requests.Session``-compatible wrapper that injects faults.

    ``inner`` is the real session to delegate clean calls to; with
    ``inner=None`` clean calls return a canned 200 (handler unit tests
    then need no sockets at all). Injected ``status`` faults return a
    synthetic reply carrying ``Retry-After`` when the fault specifies
    one; ``delay`` sleeps the injected clock before delegating, so slow
    handlers cost nothing under a :class:`ManualClock`.
    """

    def __init__(self, inner: Any = None, plan: Optional[FaultPlan] = None,
                 site: str = "http", clock: Clock = SYSTEM_CLOCK,
                 canned: Optional[CannedResponse] = None):
        self.inner = inner
        self.plan = plan or FaultPlan()
        self.site = site
        self.clock = clock
        self.canned = canned or CannedResponse()
        self.n_sent = 0

    def request(self, method, url, headers=None, data=None, timeout=None):
        f = self.plan.at(self.site)
        if f.kind == "delay":
            self.clock.sleep(f.delay)
        elif f.kind == "drop":
            raise ConnectionError(f"injected connection drop for {url}")
        elif f.kind == "reset":
            raise ConnectionResetError(f"injected reset for {url}")
        elif f.kind in ("status", "fail", "kill"):
            hdrs = {} if f.retry_after is None \
                else {"Retry-After": str(f.retry_after)}
            return CannedResponse(status_code=f.status,
                                  reason=f"injected {f.status}",
                                  content=b"", headers=hdrs)
        self.n_sent += 1
        if self.inner is None:
            return self.canned
        return self.inner.request(method, url, headers=headers, data=data,
                                  timeout=timeout)

    def close(self):
        if self.inner is not None:
            self.inner.close()


# ---------------------------------------------------------------------------
# Serving-model wrapper
# ---------------------------------------------------------------------------

class FaultyModel:
    """Wraps any Transformer-shaped model for serving chaos tests:
    ``transform`` consults the plan before delegating, so whole batches
    fail (-> 500s, never journaled) or stall on schedule. Duck-typed on
    purpose — serving only calls ``transform``; this wrapper is test
    instrumentation, not a persistable stage."""

    def __init__(self, inner: Any, plan: FaultPlan, site: str = "model",
                 clock: Clock = SYSTEM_CLOCK):
        self.inner = inner
        self.plan = plan
        self.site = site
        self.clock = clock
        self.n_transforms = 0

    def transform(self, df):
        self.plan.raise_at(self.site, clock=self.clock)
        self.n_transforms += 1
        return self.inner.transform(df)


# ---------------------------------------------------------------------------
# Checkpoint-write wrapper
# ---------------------------------------------------------------------------

class FaultyCheckpointManager:
    """Wraps a checkpoint manager so ``save`` fails on schedule;
    everything else proxies through. A failed save surfaces in the
    trainer as a step fault (the restart path restores the previous
    good checkpoint)."""

    def __init__(self, inner: Any, plan: FaultPlan,
                 site: str = "checkpoint"):
        self._inner = inner
        self._plan = plan
        self._site = site

    def save(self, *args, **kwargs):
        self._plan.raise_at(self._site)
        return self._inner.save(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._inner, name)
