"""Committed-CSV quality-regression gates.

Parity: `core/test/benchmarks/src/main/scala/Benchmarks.scala:35-113` —
metric values are compared against a committed
``benchmarks_<name>.csv`` within per-entry precision; on drift the
harness writes ``new_benchmarks_<name>.csv`` next to it (so an accepted
change is a one-file copy) and raises with the full delta list.

CSV format (one header line)::

    name,value,precision
    breast_cancer_gbdt_auc,0.9871,0.02
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Tuple


class Benchmarks:
    """Collects metric values and verifies them against the committed CSV."""

    def __init__(self, resource_dir: str, name: str):
        self.resource_dir = resource_dir
        self.name = name
        self.entries: List[Tuple[str, float]] = []

    @property
    def csv_path(self) -> str:
        return os.path.join(self.resource_dir, f"benchmarks_{self.name}.csv")

    @property
    def new_csv_path(self) -> str:
        return os.path.join(self.resource_dir,
                            f"new_benchmarks_{self.name}.csv")

    def add(self, entry: str, value: float) -> None:
        self.entries.append((entry, float(value)))

    def _committed(self) -> Dict[str, Tuple[float, float]]:
        out: Dict[str, Tuple[float, float]] = {}
        with open(self.csv_path, newline="") as f:
            for row in csv.DictReader(f):
                out[row["name"]] = (float(row["value"]),
                                    float(row["precision"]))
        return out

    def _write_new(self, precisions: Dict[str, float]) -> None:
        with open(self.new_csv_path, "w", newline="") as f:
            # csv defaults to \r\n; committed fixtures stay LF like the
            # rest of the repo
            w = csv.writer(f, lineterminator="\n")
            w.writerow(["name", "value", "precision"])
            for entry, value in self.entries:
                # entries without a committed precision get a
                # scale-relative default (5%), not an absolute one — a
                # copied-over gate must tolerate normal numeric jitter
                # on any metric scale
                default = max(abs(value) * 0.05, 1e-3)
                w.writerow([entry, f"{value:.6g}",
                            f"{precisions.get(entry, default):.4g}"])

    def verify(self) -> None:
        """Raise AssertionError on drift; write ``new_benchmarks_*.csv``.

        Missing committed file => first run: the new CSV is written and
        an error tells the author to commit it (the reference behaves
        the same for a fresh benchmark suite).
        """
        if not os.path.exists(self.csv_path):
            self._write_new({})
            raise AssertionError(
                f"no committed benchmark file {self.csv_path}; wrote "
                f"{self.new_csv_path} — review and commit it as the gate")
        committed = self._committed()
        precisions = {k: v[1] for k, v in committed.items()}
        failures = []
        seen = set()
        for entry, value in self.entries:
            seen.add(entry)
            if entry not in committed:
                failures.append(f"{entry}: no committed value "
                                f"(measured {value:.6g})")
                continue
            expect, prec = committed[entry]
            if abs(value - expect) > prec:
                failures.append(f"{entry}: {value:.6g} vs committed "
                                f"{expect:.6g} (precision {prec})")
        for entry in committed:
            if entry not in seen:
                failures.append(f"{entry}: committed but not measured")
        if failures:
            self._write_new(precisions)
            raise AssertionError(
                "benchmark drift (new values written to "
                f"{self.new_csv_path}):\n  " + "\n  ".join(failures))
