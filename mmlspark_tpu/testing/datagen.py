"""Synthetic frame generation for property tests.

Parity: `core/test/datagen/src/main/scala/GenerateDataset.scala` +
``DatasetOptions`` — random DataFrames with constrained schemas and
controlled missing values, so stage property tests can sweep input
shapes without hand-writing fixtures.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, obj_col


@dataclasses.dataclass
class ColumnOptions:
    """Constraints for one generated column."""

    kind: str = "double"        # double | int | bool | string | vector | categorical
    missing_ratio: float = 0.0  # NaN (numeric) / None (object) injection
    low: float = -100.0
    high: float = 100.0
    dim: int = 4                # vector width
    levels: Sequence[str] = ("a", "b", "c")
    string_len: int = 8


def generate_column(rng: np.random.Generator, n: int,
                    opt: ColumnOptions) -> np.ndarray:
    if opt.missing_ratio > 0 and opt.kind in ("int", "bool", "vector"):
        raise ValueError(
            f"missing_ratio is not representable for kind={opt.kind!r} "
            f"(use 'double'/'string'/'categorical', which carry NaN/None)")
    if opt.kind == "double":
        col = rng.uniform(opt.low, opt.high, n)
        if opt.missing_ratio > 0:
            col[rng.random(n) < opt.missing_ratio] = np.nan
        return col
    if opt.kind == "int":
        return rng.integers(int(opt.low), int(opt.high) + 1, n)
    if opt.kind == "bool":
        return rng.random(n) < 0.5
    if opt.kind == "vector":
        return rng.normal(size=(n, opt.dim))
    if opt.kind == "categorical":
        vals = rng.choice(list(opt.levels), size=n)
        out = obj_col(list(vals))
    elif opt.kind == "string":
        letters = np.array(list("abcdefghijklmnopqrstuvwxyz"))
        out = obj_col(["".join(rng.choice(letters, opt.string_len))
                       for _ in range(n)])
    else:
        raise ValueError(f"unknown column kind {opt.kind!r}")
    if opt.missing_ratio > 0:
        mask = rng.random(n) < opt.missing_ratio
        out[mask] = None
    return out


def generate_dataframe(schema: Dict[str, ColumnOptions], n_rows: int,
                       seed: int = 0,
                       rng: Optional[np.random.Generator] = None
                       ) -> DataFrame:
    """A random frame matching ``schema`` (name -> ColumnOptions)."""
    rng = rng or np.random.default_rng(seed)
    return DataFrame({name: generate_column(rng, n_rows, opt)
                      for name, opt in schema.items()})


def basic_mixed_frame(n_rows: int = 64, seed: int = 0,
                      missing_ratio: float = 0.0) -> DataFrame:
    """A ready-made mixed-type frame (the GenerateDataset default)."""
    return generate_dataframe({
        "doubles": ColumnOptions("double", missing_ratio=missing_ratio),
        "ints": ColumnOptions("int", low=0, high=50),
        "bools": ColumnOptions("bool"),
        "strings": ColumnOptions("string", missing_ratio=missing_ratio),
        "cats": ColumnOptions("categorical", missing_ratio=missing_ratio),
        "vecs": ColumnOptions("vector", dim=3),
    }, n_rows, seed=seed)


# ---------------------------------------------------------------------------
# CIFAR-shaped synthetic image classification (zoo training data)
# ---------------------------------------------------------------------------
#
# The reference's zoo serves nets trained on real image corpora
# (`ModelDownloader.scala:54,124`). This build environment has zero
# network egress and no CIFAR-10 files on disk, so the committed zoo
# model trains on this DETERMINISTIC procedural surrogate: 32x32x3 uint8
# images in 12 parametric pattern families (random orientation, scale,
# position, colors, contrast, pixel noise) — hard enough that a linear
# model fails and a trained ResNet is genuinely transferable, and fully
# reproducible from this code alone. `tools/train_zoo_models.py` uses
# real CIFAR-10 instead whenever its files are present (see
# `load_cifar10_batches`). Families 10-11 are reserved as *unseen*
# classes for the transfer-learning example.

SYNTH_CIFAR_CLASSES = 12


def synth_cifar(n: int, seed: int = 0, classes=tuple(range(10))):
    """``(images uint8 (n, 32, 32, 3), labels int64 (n,))``; labels are
    indices into ``classes`` (0..len(classes)-1)."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32)
    xx = (xx - 15.5) / 16.0
    yy = (yy - 15.5) / 16.0
    labels = rng.integers(0, len(classes), n)
    images = np.empty((n, 32, 32, 3), np.uint8)
    for li, fam in enumerate(classes):
        idx = np.flatnonzero(labels == li)
        if len(idx):
            images[idx] = _synth_family(rng, len(idx), fam, xx, yy)
    return images, labels.astype(np.int64)


def _synth_family(rng, m, fam, xx, yy):
    r1 = lambda lo, hi: rng.uniform(lo, hi, (m, 1, 1)).astype(np.float32)
    d2 = lambda cx, cy: (xx[None] - cx) ** 2 + (yy[None] - cy) ** 2
    cx, cy = r1(-0.4, 0.4), r1(-0.4, 0.4)

    def rot(theta_lo, theta_hi):
        th = np.deg2rad(rng.uniform(theta_lo, theta_hi, (m, 1, 1))
                        ).astype(np.float32)
        return np.cos(th) * xx[None] + np.sin(th) * yy[None]

    def stripes(theta_lo, theta_hi):
        u = rot(theta_lo, theta_hi)
        return np.sin(np.pi * r1(2.5, 7.5) * u + r1(0, 6.28)) > 0

    if fam == 0:                                   # ~horizontal stripes
        v = stripes(70, 110)
    elif fam == 1:                                 # ~vertical stripes
        v = stripes(-20, 20)
    elif fam == 2:                                 # ~diagonal stripes
        v = stripes(35, 55)
    elif fam == 3:                                 # checkerboard
        s = rng.uniform(3, 8, (m, 1, 1)).astype(np.float32) / 16.0
        ox, oy = r1(0, 1), r1(0, 1)
        v = (np.floor((xx[None] + 1 + ox) / s)
             + np.floor((yy[None] + 1 + oy) / s)) % 2 > 0.5
    elif fam == 4:                                 # filled disk
        v = d2(cx, cy) < r1(0.25, 0.65) ** 2
    elif fam == 5:                                 # ring / annulus
        r_in = r1(0.2, 0.4)
        v = (d2(cx, cy) > r_in ** 2) & (d2(cx, cy) < (r_in + 0.25) ** 2)
    elif fam == 6:                                 # axis-aligned rectangle
        v = (np.abs(xx[None] - cx) < r1(0.2, 0.5)) \
            & (np.abs(yy[None] - cy) < r1(0.2, 0.5))
    elif fam == 7:                                 # plus / cross
        t = r1(0.08, 0.2)
        v = (np.abs(xx[None] - cx) < t) | (np.abs(yy[None] - cy) < t)
    elif fam == 8:                                 # concentric rings
        v = np.sin(np.pi * r1(3, 8) * np.sqrt(d2(cx, cy) + 1e-6)
                   + r1(0, 6.28)) > 0
    elif fam == 9:                                 # gaussian blobs
        v = np.zeros((m, 32, 32), np.float32)
        for _ in range(3):
            bx, by = r1(-0.6, 0.6), r1(-0.6, 0.6)
            v += np.exp(-d2(bx, by) / (2 * r1(0.08, 0.2) ** 2))
        v = v > 0.6
    elif fam == 10:                                # V / triangle wedge
        v = (yy[None] - cy) > r1(0.8, 2.0) * np.abs(xx[None] - cx) - 0.3
    elif fam == 11:                                # diagonal X cross
        t = r1(0.08, 0.2)
        v = (np.abs(rot(40, 50)) < t) | (np.abs(rot(-50, -40)) < t)
    else:
        raise ValueError(f"unknown family {fam}")

    v = v.astype(np.float32)[..., None]            # (m, 32, 32, 1)
    fg = rng.uniform(0, 255, (m, 1, 1, 3)).astype(np.float32)
    bg = np.mod(fg + 128 + rng.uniform(-64, 64, (m, 1, 1, 3)), 256)
    img = bg * (1 - v) + fg * v
    img *= rng.uniform(0.7, 1.2, (m, 1, 1, 1))     # brightness jitter
    img += rng.normal(0, rng.uniform(5, 18, (m, 1, 1, 1)),
                      img.shape)                   # pixel noise
    return np.clip(img, 0, 255).astype(np.uint8)


def load_cifar10_batches(data_dir: str):
    """Real CIFAR-10 from the standard python pickle batches
    (``cifar-10-batches-py``), if present — the zoo trainer prefers this
    over :func:`synth_cifar` when the files exist. Returns
    ``(Xtr, ytr, Xte, yte)`` with uint8 NHWC images."""
    import pickle

    def batch(name):
        with open(os.path.join(data_dir, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x.astype(np.uint8), np.asarray(d[b"labels"], np.int64)

    parts = [batch(f"data_batch_{i}") for i in range(1, 6)]
    Xtr = np.concatenate([p[0] for p in parts])
    ytr = np.concatenate([p[1] for p in parts])
    Xte, yte = batch("test_batch")
    return Xtr, ytr, Xte, yte
