"""Synthetic frame generation for property tests.

Parity: `core/test/datagen/src/main/scala/GenerateDataset.scala` +
``DatasetOptions`` — random DataFrames with constrained schemas and
controlled missing values, so stage property tests can sweep input
shapes without hand-writing fixtures.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, obj_col


@dataclasses.dataclass
class ColumnOptions:
    """Constraints for one generated column."""

    kind: str = "double"        # double | int | bool | string | vector | categorical
    missing_ratio: float = 0.0  # NaN (numeric) / None (object) injection
    low: float = -100.0
    high: float = 100.0
    dim: int = 4                # vector width
    levels: Sequence[str] = ("a", "b", "c")
    string_len: int = 8


def generate_column(rng: np.random.Generator, n: int,
                    opt: ColumnOptions) -> np.ndarray:
    if opt.missing_ratio > 0 and opt.kind in ("int", "bool", "vector"):
        raise ValueError(
            f"missing_ratio is not representable for kind={opt.kind!r} "
            f"(use 'double'/'string'/'categorical', which carry NaN/None)")
    if opt.kind == "double":
        col = rng.uniform(opt.low, opt.high, n)
        if opt.missing_ratio > 0:
            col[rng.random(n) < opt.missing_ratio] = np.nan
        return col
    if opt.kind == "int":
        return rng.integers(int(opt.low), int(opt.high) + 1, n)
    if opt.kind == "bool":
        return rng.random(n) < 0.5
    if opt.kind == "vector":
        return rng.normal(size=(n, opt.dim))
    if opt.kind == "categorical":
        vals = rng.choice(list(opt.levels), size=n)
        out = obj_col(list(vals))
    elif opt.kind == "string":
        letters = np.array(list("abcdefghijklmnopqrstuvwxyz"))
        out = obj_col(["".join(rng.choice(letters, opt.string_len))
                       for _ in range(n)])
    else:
        raise ValueError(f"unknown column kind {opt.kind!r}")
    if opt.missing_ratio > 0:
        mask = rng.random(n) < opt.missing_ratio
        out[mask] = None
    return out


def generate_dataframe(schema: Dict[str, ColumnOptions], n_rows: int,
                       seed: int = 0,
                       rng: Optional[np.random.Generator] = None
                       ) -> DataFrame:
    """A random frame matching ``schema`` (name -> ColumnOptions)."""
    rng = rng or np.random.default_rng(seed)
    return DataFrame({name: generate_column(rng, n_rows, opt)
                      for name, opt in schema.items()})


def basic_mixed_frame(n_rows: int = 64, seed: int = 0,
                      missing_ratio: float = 0.0) -> DataFrame:
    """A ready-made mixed-type frame (the GenerateDataset default)."""
    return generate_dataframe({
        "doubles": ColumnOptions("double", missing_ratio=missing_ratio),
        "ints": ColumnOptions("int", low=0, high=50),
        "bools": ColumnOptions("bool"),
        "strings": ColumnOptions("string", missing_ratio=missing_ratio),
        "cats": ColumnOptions("categorical", missing_ratio=missing_ratio),
        "vecs": ColumnOptions("vector", dim=3),
    }, n_rows, seed=seed)
