"""Many-connection keep-alive HTTP load driver.

The counterpart of :mod:`mmlspark_tpu.serving.frontend` for the CLIENT
side of a benchmark: one selectors event loop drives N concurrent
HTTP/1.1 keep-alive connections, each running serial (pipelining-free)
request/response cycles against a serving worker. ``threading`` +
``http.client`` top out around a few hundred concurrent sockets before
scheduler overhead dominates; this loop holds 1k+ connections at a few
MB of state, which is the whole point — proving the serving frontend's
concurrency ceiling requires a client that doesn't hit its own first.

Used by ``bench.py serving_concurrency_v1``, by ``tools/
bench_serving_pipeline.py --connections``, and by the frontend's
many-connection tests. Pure stdlib.
"""

from __future__ import annotations

import selectors
import socket
import time
from typing import Dict, Iterable, Optional, Tuple

try:
    import ssl as _ssl
except ImportError:  # pragma: no cover
    _ssl = None  # type: ignore[assignment]

_WANT = ((_ssl.SSLWantReadError, _ssl.SSLWantWriteError)
         if _ssl is not None else ())

__all__ = ["drive_keepalive", "build_request"]

_CRLF2 = b"\r\n\r\n"


def build_request(host: str, path: str, payload: bytes,
                  extra_headers: Iterable[Tuple[str, str]] = ()) -> bytes:
    """One POST request, prebuilt: every cycle on a connection sends
    these exact bytes, so the driver's per-request cost is a send and
    a parse — no formatting on the hot path."""
    lines = [f"POST {path} HTTP/1.1",
             f"Host: {host}",
             "Content-Type: application/json",
             f"Content-Length: {len(payload)}"]
    for k, v in extra_headers:
        lines.append(f"{k}: {v}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + payload


class _ClientConn:
    __slots__ = ("sock", "out", "buf", "t_send", "n_done", "awaiting",
                 "connected", "hs")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.out = b""          # unsent request bytes
        self.buf = bytearray()  # response accumulation
        self.t_send = 0.0
        self.n_done = 0
        self.awaiting = False   # a response is outstanding
        self.connected = False
        self.hs = False         # TLS handshake in progress


def drive_keepalive(host: str, port: int, path: str = "/predict",
                    payload: bytes = b'{"x": 0.0}', *,
                    n_connections: int = 100,
                    duration_s: Optional[float] = None,
                    requests_per_conn: Optional[int] = None,
                    extra_headers: Iterable[Tuple[str, str]] = (),
                    settle_timeout: float = 30.0,
                    connect_burst: int = 256,
                    ssl_context=None,
                    tls_server_hostname: Optional[str] = None
                    ) -> Dict[str, object]:
    """Drive ``n_connections`` concurrent keep-alive connections, each
    cycling serial request/response (a new request leaves only after
    the previous response arrived — pipelining-free, like real
    clients). Stop after ``duration_s`` seconds OR after every
    connection completed ``requests_per_conn`` cycles (at least one
    must be given; with both, whichever comes first), then give
    in-flight responses ``settle_timeout`` to land.

    Returns req/s, latency percentiles, the connection-reuse rate
    (requests served on an already-used connection / all requests —
    1 - 1/cycles when keep-alive holds), and the connection-level
    error count (resets, refusals, unexpected server closes — the
    number the concurrency acceptance gate requires to be zero).
    """
    if duration_s is None and requests_per_conn is None:
        raise ValueError("need duration_s and/or requests_per_conn")
    req = build_request(host, path, payload, extra_headers)
    sel = selectors.DefaultSelector()
    conns: list[_ClientConn] = []
    latencies: list[float] = []
    conn_errors = 0
    http_errors = 0
    t_start = time.perf_counter()
    stop_at = (t_start + duration_s) if duration_s else float("inf")

    def fail(c: _ClientConn) -> None:
        nonlocal conn_errors
        conn_errors += 1
        close(c)

    def close(c: _ClientConn) -> None:
        try:
            sel.unregister(c.sock)
        except (KeyError, ValueError):
            pass
        try:
            c.sock.close()
        except OSError:
            pass
        live.discard(c)

    def done(c: _ClientConn) -> bool:
        return (requests_per_conn is not None
                and c.n_done >= requests_per_conn)

    def send_next(c: _ClientConn, now: float) -> None:
        c.t_send = now
        c.awaiting = True
        c.out = req
        pump_out(c)

    def pump_out(c: _ClientConn) -> None:
        if c.out:
            try:
                n = c.sock.send(c.out)
                c.out = c.out[n:]
            except (BlockingIOError, InterruptedError):
                pass
            except _WANT:
                pass
            except OSError:
                fail(c)
                return
        want = selectors.EVENT_READ | (selectors.EVENT_WRITE
                                       if c.out else 0)
        try:
            sel.modify(c.sock, want, c)
        except (KeyError, ValueError, OSError):
            pass

    def start_tls(c: _ClientConn) -> None:
        """Upgrade a just-connected socket: re-register the wrapped
        SSLSocket (wrap detaches the plain one) and drive the
        handshake from loop events."""
        try:
            sel.unregister(c.sock)
        except (KeyError, ValueError):
            pass
        try:
            kw = {}
            if tls_server_hostname is not None:
                kw["server_hostname"] = tls_server_hostname
            c.sock = ssl_context.wrap_socket(
                c.sock, do_handshake_on_connect=False, **kw)
        except (OSError, ValueError):
            fail(c)
            return
        c.hs = True
        sel.register(c.sock,
                     selectors.EVENT_READ | selectors.EVENT_WRITE, c)
        try_handshake(c)

    def try_handshake(c: _ClientConn) -> None:
        try:
            c.sock.do_handshake()
        except _ssl.SSLWantReadError:
            try:
                sel.modify(c.sock, selectors.EVENT_READ, c)
            except (KeyError, ValueError, OSError):
                pass
            return
        except _ssl.SSLWantWriteError:
            try:
                sel.modify(c.sock, selectors.EVENT_WRITE, c)
            except (KeyError, ValueError, OSError):
                pass
            return
        except OSError:
            fail(c)
            return
        c.hs = False
        send_next(c, time.perf_counter())

    # -- connect phase: bounded bursts so n_connections SYNs never
    # overflow the listen backlog at once
    live: set = set()
    to_open = n_connections
    while to_open > 0:
        burst = min(to_open, connect_burst)
        opened = []
        for _ in range(burst):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setblocking(False)
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            rc = s.connect_ex((host, port))
            if rc not in (0, 115, 36, 10035):  # EINPROGRESS variants
                s.close()
                conn_errors += 1
                continue
            c = _ClientConn(s)
            conns.append(c)
            opened.append(c)
            live.add(c)
            sel.register(s, selectors.EVENT_WRITE, c)
        # wait for this burst to finish its handshakes before the next
        t_burst = time.perf_counter() + 10.0
        pending = {c for c in opened}
        while pending and time.perf_counter() < t_burst:
            for key, _mask in sel.select(timeout=0.25):
                c = key.data
                if c.hs and c not in pending:
                    try_handshake(c)     # earlier bursts' TLS upgrades
                    continue
                if c in pending:
                    err = c.sock.getsockopt(socket.SOL_SOCKET,
                                            socket.SO_ERROR)
                    pending.discard(c)
                    if err:
                        fail(c)
                    elif ssl_context is not None:
                        c.connected = True
                        start_tls(c)     # handshake rides loop events
                    else:
                        c.connected = True
                        send_next(c, time.perf_counter())
        for c in pending:       # handshake never completed
            fail(c)
        to_open -= burst

    # -- steady state: serial request/response cycles per connection
    issuing = True
    while live:
        now = time.perf_counter()
        if issuing and now >= stop_at:
            issuing = False
            settle_at = now + settle_timeout
        if not issuing:
            if not any(c.awaiting for c in live):
                break
            if now >= settle_at:
                for c in list(live):
                    if c.awaiting:
                        fail(c)
                break
        for key, mask in sel.select(timeout=0.25):
            c = key.data
            if c not in live:
                continue
            if c.hs:
                try_handshake(c)
                continue
            if mask & selectors.EVENT_WRITE:
                if not c.connected:
                    c.connected = True
                pump_out(c)
                if c not in live:
                    continue
            if not (mask & selectors.EVENT_READ):
                continue
            try:
                data = c.sock.recv(65536)
                if ssl_context is not None and data:
                    # decrypted bytes can sit in the SSL layer with
                    # nothing left on the raw fd — drain them now
                    while c.sock.pending():
                        more = c.sock.recv(65536)
                        if not more:
                            break
                        data += more
            except (BlockingIOError, InterruptedError):
                continue
            except _WANT:
                continue
            except OSError:
                fail(c)
                continue
            if not data:
                # server closed: mid-response it's an error; after a
                # completed cycle it still breaks the keep-alive
                # contract this driver exists to measure
                fail(c)
                continue
            c.buf += data
            # one response per cycle: parse head, wait for the body
            while c.awaiting:
                he = c.buf.find(_CRLF2)
                if he < 0:
                    break
                head = bytes(c.buf[:he])
                clen = 0
                for line in head.split(b"\r\n")[1:]:
                    if line[:15].lower() == b"content-length:":
                        try:
                            clen = int(line[15:])
                        except ValueError:
                            pass
                        break
                total = he + 4 + clen
                if len(c.buf) < total:
                    break
                t_now = time.perf_counter()
                latencies.append(t_now - c.t_send)
                status = head.split(b" ", 2)[1:2]
                if status != [b"200"]:
                    http_errors += 1
                del c.buf[:total]
                c.n_done += 1
                c.awaiting = False
                if done(c) or not issuing:
                    if done(c):
                        close(c)
                else:
                    send_next(c, t_now)
        if requests_per_conn is not None and not live:
            break

    elapsed = time.perf_counter() - t_start
    for c in list(live):
        close(c)
    sel.close()
    n_reqs = len(latencies)
    n_conns_used = sum(1 for c in conns if c.n_done > 0)
    reuses = sum(max(c.n_done - 1, 0) for c in conns)
    lat_sorted = sorted(latencies)

    def pct(p: float) -> float:
        if not lat_sorted:
            return 0.0
        i = min(int(p / 100.0 * len(lat_sorted)), len(lat_sorted) - 1)
        return lat_sorted[i] * 1000.0

    return {
        "n_connections": n_connections,
        "n_connected": n_conns_used,
        "requests": n_reqs,
        "elapsed_s": round(elapsed, 3),
        "rps": round(n_reqs / elapsed, 1) if elapsed > 0 else 0.0,
        "p50_ms": round(pct(50), 3),
        "p99_ms": round(pct(99), 3),
        "conn_errors": conn_errors,
        "http_errors": http_errors,
        "reuse_rate": round(reuses / n_reqs, 4) if n_reqs else 0.0,
    }
