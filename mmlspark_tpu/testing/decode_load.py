"""Decode-serving load harness: continuous vs static whole-batch A/B.

Shared by ``bench.py decode_continuous_v1`` and
``tools/bench_decode.py`` so the gate and the exploratory tool time
exactly the same simulation. Both modes drive the SAME
:class:`~mmlspark_tpu.serving.decode.TransformerDecoder` (same jitted
prefill/step, same KV pool) over the same seeded workload of requests
arriving at staggered wall-clock offsets; only the batching discipline
differs:

* **continuous** — the scheduler discipline: arrived requests claim
  free slots between steps, finished requests release them mid-batch,
  the fixed-shape step runs whenever any slot is live;
* **static** — the whole-batch baseline: collect the arrived requests
  into one batch, decode the ENTIRE batch until its longest member
  finishes (early finishers pad the batch, the classic cost), only
  then admit the next group — requests arriving mid-batch wait.

Evidence collected alongside tokens/s: post-warmup compile-count delta
(must be zero), KV-pool buffer-pointer stability across steps (the
donation proof — cache-out reuses cache-in's buffer IN PLACE), and
device live-array count stability over the steady state (zero
allocation growth).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np


@dataclass
class DecodeJob:
    arrival_s: float          # offset from window start
    prompt: np.ndarray
    max_new: int
    # filled by the runs
    t_done: float = 0.0
    n_tokens: int = 0


def make_workload(vocab: int, n_requests: int, seed: int = 0,
                  mean_gap_ms: float = 30.0,
                  prompt_lens=(3, 5, 8, 12),
                  max_new=(8, 16, 24),
                  prefix_share: float = 0.0,
                  prefix_len: int = 16,
                  prefix_pool: int = 2) -> List[DecodeJob]:
    """Seeded mixed-arrival workload: exponential inter-arrival gaps
    (the memoryless traffic shape), cycled prompt lengths and token
    budgets — so requests genuinely join and leave mid-flight.

    ``prefix_share`` shapes the multi-tenant prompt-overlap regime the
    prefix cache targets (shared system preambles / few-shot
    templates): that fraction of requests draws its first
    ``prefix_len`` tokens from a small pool of ``prefix_pool`` shared
    prefixes (then a unique ``prompt_lens``-cycled suffix); the rest
    get a unique random prefix of the SAME length, so both arms of a
    cache A/B see identical prompt-length distributions and only the
    overlap differs. One generator serves ``bench.py
    decode_prefix_cache_v1`` and ``tools/bench_decode.py
    --prefix-share``."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(mean_gap_ms / 1000.0, size=n_requests)
    arrivals = np.cumsum(gaps)
    # draw the shared pool ONLY when the knob is on: prefix_share=0
    # callers (every pre-existing seeded workload) must keep their
    # exact historical prompt streams at the same seed
    shared = ([rng.integers(0, vocab, size=int(prefix_len))
               .astype(np.int32) for _ in range(max(prefix_pool, 1))]
              if prefix_share > 0.0 else [])
    jobs = []
    for i in range(n_requests):
        plen = prompt_lens[i % len(prompt_lens)]
        if prefix_share > 0.0:
            head = (shared[i % len(shared)]
                    if rng.random() < prefix_share
                    else rng.integers(0, vocab, size=int(prefix_len))
                    .astype(np.int32))
            prompt = np.concatenate(
                [head, rng.integers(0, vocab, size=plen)
                 .astype(np.int32)])
        else:
            prompt = rng.integers(0, vocab,
                                  size=plen).astype(np.int32)
        jobs.append(DecodeJob(
            arrival_s=float(arrivals[i]),
            prompt=prompt,
            max_new=int(max_new[i % len(max_new)])))
    return jobs


def _reset_jobs(jobs: List[DecodeJob]) -> None:
    for j in jobs:
        j.t_done = 0.0
        j.n_tokens = 0


def run_continuous(decoder, jobs: List[DecodeJob]) -> Dict[str, Any]:
    """The slot-level discipline, inline (no HTTP, no threads — the
    engine's own ceiling). Returns tokens/s plus the zero-alloc /
    zero-retrace evidence."""
    import jax
    _reset_jobs(jobs)
    compiles_before = decoder.n_compiles()
    n_slots = decoder.n_slots
    tokens = np.zeros(n_slots, np.int32)
    pos = np.zeros(n_slots, np.int32)
    free = list(range(n_slots))
    active: Dict[int, DecodeJob] = {}
    queue = sorted(jobs, key=lambda j: j.arrival_s)
    total_tokens = 0
    ptr0 = decoder.cache["k"].unsafe_buffer_pointer()
    live_counts: List[int] = []
    t0 = time.perf_counter()
    while queue or active:
        now = time.perf_counter() - t0
        while queue and free and queue[0].arrival_s <= now:
            job = queue.pop(0)
            slot = free.pop()
            first = decoder.prefill(slot, job.prompt)
            job.n_tokens = 1
            total_tokens += 1
            tokens[slot] = first
            pos[slot] = len(job.prompt)
            active[slot] = job
            if job.n_tokens >= job.max_new:       # 1-token budgets
                job.t_done = time.perf_counter() - t0
                del active[slot]
                free.append(slot)
        if not active:
            if queue:
                time.sleep(max(min(queue[0].arrival_s - now, 0.002),
                               0.0))
            continue
        out = decoder.step(tokens, pos)
        live_counts.append(len(jax.live_arrays()))
        for slot, job in list(active.items()):
            tok = int(out[slot])
            job.n_tokens += 1
            total_tokens += 1
            pos[slot] += 1
            tokens[slot] = tok
            if job.n_tokens >= job.max_new or \
                    int(pos[slot]) >= decoder.max_len - 1:
                job.t_done = time.perf_counter() - t0
                tokens[slot] = 0
                pos[slot] = 0
                del active[slot]
                free.append(slot)
    makespan = time.perf_counter() - t0
    half = len(live_counts) // 2
    return {
        "mode": "continuous",
        "tokens": total_tokens,
        "makespan_s": round(makespan, 4),
        "tokens_per_s": round(total_tokens / makespan, 1),
        "mean_done_s": round(float(np.mean([j.t_done for j in jobs])),
                             4),
        "post_warmup_recompiles":
            decoder.n_compiles() - compiles_before,
        # the donation proof: the pool's device buffer never moved
        "cache_buffer_stable":
            decoder.cache["k"].unsafe_buffer_pointer() == ptr0,
        # steady-state device allocation growth (second half vs first
        # sample): 0 = the warm loop allocates nothing that lives
        "live_array_growth":
            (max(live_counts[half:]) - live_counts[0])
            if half > 0 else 0,
    }


def run_static(decoder, jobs: List[DecodeJob]) -> Dict[str, Any]:
    """The whole-batch baseline: group the arrived requests, decode
    the whole group to its LONGEST member's budget, admit the next
    group only when the batch fully drains."""
    _reset_jobs(jobs)
    compiles_before = decoder.n_compiles()
    n_slots = decoder.n_slots
    tokens = np.zeros(n_slots, np.int32)
    pos = np.zeros(n_slots, np.int32)
    queue = sorted(jobs, key=lambda j: j.arrival_s)
    total_tokens = 0
    t0 = time.perf_counter()
    while queue:
        now = time.perf_counter() - t0
        if queue[0].arrival_s > now:
            time.sleep(min(queue[0].arrival_s - now, 0.002))
            continue
        batch: List[DecodeJob] = []
        while queue and len(batch) < n_slots and \
                queue[0].arrival_s <= time.perf_counter() - t0:
            batch.append(queue.pop(0))
        for slot, job in enumerate(batch):
            first = decoder.prefill(slot, job.prompt)
            job.n_tokens = 1
            total_tokens += 1
            tokens[slot] = first
            pos[slot] = len(job.prompt)
        # the whole batch runs to its longest member; early finishers
        # ride along as padding (their extra tokens are discarded)
        remaining = {slot: job for slot, job in enumerate(batch)
                     if job.n_tokens < job.max_new}
        for job in batch:
            if job.n_tokens >= job.max_new:
                job.t_done = time.perf_counter() - t0
        while remaining:
            out = decoder.step(tokens, pos)
            for slot, job in list(remaining.items()):
                job.n_tokens += 1
                total_tokens += 1
                pos[slot] += 1
                tokens[slot] = int(out[slot])
                if job.n_tokens >= job.max_new or \
                        int(pos[slot]) >= decoder.max_len - 1:
                    job.t_done = time.perf_counter() - t0
                    del remaining[slot]
        tokens[:] = 0
        pos[:] = 0
    makespan = time.perf_counter() - t0
    return {
        "mode": "static",
        "tokens": total_tokens,
        "makespan_s": round(makespan, 4),
        "tokens_per_s": round(total_tokens / makespan, 1),
        "mean_done_s": round(float(np.mean([j.t_done for j in jobs])),
                             4),
        "post_warmup_recompiles":
            decoder.n_compiles() - compiles_before,
    }


# ---------------------------------------------------------------------------
# scheduler-level session harness (paged + speculative A/B)
# ---------------------------------------------------------------------------


class _BenchPending:
    """The _PendingRequest slice a standalone DecodeScheduler touches
    (the same shim the direct-scheduler tests use)."""

    def __init__(self, payload, rid):
        self.payload = payload
        self.rid = rid
        self.deadline = None
        self.event = threading.Event()
        self.callbacks: list = []
        self.reply = None
        self.status = 200
        self.span = None
        self.trace = rid
        self.stream = None


def make_spec_model_pair(cfg, draft_layers: int = 1,
                         resid_scale: float = 0.05, seed: int = 0):
    """A (target params, draft params, draft cfg) triple whose
    truncated-layer draft AGREES with the target at trained-pair rates.

    Randomly initialized blocks drown the embedding stream in residual
    noise, so an early exit's argmax is uncorrelated with the full
    model's — unlike a real trained pair, where the draft exists
    because it agrees. Scaling each block's output projections by
    ``resid_scale`` restores the trained regime (the residual refines
    rather than replaces the stream), giving the ~0.8 greedy agreement
    a production draft is chosen for — so the bench measures the
    speculative MACHINERY at a realistic acceptance rate, which it
    reports and gates on rather than assumes."""
    from mmlspark_tpu.models import transformer as T
    params = T.init_params(cfg, seed=seed)
    params["blocks"] = [dict(b) for b in params["blocks"]]
    for b in params["blocks"]:
        b["wo"] = b["wo"] * resid_scale
        b["w2"] = b["w2"] * resid_scale
    draft_params, draft_cfg = T.layer_truncated_draft(
        params, cfg, draft_layers)
    return params, draft_params, draft_cfg


def run_scheduler_sessions(scheduler, jobs: List[DecodeJob],
                           timeout_s: float = 300.0,
                           payload_extra: Optional[Dict[str, Any]]
                           = None,
                           rid_prefix: str = "bench"
                           ) -> Dict[str, Any]:
    """Drive a live :class:`DecodeScheduler` with the whole workload
    (backlogged submission — every request queued up front, so
    concurrency is bounded by slots/pages, not arrival gaps) and
    collect the sessions-at-fixed-HBM evidence: peak concurrent
    sessions, tokens/s, prefill tokens/s (the prefix-cache A/B
    metric), per-request token sequences (the cross-layout parity
    probe), compile-count delta, and the donation pointer.
    ``payload_extra`` merges into every request's payload (sampling
    knobs for the seeded-parity probes)."""
    import json
    compiles_before = scheduler.decoder.n_compiles()
    prefill_s0 = scheduler.prefill_s
    prompt_tokens0 = scheduler.n_prompt_tokens
    prefills0 = scheduler.n_prefills
    ptr0 = scheduler.decoder.cache["k"].unsafe_buffer_pointer()
    pendings = [_BenchPending(
        dict({"prompt": [int(t) for t in j.prompt],
              "max_new_tokens": int(j.max_new)},
             **(payload_extra or {})), f"{rid_prefix}-{i}")
        for i, j in enumerate(jobs)]
    t0 = time.perf_counter()
    for p in pendings:
        scheduler.submit(p)
    errors = 0
    sequences: List[List[int]] = []
    for p in pendings:
        if not p.event.wait(timeout_s):
            raise RuntimeError("bench request stranded")
        if p.status != 200:
            errors += 1
            sequences.append([])
        else:
            sequences.append(json.loads(p.reply)["tokens"])
    makespan = time.perf_counter() - t0
    total = sum(len(s) for s in sequences)
    out = {
        "n_requests": len(jobs),
        "tokens": total,
        "makespan_s": round(makespan, 4),
        "tokens_per_s": round(total / makespan, 1),
        "errors": errors,
        "sequences": sequences,
        "peak_concurrent_sessions": scheduler.slots_high_water,
        "post_warmup_recompiles":
            scheduler.decoder.n_compiles() - compiles_before,
        "cache_buffer_stable":
            scheduler.decoder.cache["k"].unsafe_buffer_pointer()
            == ptr0,
        "slots_all_freed":
            scheduler.pool.n_free == scheduler.decoder.n_slots,
    }
    d_wall = scheduler.prefill_s - prefill_s0
    d_tokens = scheduler.n_prompt_tokens - prompt_tokens0
    out["prefill_tokens_per_s"] = (round(d_tokens / d_wall, 1)
                                   if d_wall > 0 else None)
    out["mean_prefill_ms"] = round(1000.0 * d_wall / max(
        scheduler.n_prefills - prefills0, 1), 3)
    if scheduler.pages is not None:
        # the refcounted idle invariant: free + index-cached covers
        # the claimable pool, every cached page held exactly once
        cached = (scheduler.prefix.n_cached
                  if scheduler.prefix is not None else 0)
        out["pages_all_freed"] = (
            scheduler.pages.n_free + cached
            == scheduler.pages.n_pages - 1
            and (scheduler.prefix is None
                 or scheduler.prefix.ledger_clean()))
        out["page_high_water"] = scheduler.pages.high_water
    if scheduler.prefix is not None:
        out["prefix_cache"] = scheduler.prefix.stats()
    spec = scheduler.stats().get("speculative")
    if spec is not None:
        out["acceptance_rate"] = spec["acceptance_rate"]
        out["spec_rounds"] = spec["rounds"]
    return out
