"""Decode-serving load harness: continuous vs static whole-batch A/B.

Shared by ``bench.py decode_continuous_v1`` and
``tools/bench_decode.py`` so the gate and the exploratory tool time
exactly the same simulation. Both modes drive the SAME
:class:`~mmlspark_tpu.serving.decode.TransformerDecoder` (same jitted
prefill/step, same KV pool) over the same seeded workload of requests
arriving at staggered wall-clock offsets; only the batching discipline
differs:

* **continuous** — the scheduler discipline: arrived requests claim
  free slots between steps, finished requests release them mid-batch,
  the fixed-shape step runs whenever any slot is live;
* **static** — the whole-batch baseline: collect the arrived requests
  into one batch, decode the ENTIRE batch until its longest member
  finishes (early finishers pad the batch, the classic cost), only
  then admit the next group — requests arriving mid-batch wait.

Evidence collected alongside tokens/s: post-warmup compile-count delta
(must be zero), KV-pool buffer-pointer stability across steps (the
donation proof — cache-out reuses cache-in's buffer IN PLACE), and
device live-array count stability over the steady state (zero
allocation growth).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List

import numpy as np


@dataclass
class DecodeJob:
    arrival_s: float          # offset from window start
    prompt: np.ndarray
    max_new: int
    # filled by the runs
    t_done: float = 0.0
    n_tokens: int = 0


def make_workload(vocab: int, n_requests: int, seed: int = 0,
                  mean_gap_ms: float = 30.0,
                  prompt_lens=(3, 5, 8, 12),
                  max_new=(8, 16, 24)) -> List[DecodeJob]:
    """Seeded mixed-arrival workload: exponential inter-arrival gaps
    (the memoryless traffic shape), cycled prompt lengths and token
    budgets — so requests genuinely join and leave mid-flight."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(mean_gap_ms / 1000.0, size=n_requests)
    arrivals = np.cumsum(gaps)
    jobs = []
    for i in range(n_requests):
        plen = prompt_lens[i % len(prompt_lens)]
        jobs.append(DecodeJob(
            arrival_s=float(arrivals[i]),
            prompt=rng.integers(0, vocab, size=plen).astype(np.int32),
            max_new=int(max_new[i % len(max_new)])))
    return jobs


def _reset_jobs(jobs: List[DecodeJob]) -> None:
    for j in jobs:
        j.t_done = 0.0
        j.n_tokens = 0


def run_continuous(decoder, jobs: List[DecodeJob]) -> Dict[str, Any]:
    """The slot-level discipline, inline (no HTTP, no threads — the
    engine's own ceiling). Returns tokens/s plus the zero-alloc /
    zero-retrace evidence."""
    import jax
    _reset_jobs(jobs)
    compiles_before = decoder.n_compiles()
    n_slots = decoder.n_slots
    tokens = np.zeros(n_slots, np.int32)
    pos = np.zeros(n_slots, np.int32)
    free = list(range(n_slots))
    active: Dict[int, DecodeJob] = {}
    queue = sorted(jobs, key=lambda j: j.arrival_s)
    total_tokens = 0
    ptr0 = decoder.cache["k"].unsafe_buffer_pointer()
    live_counts: List[int] = []
    t0 = time.perf_counter()
    while queue or active:
        now = time.perf_counter() - t0
        while queue and free and queue[0].arrival_s <= now:
            job = queue.pop(0)
            slot = free.pop()
            first = decoder.prefill(slot, job.prompt)
            job.n_tokens = 1
            total_tokens += 1
            tokens[slot] = first
            pos[slot] = len(job.prompt)
            active[slot] = job
            if job.n_tokens >= job.max_new:       # 1-token budgets
                job.t_done = time.perf_counter() - t0
                del active[slot]
                free.append(slot)
        if not active:
            if queue:
                time.sleep(max(min(queue[0].arrival_s - now, 0.002),
                               0.0))
            continue
        out = decoder.step(tokens, pos)
        live_counts.append(len(jax.live_arrays()))
        for slot, job in list(active.items()):
            tok = int(out[slot])
            job.n_tokens += 1
            total_tokens += 1
            pos[slot] += 1
            tokens[slot] = tok
            if job.n_tokens >= job.max_new or \
                    int(pos[slot]) >= decoder.max_len - 1:
                job.t_done = time.perf_counter() - t0
                tokens[slot] = 0
                pos[slot] = 0
                del active[slot]
                free.append(slot)
    makespan = time.perf_counter() - t0
    half = len(live_counts) // 2
    return {
        "mode": "continuous",
        "tokens": total_tokens,
        "makespan_s": round(makespan, 4),
        "tokens_per_s": round(total_tokens / makespan, 1),
        "mean_done_s": round(float(np.mean([j.t_done for j in jobs])),
                             4),
        "post_warmup_recompiles":
            decoder.n_compiles() - compiles_before,
        # the donation proof: the pool's device buffer never moved
        "cache_buffer_stable":
            decoder.cache["k"].unsafe_buffer_pointer() == ptr0,
        # steady-state device allocation growth (second half vs first
        # sample): 0 = the warm loop allocates nothing that lives
        "live_array_growth":
            (max(live_counts[half:]) - live_counts[0])
            if half > 0 else 0,
    }


def run_static(decoder, jobs: List[DecodeJob]) -> Dict[str, Any]:
    """The whole-batch baseline: group the arrived requests, decode
    the whole group to its LONGEST member's budget, admit the next
    group only when the batch fully drains."""
    _reset_jobs(jobs)
    compiles_before = decoder.n_compiles()
    n_slots = decoder.n_slots
    tokens = np.zeros(n_slots, np.int32)
    pos = np.zeros(n_slots, np.int32)
    queue = sorted(jobs, key=lambda j: j.arrival_s)
    total_tokens = 0
    t0 = time.perf_counter()
    while queue:
        now = time.perf_counter() - t0
        if queue[0].arrival_s > now:
            time.sleep(min(queue[0].arrival_s - now, 0.002))
            continue
        batch: List[DecodeJob] = []
        while queue and len(batch) < n_slots and \
                queue[0].arrival_s <= time.perf_counter() - t0:
            batch.append(queue.pop(0))
        for slot, job in enumerate(batch):
            first = decoder.prefill(slot, job.prompt)
            job.n_tokens = 1
            total_tokens += 1
            tokens[slot] = first
            pos[slot] = len(job.prompt)
        # the whole batch runs to its longest member; early finishers
        # ride along as padding (their extra tokens are discarded)
        remaining = {slot: job for slot, job in enumerate(batch)
                     if job.n_tokens < job.max_new}
        for job in batch:
            if job.n_tokens >= job.max_new:
                job.t_done = time.perf_counter() - t0
        while remaining:
            out = decoder.step(tokens, pos)
            for slot, job in list(remaining.items()):
                job.n_tokens += 1
                total_tokens += 1
                pos[slot] += 1
                tokens[slot] = int(out[slot])
                if job.n_tokens >= job.max_new or \
                        int(pos[slot]) >= decoder.max_len - 1:
                    job.t_done = time.perf_counter() - t0
                    del remaining[slot]
        tokens[:] = 0
        pos[:] = 0
    makespan = time.perf_counter() - t0
    return {
        "mode": "static",
        "tokens": total_tokens,
        "makespan_s": round(makespan, 4),
        "tokens_per_s": round(total_tokens / makespan, 1),
        "mean_done_s": round(float(np.mean([j.t_done for j in jobs])),
                             4),
        "post_warmup_recompiles":
            decoder.n_compiles() - compiles_before,
    }
