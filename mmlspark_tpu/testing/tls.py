"""TLS test fixtures: self-signed certificates + client contexts.

The TLS edge (``docs/serving.md`` "TLS at the edge") needs a
certificate to test against; this module mints a throwaway self-signed
one with the ``openssl`` CLI (no Python crypto dependency — the binary
ships in every base image this repo targets) and builds the matching
client ``SSLContext``. Tests call :func:`tls_supported` and skip when
the interpreter lacks ``ssl`` or the box lacks ``openssl``.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Optional, Tuple

try:
    import ssl
except ImportError:  # pragma: no cover
    ssl = None  # type: ignore[assignment]

__all__ = ["tls_supported", "generate_self_signed_cert",
           "client_context"]


def tls_supported() -> Tuple[bool, str]:
    """(ok, reason): whether this box can run the TLS edge tests —
    the ``ssl`` module with the modern server protocol AND an
    ``openssl`` binary to mint the self-signed cert."""
    if ssl is None:
        return False, "no ssl module"
    if not hasattr(ssl, "PROTOCOL_TLS_SERVER"):
        return False, "ssl lacks PROTOCOL_TLS_SERVER"
    if shutil.which("openssl") is None:
        return False, "no openssl binary to mint a test cert"
    return True, ""


def generate_self_signed_cert(directory: str,
                              common_name: str = "localhost"
                              ) -> Tuple[str, str]:
    """Mint a throwaway self-signed cert + key under ``directory``;
    returns ``(cert_path, key_path)``. Valid for 127.0.0.1/localhost
    (subjectAltName), 2 days — long enough for any test run, short
    enough that a leaked fixture is worthless."""
    cert = os.path.join(directory, "test-cert.pem")
    key = os.path.join(directory, "test-key.pem")
    cmd = ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
           "-keyout", key, "-out", cert, "-days", "2",
           "-subj", f"/CN={common_name}",
           "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1"]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=60)
    if proc.returncode != 0:
        raise RuntimeError(
            f"openssl could not mint a test cert: {proc.stderr[-400:]}")
    return cert, key


def client_context(cert_path: Optional[str] = None):
    """A client ``SSLContext`` for the test cert: verifies against the
    minted cert when given (hostname checks off — tests dial by IP),
    otherwise trusts anything (the drive-the-edge harness case)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    if cert_path is not None:
        ctx.load_verify_locations(cafile=cert_path)
    else:
        ctx.verify_mode = ssl.CERT_NONE
    return ctx
