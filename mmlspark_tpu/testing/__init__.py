"""Test-infrastructure components shipped with the framework.

Parity: the reference packages its test harness as library code under
`core/test/` (TestBase, Benchmarks, datagen) so downstream modules and
users regression-gate their own models the same way.
"""

from mmlspark_tpu.testing.benchmarks import Benchmarks
from mmlspark_tpu.testing.faults import (
    Fault,
    FaultPlan,
    FaultyCheckpointManager,
    FaultyModel,
    FaultySession,
    InjectedFault,
)

__all__ = ["Benchmarks", "Fault", "FaultPlan", "FaultyCheckpointManager",
           "FaultyModel", "FaultySession", "InjectedFault"]
