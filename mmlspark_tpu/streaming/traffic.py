"""TrafficLogSource: served-traffic capture segments as a stream source.

Reads the rotating JSON-line segments
:class:`~mmlspark_tpu.serving.capture.TrafficCapture` writes (one
directory per worker; point this at a parent directory and every
worker's segments are merged) and exposes the engine source protocol:
``plan`` hands out line ranges of settled (newline-terminated) records,
``read`` materializes a range deterministically — the same offsets
yield the same rows on a post-crash replay, because segments are
append-only — and ``ack`` advances a durable cursor journal so a
restarted query resumes where the committed work ended. Torn tails
(a capture writer killed mid-line) are simply not planned until the
line completes; pruned segments fall out of the cursor at ack time
(the same dead-path compaction rule as ``FileStreamSource``).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.logs import get_logger

logger = get_logger("streaming.traffic")

#: meta columns every produced row carries, ordered first in frames.
#: On a name collision the PAYLOAD's value wins (a request feature
#: named "version" is training data; the serving metadata yields)
_META_COLS = ("kind", "event_time", "rid", "trace_id", "version")


class TrafficLogSource:
    """Stream source over a tree of ``*.jsonl`` capture segments.

    ``kinds`` filters records (default: live ``traffic`` rows only —
    pass ``("traffic", "shadow")`` to stream shadow-diff rows too).
    Each produced row flattens to: the meta columns (``kind``,
    ``event_time`` (wall seconds), ``rid``, ``trace_id``, ``version``),
    then the ``request`` object's keys, then the ``reply`` object's
    keys (request wins name collisions). ``cursor_path`` (default
    ``<directory>/_cursor.json``) journals the committed read position
    per segment, so a fresh source instance resumes exactly after the
    last acked line.
    """

    def __init__(self, directory: str,
                 kinds: Tuple[str, ...] = ("traffic",),
                 cursor_path: Optional[str] = None,
                 include_reply: bool = True):
        self.directory = os.path.abspath(directory)
        self.kinds = tuple(kinds)
        self.include_reply = bool(include_reply)
        self.cursor_path = cursor_path or os.path.join(
            self.directory, "_cursor.json")
        self._lock = threading.Lock()
        #: per-segment (bytes_scanned, complete_lines) — line counting
        #: reads only the appended tail, so plan()/backlog() (which the
        #: metrics gauge calls every scrape) cost O(new bytes), not a
        #: full reread of every segment
        self._line_cache: Dict[str, Tuple[int, int]] = {}
        #: committed lines per segment relpath (durable via the journal)
        self._cursor: Dict[str, int] = {}
        #: planned-but-unacked lines per relpath (in-memory; the engine
        #: WAL re-acks across restarts)
        self._planned: Dict[str, int] = {}
        self.n_bad_lines = 0
        if os.path.exists(self.cursor_path):
            try:
                with open(self.cursor_path) as f:
                    self._cursor = {str(k): int(v)
                                    for k, v in json.load(f).items()}
            except (ValueError, OSError):
                logger.warning("unreadable cursor journal %s; starting "
                               "from zero", self.cursor_path)
        self._planned = dict(self._cursor)

    # -- segment discovery ---------------------------------------------------

    def _segments(self) -> List[str]:
        """Sorted relpaths of every settled-looking segment file."""
        out = []
        for root, dirs, files in os.walk(self.directory):
            dirs.sort()
            for name in sorted(files):
                if not name.endswith(".jsonl"):
                    continue
                out.append(os.path.relpath(os.path.join(root, name),
                                           self.directory))
        return out

    def _complete_lines(self, rel: str) -> int:
        """Newline-terminated line count of one segment (a torn tail is
        not yet a record). Incremental: only bytes beyond the last scan
        are read — a partial tail contributes no newline now and its
        completing bytes carry the newline later, so chunked counts
        sum exactly."""
        path = os.path.join(self.directory, rel)
        try:
            size = os.path.getsize(path)
        except OSError:
            self._line_cache.pop(rel, None)
            return 0
        off, lines = self._line_cache.get(rel, (0, 0))
        if size < off:
            off, lines = 0, 0        # replaced/truncated: rescan
        if size > off:
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    data = f.read()
            except OSError:
                return lines
            lines += data.count(b"\n")
            off += len(data)
            self._line_cache[rel] = (off, lines)
        return lines

    # -- engine source protocol ----------------------------------------------

    def plan(self, limit_rows: Optional[int] = None
             ) -> Optional[Dict[str, Any]]:
        budget = int(limit_rows) if limit_rows else None
        parts: List[List[Any]] = []
        with self._lock:
            for rel in self._segments():
                done = self._planned.get(rel, 0)
                avail = self._complete_lines(rel)
                if avail <= done:
                    continue
                take = avail - done
                if budget is not None:
                    take = min(take, budget)
                if take <= 0:
                    break
                parts.append([rel, done, done + take])
                self._planned[rel] = done + take
                if budget is not None:
                    budget -= take
                    if budget <= 0:
                        break
        if not parts:
            return None
        return {"parts": parts}

    def read(self, meta: Dict[str, Any]) -> DataFrame:
        rows: List[Dict[str, Any]] = []
        for rel, start, end in meta["parts"]:
            path = os.path.join(self.directory, rel)
            try:
                with open(path, "rb") as f:
                    lines = f.read().split(b"\n")
            except OSError:
                # segment pruned between plan and (replayed) read: the
                # rows are gone; deliver what remains rather than wedge
                logger.warning("capture segment %s vanished before "
                               "read; its rows are lost", rel)
                continue
            for ln in lines[int(start):int(end)]:
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except ValueError:
                    self.n_bad_lines += 1
                    continue
                if rec.get("kind") not in self.kinds:
                    continue
                rows.append(self._flatten(rec))
        return _frame_from_ragged_rows(rows)

    def ack(self, meta: Dict[str, Any]) -> None:
        with self._lock:
            for rel, _start, end in meta["parts"]:
                if int(end) > self._cursor.get(rel, 0):
                    self._cursor[rel] = int(end)
                if self._planned.get(rel, 0) < self._cursor[rel]:
                    self._planned[rel] = self._cursor[rel]
            # dead-path compaction: segments pruned from disk stay out
            # of the journal (same rule as FileStreamSource._checkpoint)
            live = set(self._segments())
            self._cursor = {rel: n for rel, n in self._cursor.items()
                            if rel in live}
            self._planned = {rel: n for rel, n in self._planned.items()
                             if rel in live}
            self._line_cache = {rel: v for rel, v
                                in self._line_cache.items()
                                if rel in live}
            self._write_cursor()

    def backlog(self) -> int:
        with self._lock:
            total = 0
            for rel in self._segments():
                total += max(self._complete_lines(rel)
                             - self._planned.get(rel, 0), 0)
            return total

    # -- helpers -------------------------------------------------------------

    def _flatten(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        # payload fields FIRST: a request feature named "version" or
        # "kind" is training data and must not be shadowed by serving
        # metadata (request wins over reply, both win over meta)
        row: Dict[str, Any] = {}
        for key, obj in (("request", rec.get("request")),
                         ("reply", rec.get("reply")
                          if self.include_reply else None),
                         ("live", rec.get("live")),
                         ("shadow", rec.get("shadow"))):
            if not isinstance(obj, dict):
                continue
            prefix = "" if key in ("request", "reply") else f"{key}_"
            for k, v in obj.items():
                row.setdefault(f"{prefix}{k}", v)
        row.setdefault("kind", rec.get("kind"))
        row.setdefault("event_time", rec.get("t"))
        row.setdefault("rid", rec.get("rid"))
        row.setdefault("trace_id", rec.get("trace"))
        row.setdefault("version", rec.get("version"))
        if "staged_version" in rec:      # shadow-diff rows only
            row.setdefault("staged_version", rec["staged_version"])
        return row

    def _write_cursor(self) -> None:
        tmp = f"{self.cursor_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(self._cursor, f, sort_keys=True)
            os.replace(tmp, self.cursor_path)
        except OSError:
            logger.warning("cursor journal write to %s failed",
                           self.cursor_path, exc_info=True)


def _frame_from_ragged_rows(rows: List[Dict[str, Any]]) -> DataFrame:
    """Rows may be heterogeneous (mixed kinds / evolving schemas):
    build the column union with ``None`` holes, meta columns first."""
    if not rows:
        return DataFrame({})
    cols: List[str] = [c for c in _META_COLS if any(c in r for r in rows)]
    seen = set(cols)
    for r in rows:
        for k in r:
            if k not in seen:
                seen.add(k)
                cols.append(k)
    return DataFrame({c: [r.get(c) for r in rows] for c in cols})
