"""Micro-batch streaming engine core.

Execution model (the structured-streaming shape, at this repo's scale):
a :class:`StreamingQuery` runs a ``source -> transform -> sink`` graph
in **versioned micro-batches**. Each batch is durably *planned* before
it runs — the source's offset descriptor lands in a write-ahead
**offset log** — and durably *committed* after the sink finishes — the
batch's post-state (watermark, window-aggregation state, counters)
lands in a **commit log**. Both logs are one atomic-rename JSON file
per batch under ``checkpoint_dir``, the same journal idiom the serving
replay journal and checkpoint digest manifests use (manifest-last /
append-then-replace; torn writes are detectably incomplete).

Exactly-once: on restart the query replays every planned-but-
uncommitted batch from its logged offsets — the *same* rows reach the
sink again, under the *same* batch id. A crash between the sink write
and the commit append therefore downgrades to at-least-once at the
engine boundary, and idempotent sinks (keyed by batch id — e.g. the
``fit_stream`` trainer sink journals its high-water batch id inside
its own checkpoint) restore exactly-once end to end: replay beats
re-dispatch, exactly like the serving journal's rule.

Event time: with ``event_time_col`` the engine tracks the max event
time seen and a **watermark** ``max_event - delay`` (monotone,
persisted in the commit log so restarts resume it). Windowed
aggregation (:class:`WindowSpec`, tumbling or sliding) accumulates
per-window partial aggregates in engine state; a window is emitted to
the sink once the watermark passes its end, and rows older than the
watermark are **late data**: counted, surfaced, excluded from state.

Backpressure: the planner asks the source for at most ``rows_limit``
rows per batch; the limit adapts off a sink-latency EWMA toward
``target_batch_ms`` (source-side rate adaptation). Sink faults ride
the resilience layer: a :class:`~mmlspark_tpu.core.resilience.
RetryPolicy` retries the batch in place (never skips — skipping would
break exactly-once) and an optional breaker gives a collapsed sink
time to recover; retries exhausted is a terminal query failure,
surfaced via :meth:`StreamingQuery.status` / :attr:`exception`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.logs import get_logger
from mmlspark_tpu.core.resilience import (
    Clock, CircuitBreaker, RetryPolicy, SYSTEM_CLOCK,
)

logger = get_logger("streaming.engine")

OFFSETS_DIR = "offsets"
COMMITS_DIR = "commits"


class StreamingQueryError(RuntimeError):
    """The query is in a state that cannot honor the request."""


def _atomic_write_json(path: str, obj: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, sort_keys=True)
    os.replace(tmp, path)


def _read_log(dirpath: str) -> Dict[int, Dict[str, Any]]:
    """``{batch_id: entry}`` for every readable log file; torn/partial
    files (no atomic rename happened) simply do not exist here."""
    out: Dict[int, Dict[str, Any]] = {}
    if not os.path.isdir(dirpath):
        return out
    for name in os.listdir(dirpath):
        if not name.endswith(".json"):
            continue
        try:
            bid = int(name[:-len(".json")])
            with open(os.path.join(dirpath, name)) as f:
                out[bid] = json.load(f)
        except (ValueError, OSError):
            continue
    return out


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------

class MemoryStreamSource:
    """In-memory source (the MemoryStream parity): rows appended via
    :meth:`add_rows` are planned in arrival order by absolute position,
    so a replayed offset range reads back the identical rows. Testing
    and docs — positions do not survive the process."""

    def __init__(self):
        self._rows: List[Dict[str, Any]] = []
        self._planned = 0      # rows handed to the engine (plan cursor)
        self._acked = 0        # rows durably committed downstream
        self._lock = threading.Lock()

    def add_rows(self, rows: List[Dict[str, Any]]) -> None:
        with self._lock:
            self._rows.extend(dict(r) for r in rows)

    # -- engine source protocol ---------------------------------------------

    def plan(self, limit_rows: Optional[int] = None
             ) -> Optional[Dict[str, Any]]:
        with self._lock:
            end = len(self._rows)
            if limit_rows is not None:
                end = min(end, self._planned + max(int(limit_rows), 1))
            if end <= self._planned:
                return None
            meta = {"start": self._planned, "end": end}
            self._planned = end
            return meta

    def read(self, meta: Dict[str, Any]) -> DataFrame:
        with self._lock:
            rows = self._rows[int(meta["start"]):int(meta["end"])]
        return DataFrame.from_rows(rows)

    def ack(self, meta: Dict[str, Any]) -> None:
        with self._lock:
            self._acked = max(self._acked, int(meta["end"]))
            self._planned = max(self._planned, self._acked)

    def backlog(self) -> int:
        with self._lock:
            return len(self._rows) - self._planned


# ---------------------------------------------------------------------------
# event-time windows
# ---------------------------------------------------------------------------

_AGG_OPS = ("count", "sum", "mean", "min", "max")


class WindowSpec:
    """Tumbling/sliding event-time window aggregation.

    ``size_s`` is the window length, ``slide_s`` the hop (defaults to
    ``size_s`` — tumbling). ``aggs`` maps output columns to
    ``(op, input_col)`` with ops ``count|sum|mean|min|max`` (``count``
    ignores its input column). Emitted frames carry ``window_start``,
    ``window_end`` and one row per closed window, ordered by start.
    """

    def __init__(self, size_s: float, slide_s: Optional[float] = None,
                 aggs: Optional[Dict[str, Tuple[str, Optional[str]]]] = None):
        self.size_s = float(size_s)
        self.slide_s = float(slide_s) if slide_s is not None else self.size_s
        if self.size_s <= 0 or self.slide_s <= 0:
            raise ValueError("window size_s and slide_s must be > 0")
        if self.slide_s > self.size_s:
            raise ValueError("slide_s > size_s leaves event-time gaps no "
                             "window covers; use slide_s <= size_s")
        self.aggs = dict(aggs or {"count": ("count", None)})
        for out, (op, _col) in self.aggs.items():
            if op not in _AGG_OPS:
                raise ValueError(f"unknown agg op {op!r} for {out!r}; "
                                 f"have {_AGG_OPS}")

    def starts_for(self, t: float) -> List[float]:
        """Every window start containing event time ``t`` (one for a
        tumbling window, ``size/slide`` for a sliding one)."""
        last = float(np.floor(t / self.slide_s)) * self.slide_s
        starts = []
        s = last
        while s > t - self.size_s:
            starts.append(float(round(s, 9)))
            s -= self.slide_s
        return starts


class _WindowState:
    """Partial aggregates per open window, JSON round-trippable (the
    commit log persists it so a restarted query resumes mid-window)."""

    def __init__(self, spec: WindowSpec):
        self.spec = spec
        #: {start: {"count": n, "sum": {col: v}, "min": {...}, "max": {...}}}
        self.windows: Dict[float, Dict[str, Any]] = {}

    def update(self, times: np.ndarray, df: DataFrame,
               not_late: np.ndarray) -> None:
        cols = {c for _, (op, c) in self.spec.aggs.items()
                if c is not None and op != "count"}
        data = {c: np.asarray(df[c], dtype=np.float64) for c in cols}
        for i in np.nonzero(not_late)[0]:
            t = float(times[i])
            for start in self.spec.starts_for(t):
                w = self.windows.setdefault(
                    start, {"count": 0, "sum": {}, "min": {}, "max": {}})
                w["count"] += 1
                for c, col in data.items():
                    v = float(col[i])
                    w["sum"][c] = w["sum"].get(c, 0.0) + v
                    w["min"][c] = min(w["min"].get(c, v), v)
                    w["max"][c] = max(w["max"].get(c, v), v)

    def close_until(self, watermark: float) -> Optional[DataFrame]:
        """Finalize every window whose end the watermark passed."""
        done = sorted(s for s in self.windows
                      if s + self.spec.size_s <= watermark)
        if not done:
            return None
        rows = []
        for start in done:
            w = self.windows.pop(start)
            row: Dict[str, Any] = {
                "window_start": start,
                "window_end": round(start + self.spec.size_s, 9)}
            for out, (op, c) in self.spec.aggs.items():
                if op == "count":
                    row[out] = w["count"]
                elif op == "sum":
                    row[out] = w["sum"].get(c, 0.0)
                elif op == "mean":
                    row[out] = (w["sum"].get(c, 0.0) / w["count"]
                                if w["count"] else float("nan"))
                else:
                    row[out] = w[op].get(c, float("nan"))
            rows.append(row)
        return DataFrame.from_rows(rows)

    def to_json(self) -> Dict[str, Any]:
        return {repr(float(s)): w for s, w in self.windows.items()}

    def load_json(self, obj: Dict[str, Any]) -> None:
        self.windows = {float(s): w for s, w in (obj or {}).items()}


# ---------------------------------------------------------------------------
# the query
# ---------------------------------------------------------------------------

class StreamingQuery:
    """One running micro-batch pipeline: ``source -> transform ->
    [windowed agg] -> sink`` with WAL-backed exactly-once batches.

    ``sink`` is ``callable(batch_id, df)`` (or an object with a
    ``process(batch_id, df)`` method). With a :class:`WindowSpec` the
    sink receives closed-window aggregate frames; otherwise the
    transformed raw batches. ``checkpoint_dir=None`` runs without a WAL
    (no crash recovery — tests/ephemeral pipes only).

    Drive it either synchronously — :meth:`process_available` runs
    plan/read/sink inline on the caller's thread (deterministic; the
    ManualClock test mode) — or threaded via :meth:`start`, which polls
    the source every ``trigger_interval_s``.
    """

    def __init__(self, source, sink=None,
                 transform: Optional[Callable[[DataFrame], DataFrame]] = None,
                 name: str = "query",
                 checkpoint_dir: Optional[str] = None,
                 trigger_interval_s: float = 0.2,
                 event_time_col: Optional[str] = None,
                 watermark_delay_s: float = 0.0,
                 window: Optional[WindowSpec] = None,
                 max_batch_rows: int = 1024,
                 min_batch_rows: int = 1,
                 target_batch_ms: Optional[float] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 keep_log_entries: int = 64,
                 registry=None,
                 tracer=None,
                 clock: Clock = SYSTEM_CLOCK):
        if window is not None and event_time_col is None:
            raise ValueError("windowed aggregation needs event_time_col")
        self.source = source
        self.sink = sink
        self.transform = transform
        self.name = str(name)
        self.checkpoint_dir = checkpoint_dir
        self.trigger_interval_s = float(trigger_interval_s)
        self.event_time_col = event_time_col
        self.watermark_delay_s = float(watermark_delay_s)
        self.window = window
        self._window_state = _WindowState(window) if window else None
        self.max_batch_rows = max(int(max_batch_rows), 1)
        self.min_batch_rows = max(int(min_batch_rows), 1)
        # rate adaptation target: how long one batch (sink included)
        # should take; defaults to the trigger interval so a saturated
        # sink pushes the planner down toward smaller batches instead
        # of queueing an ever-deeper backlog
        self.target_batch_ms = (float(target_batch_ms)
                                if target_batch_ms is not None
                                else max(self.trigger_interval_s * 1000.0,
                                         1.0))
        self.retry_policy = retry_policy if retry_policy is not None \
            else RetryPolicy(max_attempts=4, base=0.05, cap=2.0,
                             clock=clock)
        self.breaker = breaker
        self.keep_log_entries = max(int(keep_log_entries), 8)
        self.clock = clock
        from mmlspark_tpu.core.tracing import TRACER
        self.tracer = tracer if tracer is not None else TRACER

        # -- progress state
        self.batch_id = 0              # last PLANNED batch id
        self.watermark: Optional[float] = None
        self.max_event_time: Optional[float] = None
        self._rows_limit = self.max_batch_rows
        self._sink_ms_ewma: Optional[float] = None
        self.state = "initialized"     # -> running -> terminated | failed
        self.error: Optional[BaseException] = None
        # -- counters
        self.n_batches = 0
        self.n_rows = 0
        self.n_late_rows = 0
        self.n_replayed_batches = 0
        self.n_sink_retries = 0
        self.n_sink_failures = 0
        self.n_windows_emitted = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._terminated = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._replay: List[Tuple[int, Dict[str, Any]]] = []
        if checkpoint_dir:
            os.makedirs(os.path.join(checkpoint_dir, OFFSETS_DIR),
                        exist_ok=True)
            os.makedirs(os.path.join(checkpoint_dir, COMMITS_DIR),
                        exist_ok=True)
            self._recover()
        if registry is None:
            from mmlspark_tpu.core.telemetry import REGISTRY
            registry = REGISTRY
        self._register_metrics(registry)

    # -- telemetry -----------------------------------------------------------

    def _register_metrics(self, registry) -> None:
        # set_function closures hold only a WEAK reference to the
        # query: a long-lived process creating many uniquely-named
        # queries must not keep each one (and, for fit_stream, its
        # device-resident train state) alive through the registry
        # forever. A dead query's series reads 0. Two queries sharing
        # a name share a child — last registered wins, the same
        # documented idiom as server tail-capture thresholds.
        import weakref
        ref = weakref.ref(self)

        def attr_fn(attr):
            def read() -> float:
                q = ref()
                return float(getattr(q, attr)) if q is not None else 0.0
            return read

        def derived_fn(fn):
            def read() -> float:
                q = ref()
                return float(fn(q)) if q is not None else 0.0
            return read

        lbl = (self.name,)
        for mname, help_, attr in (
            ("streaming_batches_total",
             "Micro-batches committed by the streaming engine.",
             "n_batches"),
            ("streaming_rows_total",
             "Source rows processed by the streaming engine.", "n_rows"),
            ("streaming_late_rows_total",
             "Rows older than the watermark (excluded from windowed "
             "aggregation state).", "n_late_rows"),
            ("streaming_replayed_batches_total",
             "Planned-but-uncommitted batches replayed from the offset "
             "log after a restart (idempotent sinks deduplicate them).",
             "n_replayed_batches"),
            ("streaming_sink_retries_total",
             "Sink attempts retried under the query's RetryPolicy.",
             "n_sink_retries"),
            ("streaming_sink_failures_total",
             "Batches whose sink exhausted its retries (terminal "
             "query failures).", "n_sink_failures"),
        ):
            registry.counter(mname, help_, labels=("query",)).labels(
                *lbl).set_function(attr_fn(attr))
        registry.gauge(
            "streaming_watermark_seconds",
            "Current event-time watermark (event-time seconds; absent "
            "until the first event).", labels=("query",)).labels(
            *lbl).set_function(
            derived_fn(lambda q: q.watermark or 0.0))
        registry.gauge(
            "streaming_event_time_lag_seconds",
            "Max event time seen minus the watermark (the late-data "
            "allowance actually in force).", labels=("query",)).labels(
            *lbl).set_function(
            derived_fn(lambda q: (q.max_event_time or 0.0)
                       - (q.watermark or 0.0)))
        registry.gauge(
            "streaming_source_backlog",
            "Source-reported unplanned backlog (rows/files/lines).",
            labels=("query",)).labels(*lbl).set_function(
            derived_fn(lambda q: q._backlog_metric()))
        registry.gauge(
            "streaming_batch_rows_limit",
            "Adaptive per-batch row budget the planner asks the source "
            "for (rate adaptation off the sink-latency EWMA).",
            labels=("query",)).labels(*lbl).set_function(
            derived_fn(lambda q: q._rows_limit))
        self._m_batch_ms = registry.histogram(
            "streaming_batch_duration_ms",
            "Wall-clock per committed micro-batch (read + transform + "
            "sink + commit).", labels=("query",)).labels(*lbl)
        self._m_sink_ms = registry.histogram(
            "streaming_sink_latency_ms",
            "Sink call wall-clock per micro-batch (the rate-adaptation "
            "signal).", labels=("query",)).labels(*lbl)

    def _backlog_metric(self) -> float:
        try:
            return float(self.source.backlog())
        except Exception:  # noqa: BLE001 — a source without backlog()
            return 0.0

    # -- WAL -----------------------------------------------------------------

    def _log_path(self, kind: str, batch_id: int) -> str:
        return os.path.join(self.checkpoint_dir, kind,
                            f"{batch_id:08d}.json")

    def _recover(self) -> None:
        """Rebuild progress from the logs: restore watermark/state from
        the newest commit, re-ack committed offsets into the source
        (its own progress journal may be a step behind — ack is
        idempotent), queue planned-but-uncommitted offsets for replay."""
        offsets = _read_log(os.path.join(self.checkpoint_dir, OFFSETS_DIR))
        commits = _read_log(os.path.join(self.checkpoint_dir, COMMITS_DIR))
        last_commit = max(commits) if commits else 0
        self.batch_id = max(list(offsets) + list(commits) + [0])
        if last_commit:
            entry = commits[last_commit]
            self.watermark = entry.get("watermark")
            self.max_event_time = entry.get("max_event_time")
            if self._window_state is not None:
                self._window_state.load_json(entry.get("window_state"))
        for bid in sorted(offsets):
            if bid <= last_commit:
                # the crash window between commit-append and source-ack:
                # re-acking is idempotent and closes it
                try:
                    self.source.ack(offsets[bid]["offset"])
                except Exception:  # noqa: BLE001 — best effort; the
                    logger.warning("source re-ack of batch %d failed",
                                   bid, exc_info=True)
            else:
                self._replay.append((bid, offsets[bid]["offset"]))
        if self._replay:
            logger.info(
                "streaming query %r: replaying %d planned-but-"
                "uncommitted batch(es) %s from the offset log",
                self.name, len(self._replay),
                [b for b, _ in self._replay])

    def _prune_logs(self) -> None:
        horizon = self.batch_id - self.keep_log_entries
        if horizon <= 0:
            return
        for kind in (OFFSETS_DIR, COMMITS_DIR):
            d = os.path.join(self.checkpoint_dir, kind)
            for fname in os.listdir(d):
                try:
                    if fname.endswith(".json") \
                            and int(fname[:-len(".json")]) <= horizon:
                        os.remove(os.path.join(d, fname))
                except (ValueError, OSError):
                    continue

    # -- one batch -----------------------------------------------------------

    def _plan(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        meta = self.source.plan(self._rows_limit)
        if meta is None:
            return None
        self.batch_id += 1
        bid = self.batch_id
        if self.checkpoint_dir:
            # the WAL write: once this lands, the batch WILL run (now
            # or as a post-restart replay) — the exactly-once anchor
            _atomic_write_json(self._log_path(OFFSETS_DIR, bid),
                               {"batch_id": bid, "offset": meta,
                                "planned_unix": round(time.time(), 3)})
        return bid, meta

    def _sink_call(self, batch_id: int, df: DataFrame) -> None:
        sink = self.sink
        if sink is None:
            return
        fn = sink.process if hasattr(sink, "process") else sink

        attempts = {"n": 0}

        def once():
            attempts["n"] += 1
            if attempts["n"] > 1:
                self.n_sink_retries += 1
            if self.breaker is not None:
                return self.breaker.call(lambda: fn(batch_id, df))
            return fn(batch_id, df)

        # CircuitOpen is retryable here by design: the breaker halves
        # open after its recovery timeout and the SAME batch goes again
        # — a streaming engine may never skip a planned batch
        self.retry_policy.call(once)

    def _process(self, batch_id: int, meta: Dict[str, Any],
                 replayed: bool = False) -> None:
        t_batch = self.clock.now()
        with self.tracer.span("stream_batch",
                              route=f"stream:{self.name}",
                              batch=batch_id, replayed=replayed) as sp:
            t0 = self.clock.now()
            df = self.source.read(meta)
            self.tracer.add("read", t0, self.clock.now(), parent=sp,
                            rows=df.num_rows)
            if self.transform is not None and df.num_rows:
                t0 = self.clock.now()
                df = self.transform(df)
                self.tracer.add("transform", t0, self.clock.now(),
                                parent=sp)
            out, late = self._advance_event_time(df)
            if out is not None and out.num_rows:
                t0 = self.clock.now()
                try:
                    self._sink_call(batch_id, out)
                except Exception:
                    self.n_sink_failures += 1
                    raise
                dt_ms = (self.clock.now() - t0) * 1000.0
                self._m_sink_ms.observe(dt_ms)
                self._note_sink_latency(dt_ms)
                self.tracer.add("sink", t0, self.clock.now(), parent=sp,
                                rows=out.num_rows)
            t0 = self.clock.now()
            if self.checkpoint_dir:
                entry: Dict[str, Any] = {
                    "batch_id": batch_id,
                    "watermark": self.watermark,
                    "max_event_time": self.max_event_time,
                    "n_rows": int(df.num_rows),
                    "committed_unix": round(time.time(), 3)}
                if self._window_state is not None:
                    entry["window_state"] = self._window_state.to_json()
                _atomic_write_json(
                    self._log_path(COMMITS_DIR, batch_id), entry)
                self._prune_logs()
            self.source.ack(meta)
            self.tracer.add("commit", t0, self.clock.now(), parent=sp)
        with self._lock:
            self.n_batches += 1
            self.n_rows += int(df.num_rows)
            self.n_late_rows += late
            if replayed:
                self.n_replayed_batches += 1
        self._m_batch_ms.observe((self.clock.now() - t_batch) * 1000.0)

    def _advance_event_time(self, df: DataFrame
                            ) -> Tuple[Optional[DataFrame], int]:
        """Watermark + window bookkeeping for one batch. Returns the
        frame the sink should see and the late-row count."""
        if self.event_time_col is None:
            return df, 0
        late = 0
        if df.num_rows and self.event_time_col in df:
            times = np.asarray(df[self.event_time_col], dtype=np.float64)
            # late vs the watermark as of batch START: rows the
            # downstream state may already have finalized past
            wm = self.watermark
            late_mask = (times < wm) if wm is not None \
                else np.zeros(len(times), dtype=bool)
            late = int(late_mask.sum())
            if self._window_state is not None:
                self._window_state.update(times, df, ~late_mask)
            batch_max = float(times.max())
            self.max_event_time = batch_max \
                if self.max_event_time is None \
                else max(self.max_event_time, batch_max)
            new_wm = self.max_event_time - self.watermark_delay_s
            # monotone: event time regressing never pulls it back
            if self.watermark is None or new_wm > self.watermark:
                self.watermark = new_wm
        if self._window_state is None:
            return df, late
        emitted = None
        if self.watermark is not None:
            emitted = self._window_state.close_until(self.watermark)
        if emitted is not None:
            self.n_windows_emitted += emitted.num_rows
        return emitted, late

    def _note_sink_latency(self, dt_ms: float) -> None:
        ew = self._sink_ms_ewma
        self._sink_ms_ewma = dt_ms if ew is None \
            else 0.7 * ew + 0.3 * dt_ms
        # multiplicative rate adaptation, bounded per step so one
        # outlier batch can't collapse (or explode) the budget
        ratio = self.target_batch_ms / max(self._sink_ms_ewma, 1e-3)
        ratio = min(max(ratio, 0.5), 2.0)
        self._rows_limit = int(min(max(self._rows_limit * ratio,
                                       self.min_batch_rows),
                                   self.max_batch_rows))

    # -- driving -------------------------------------------------------------

    def run_once(self) -> bool:
        """Process one micro-batch if the source has data (replays
        first). Returns True when a batch was processed. Terminal
        failures re-raise after recording state."""
        if self.state == "failed":
            raise StreamingQueryError(
                f"query {self.name!r} already failed: {self.error!r}")
        try:
            if self._replay:
                bid, meta = self._replay.pop(0)
                self._process(bid, meta, replayed=True)
                return True
            planned = self._plan()
            if planned is None:
                return False
            self._process(*planned)
            return True
        except Exception as e:
            self.state = "failed"
            self.error = e
            self._terminated.set()
            logger.error("streaming query %r failed on batch %d: %s",
                         self.name, self.batch_id, e)
            raise

    def process_available(self, max_batches: Optional[int] = None) -> int:
        """Synchronous drain: run batches until the source is idle (or
        ``max_batches``). The deterministic test/driver mode."""
        n = 0
        while max_batches is None or n < max_batches:
            if not self.run_once():
                break
            n += 1
        return n

    def _run_loop(self) -> None:
        try:
            while not self._stop.is_set():
                if not self.run_once():
                    self._stop.wait(self.trigger_interval_s)
        except Exception:  # noqa: BLE001 — recorded by run_once; the
            pass           # thread must die quietly, status() says why
        finally:
            if self.state != "failed":
                self.state = "terminated"
            self._terminated.set()

    def start(self) -> "StreamingQuery":
        if self._thread is not None and self._thread.is_alive():
            raise StreamingQueryError(f"query {self.name!r} already "
                                      "running")
        self.state = "running"
        self._stop.clear()
        self._terminated.clear()
        self._thread = threading.Thread(
            target=self._run_loop, daemon=True,
            name=f"stream-{self.name}")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        if self.state == "running":
            self.state = "terminated"
        self._terminated.set()

    def await_termination(self, timeout: Optional[float] = None) -> bool:
        """Block until the query terminates (stop() or failure).
        Returns True when it did."""
        return self._terminated.wait(timeout)

    @property
    def exception(self) -> Optional[BaseException]:
        return self.error

    def status(self) -> Dict[str, Any]:
        with self._lock:
            st: Dict[str, Any] = {
                "name": self.name,
                "state": self.state,
                "batch_id": self.batch_id,
                "watermark": self.watermark,
                "max_event_time": self.max_event_time,
                "rows_limit": self._rows_limit,
                "sink_ms_ewma": (round(self._sink_ms_ewma, 3)
                                 if self._sink_ms_ewma is not None
                                 else None),
                "n_batches": self.n_batches,
                "n_rows": self.n_rows,
                "n_late_rows": self.n_late_rows,
                "n_replayed_batches": self.n_replayed_batches,
                "n_sink_retries": self.n_sink_retries,
                "n_sink_failures": self.n_sink_failures,
                "n_windows_emitted": self.n_windows_emitted,
                "pending_replays": len(self._replay),
                "error": (f"{type(self.error).__name__}: {self.error}"
                          if self.error is not None else None),
            }
        try:
            st["source_backlog"] = int(self.source.backlog())
        except Exception:  # noqa: BLE001
            st["source_backlog"] = None
        if self.window is not None:
            st["open_windows"] = len(self._window_state.windows)
        return st

    def __enter__(self) -> "StreamingQuery":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
