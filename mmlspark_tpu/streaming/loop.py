"""RetrainLoop: auto-redeploy freshly trained checkpoints through the
fleet rollout gates.

The last arc of the retrain->redeploy loop: watch the directory where
``NNLearner.fit_stream`` exports its digest-manifested model
checkpoints, and push each new flip-eligible export through the
coordinator's ``POST /rollout`` — the SAME shadow/canary/auto-rollback
machinery every manual rollout rides (serving/rollout.py), so a bad
retrain can never take the fleet down: the canary gate rolls it back
and the loop simply waits for the next export.

Eligibility is the manifest-last contract: an export directory counts
only once ``checkpoint.sha256.json`` exists (an interrupted export is
invisible), and the rollout staging path re-verifies the digest
strictly on every worker before anything flips. When several exports
appear between polls, only the NEWEST is pushed — intermediate
checkpoints are superseded exactly like intermediate rollouts.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from mmlspark_tpu.core.logs import get_logger
from mmlspark_tpu.io.checkpoint import MANIFEST_FILE

logger = get_logger("streaming.loop")


class RetrainLoop:
    """Watch ``watch_dir`` for flip-eligible checkpoint exports and
    drive each through the coordinator's fleet rollout.

    ``rollout`` carries extra ``POST /rollout`` knobs (``canary``,
    ``shadow_fraction``, ``canary_window_s``, ...) merged into every
    push. One rollout at a time: while one is in flight the loop polls
    ``GET /rollout`` until it lands (``completed`` / ``rolled_back`` /
    ``failed``) before pushing the next candidate; a 409 from a
    concurrent manual rollout just retries next poll.
    """

    _TERMINAL = ("completed", "failed", "rolled_back")

    def __init__(self, watch_dir: str, coordinator_url: str,
                 warmup_payload: Any = None,
                 rollout: Optional[Dict[str, Any]] = None,
                 poll_interval_s: float = 0.5,
                 rollout_timeout_s: float = 120.0,
                 history: int = 32,
                 http_timeout_s: float = 5.0):
        self.watch_dir = os.path.abspath(watch_dir)
        self.coordinator_url = coordinator_url.rstrip("/")
        self.warmup_payload = warmup_payload
        self.rollout_kwargs = dict(rollout or {})
        self.poll_interval_s = float(poll_interval_s)
        self.rollout_timeout_s = float(rollout_timeout_s)
        self.http_timeout_s = float(http_timeout_s)
        self._stop = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._thread: Optional[threading.Thread] = None
        self._last_pushed: Optional[str] = None
        self.current: Optional[Dict[str, Any]] = None
        self.history: "deque[Dict[str, Any]]" = deque(
            maxlen=max(int(history), 1))
        self.n_pushed = 0
        self.n_completed = 0
        self.n_rolled_back = 0
        self.n_failed = 0

    # -- candidate discovery -------------------------------------------------

    def eligible_exports(self) -> List[str]:
        """Sorted export directory names that carry a digest manifest
        (the manifest is written LAST, so presence == complete)."""
        if not os.path.isdir(self.watch_dir):
            return []
        out = []
        for name in sorted(os.listdir(self.watch_dir)):
            d = os.path.join(self.watch_dir, name)
            if os.path.isdir(d) and \
                    os.path.exists(os.path.join(d, MANIFEST_FILE)):
                out.append(name)
        return out

    def _next_candidate(self) -> Optional[str]:
        exports = self.eligible_exports()
        if not exports:
            return None
        newest = exports[-1]
        if self._last_pushed is not None and newest <= self._last_pushed:
            return None
        return newest

    # -- HTTP ----------------------------------------------------------------

    def _post_rollout(self, body: Dict[str, Any]):
        import requests
        return requests.post(f"{self.coordinator_url}/rollout",
                             json=body, timeout=self.http_timeout_s)

    def _get_rollout(self) -> Dict[str, Any]:
        import requests
        r = requests.get(f"{self.coordinator_url}/rollout",
                         timeout=self.http_timeout_s)
        r.raise_for_status()
        return r.json()

    # -- the loop ------------------------------------------------------------

    def _push(self, name: str) -> None:
        body = {"version": name,
                "path": os.path.join(self.watch_dir, name),
                **self.rollout_kwargs}
        if self.warmup_payload is not None:
            body.setdefault("warmup_payload", self.warmup_payload)
        resp = self._post_rollout(body)
        if resp.status_code == 409:
            # a rollout (manual, or a previous push still landing) is
            # in flight: not ours to interrupt — retry next poll
            logger.info("retrain loop: rollout busy (409); will retry "
                        "%s", name)
            return
        resp.raise_for_status()
        self._last_pushed = name
        self.n_pushed += 1
        self._idle.clear()
        self.current = {"version": name, "state": "pushed",
                        "pushed_unix": round(time.time(), 3)}
        logger.info("retrain loop: pushed checkpoint %s into rollout",
                    name)
        self._await_rollout(name)

    def _await_rollout(self, name: str) -> None:
        deadline = time.monotonic() + self.rollout_timeout_s
        final: Dict[str, Any] = {"state": "timeout"}
        while time.monotonic() < deadline and not self._stop.is_set():
            try:
                st = self._get_rollout()
            except Exception as e:  # noqa: BLE001 — coordinator blip:
                logger.warning("retrain loop: rollout poll failed: %s", e)
                self._stop.wait(self.poll_interval_s)
                continue
            if st.get("version") == name:
                self.current = {"version": name, **st}
                if st.get("state") in self._TERMINAL:
                    final = st
                    break
            self._stop.wait(self.poll_interval_s)
        state = final.get("state")
        if state == "timeout" and self._stop.is_set():
            # stop() landed while a healthy rollout was in flight: the
            # coordinator finishes it on its own — recording a failure
            # the rollout never had would page someone for nothing
            state = "interrupted"
        if state == "completed":
            self.n_completed += 1
        elif state == "rolled_back":
            # auto-rollback did its job: the fleet is back on the old
            # version and the loop waits for a better export
            self.n_rolled_back += 1
        elif state != "interrupted":
            self.n_failed += 1
        entry = {"version": name, "state": state,
                 "decision": final.get("decision"),
                 "detail": final.get("detail"),
                 "finished_unix": round(time.time(), 3)}
        self.history.append(entry)
        self.current = None
        self._idle.set()
        (logger.info if state == "completed" else logger.warning)(
            "retrain loop: rollout of %s ended %s", name, state)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                name = self._next_candidate()
                if name is not None:
                    self._push(name)
            except Exception:  # noqa: BLE001 — the loop must survive a
                # transient coordinator/HTTP failure and keep watching
                logger.warning("retrain loop iteration failed",
                               exc_info=True)
                self._idle.set()
                self.current = None
            self._stop.wait(self.poll_interval_s)

    # -- lifecycle / surfaces ------------------------------------------------

    def start(self) -> "RetrainLoop":
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("retrain loop already running")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="retrain-loop")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def await_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until no push is in flight (True when idle)."""
        return self._idle.wait(timeout)

    def status(self) -> Dict[str, Any]:
        return {"watch_dir": self.watch_dir,
                "coordinator": self.coordinator_url,
                "last_pushed": self._last_pushed,
                "current": self.current,
                "n_pushed": self.n_pushed,
                "n_completed": self.n_completed,
                "n_rolled_back": self.n_rolled_back,
                "n_failed": self.n_failed,
                "eligible": self.eligible_exports(),
                "history": list(self.history)}

    def __enter__(self) -> "RetrainLoop":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
