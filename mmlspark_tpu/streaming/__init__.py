"""Micro-batch streaming engine: the structured-streaming half of the
reference system ("MMLSpark: Unifying Machine Learning Ecosystems at
Massive Scales", arxiv 1810.08744) — versioned micro-batches over a
write-ahead offset log + commit log (exactly-once sinks across
crash/restart), event-time watermarks with windowed aggregation, and
source-side backpressure wired into the resilience layer.

The headline consumer is the retrain->redeploy loop
(:mod:`mmlspark_tpu.streaming.loop`): served traffic captured by
:class:`mmlspark_tpu.serving.capture.TrafficCapture` flows through
:class:`~mmlspark_tpu.streaming.traffic.TrafficLogSource` into
``NNLearner.fit_stream``, whose digest-manifested checkpoint exports a
:class:`~mmlspark_tpu.streaming.loop.RetrainLoop` pushes through the
coordinator's shadow/canary rollout gates — the system continuously
learns from its own traffic and redeploys itself with zero downtime.
See docs/streaming.md.
"""

from mmlspark_tpu.streaming.engine import (
    MemoryStreamSource,
    StreamingQuery,
    StreamingQueryError,
    WindowSpec,
)
from mmlspark_tpu.streaming.loop import RetrainLoop
from mmlspark_tpu.streaming.traffic import TrafficLogSource

__all__ = [
    "MemoryStreamSource",
    "RetrainLoop",
    "StreamingQuery",
    "StreamingQueryError",
    "TrafficLogSource",
    "WindowSpec",
]
