"""ctypes binding for the native binary-file reader (binary_reader.cpp).

Same record semantics as the pure-Python reader in ``io/binary.py``
(whole files and zip members as ``(path, bytes)``, deterministic
sorted-path order), but the scan/read/unzip/sample pipeline runs in
native threads off the GIL with bounded prefetch.
"""

from __future__ import annotations

import ctypes
from typing import Iterator, Optional, Tuple

from mmlspark_tpu.native.loader import NativeLoader


def _bind():
    lib = NativeLoader.load_library_by_name("mmlbinary")
    lib.mml_open_reader.restype = ctypes.c_void_p
    lib.mml_open_reader.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_double,
        ctypes.c_uint64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.mml_next_record.restype = ctypes.c_int
    lib.mml_next_record.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.mml_last_error.restype = ctypes.c_char_p
    lib.mml_last_error.argtypes = [ctypes.c_void_p]
    lib.mml_close_reader.argtypes = [ctypes.c_void_p]
    return lib


def native_read_records(path: str,
                        recursive: bool = True,
                        pattern: Optional[str] = None,
                        sample_ratio: float = 1.0,
                        inspect_zip: bool = True,
                        seed: int = 0,
                        n_threads: int = 8,
                        prefetch_files: int = 16,
                        ) -> Iterator[Tuple[str, bytes]]:
    """Yield ``(path, bytes)`` records via the native prefetching reader."""
    import os
    if not os.path.exists(path):  # engine parity: python engine raises too
        raise FileNotFoundError(path)
    lib = _bind()
    handle = lib.mml_open_reader(
        path.encode(), int(recursive),
        pattern.encode() if pattern else None,
        float(sample_ratio), seed, int(inspect_zip),
        n_threads, prefetch_files)
    if not handle:
        raise RuntimeError("mml_open_reader failed")
    try:
        p = ctypes.c_char_p()
        d = ctypes.c_void_p()
        n = ctypes.c_int64()
        while True:
            rc = lib.mml_next_record(handle, ctypes.byref(p),
                                     ctypes.byref(d), ctypes.byref(n))
            if rc == 0:
                return
            if rc < 0:
                raise IOError(lib.mml_last_error(handle).decode())
            data = ctypes.string_at(d.value, n.value) if n.value else b""
            yield p.value.decode(), data
    finally:
        lib.mml_close_reader(handle)
