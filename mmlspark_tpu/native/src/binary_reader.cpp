// Native host-side data loader: whole-file (path, bytes) records with a
// prefetching thread pool and zip-archive inspection.
//
// TPU-native counterpart of the reference's record-reader C++/JVM stack
// (BinaryFileFormat.scala:114 / BinaryRecordReader.scala:34, whose heavy
// lifting happens in Hadoop's native IO): the TPU framework keeps the
// device fed from the host, so file scanning, reading, zip expansion and
// subsampling run in native threads off the Python GIL. Exposed as a
// plain C API consumed over ctypes (loader.py).
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread binary_reader.cpp -lz
//
// Determinism: records are delivered in sorted-path file order regardless
// of thread scheduling (per-file results are re-sequenced), and sampling
// uses a per-file RNG seeded with (seed, file index).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <filesystem>
#include <fstream>
#include <map>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <fnmatch.h>
#include <zlib.h>

namespace fs = std::filesystem;

namespace {

struct Record {
  std::string path;
  std::vector<uint8_t> data;
};

struct FileResult {
  std::vector<Record> records;
  std::string error;  // empty on success
};

// ---------------------------------------------------------------------------
// zip central-directory parsing (no external zip lib; deflate via zlib)
// ---------------------------------------------------------------------------

uint16_t rd16(const uint8_t* p) { return p[0] | (p[1] << 8); }
uint32_t rd32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

bool inflate_raw(const uint8_t* src, size_t src_len, std::vector<uint8_t>* out) {
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -MAX_WBITS) != Z_OK) return false;
  zs.next_in = const_cast<Bytef*>(src);
  zs.avail_in = static_cast<uInt>(src_len);
  zs.next_out = out->data();
  zs.avail_out = static_cast<uInt>(out->size());
  int rc = inflate(&zs, Z_FINISH);
  inflateEnd(&zs);
  return rc == Z_STREAM_END && zs.total_out == out->size();
}

// Expands `blob` (a zip archive) into records named "<zip_path>/<member>".
bool expand_zip(const std::string& zip_path, const std::vector<uint8_t>& blob,
                std::vector<Record>* out, std::string* err) {
  if (blob.size() < 22) { *err = "zip too small"; return false; }
  // find End Of Central Directory (scan back over a possible comment)
  size_t eocd = std::string::npos;
  size_t lo = blob.size() >= 22 + 65535 ? blob.size() - 22 - 65535 : 0;
  for (size_t i = blob.size() - 22 + 1; i-- > lo;) {
    if (rd32(&blob[i]) == 0x06054b50) { eocd = i; break; }
  }
  if (eocd == std::string::npos) { *err = "zip: no EOCD"; return false; }
  uint16_t n_entries = rd16(&blob[eocd + 10]);
  uint32_t cd_off = rd32(&blob[eocd + 16]);
  // zip64: a sentinel field alone is not proof (a legal zip32 archive can
  // hold exactly 65535 members) — the discriminator is the zip64 EOCD
  // locator record (sig 0x07064b50, 20 bytes) directly before the EOCD
  bool has_z64_locator =
      eocd >= 20 && rd32(&blob[eocd - 20]) == 0x07064b50u;
  if (has_z64_locator &&
      (n_entries == 0xFFFFu || cd_off == 0xFFFFFFFFu)) {
    *err = "zip64 archives are not supported";
    return false;
  }

  size_t p = cd_off;
  for (uint16_t e = 0; e < n_entries; ++e) {
    if (p + 46 > blob.size() || rd32(&blob[p]) != 0x02014b50) {
      *err = "zip: bad central directory entry";
      return false;
    }
    uint16_t method = rd16(&blob[p + 10]);
    uint32_t csize = rd32(&blob[p + 20]);
    uint32_t usize = rd32(&blob[p + 24]);
    if (csize == 0xFFFFFFFFu || usize == 0xFFFFFFFFu) {
      *err = "zip64 archives are not supported";
      return false;
    }
    uint16_t name_len = rd16(&blob[p + 28]);
    uint16_t extra_len = rd16(&blob[p + 30]);
    uint16_t comment_len = rd16(&blob[p + 32]);
    uint32_t lho = rd32(&blob[p + 42]);
    std::string name(reinterpret_cast<const char*>(&blob[p + 46]), name_len);
    p += 46 + name_len + extra_len + comment_len;
    if (!name.empty() && name.back() == '/') continue;  // directory entry
    // local header gives the actual data offset
    if (lho + 30 > blob.size() || rd32(&blob[lho]) != 0x04034b50) {
      *err = "zip: bad local header";
      return false;
    }
    size_t data_off = lho + 30 + rd16(&blob[lho + 26]) + rd16(&blob[lho + 28]);
    if (data_off + csize > blob.size()) { *err = "zip: truncated"; return false; }
    Record rec;
    rec.path = zip_path + "/" + name;
    if (method == 0) {  // stored
      rec.data.assign(blob.begin() + data_off, blob.begin() + data_off + csize);
    } else if (method == 8) {  // deflate
      rec.data.resize(usize);
      // empty members: zlib rejects a null next_out, and there is
      // nothing to inflate anyway
      if (usize > 0 && !inflate_raw(&blob[data_off], csize, &rec.data)) {
        *err = "zip: inflate failed for " + name;
        return false;
      }
    } else {
      *err = "zip: unsupported method for " + name;
      return false;
    }
    out->push_back(std::move(rec));
  }
  return true;
}

// ---------------------------------------------------------------------------
// Reader: scan + thread-pool prefetch with in-order delivery
// ---------------------------------------------------------------------------

bool ends_with_nocase(const std::string& s, const std::string& suf) {
  if (s.size() < suf.size()) return false;
  for (size_t i = 0; i < suf.size(); ++i) {
    if (std::tolower(s[s.size() - suf.size() + i]) != suf[i]) return false;
  }
  return true;
}

class Reader {
 public:
  Reader(std::string root, bool recursive, std::string pattern,
         double sample_ratio, uint64_t seed, bool inspect_zip, int n_threads,
         int max_outstanding)
      : sample_ratio_(sample_ratio),
        seed_(seed),
        inspect_zip_(inspect_zip),
        max_outstanding_(std::max(max_outstanding, 1)) {
    scan(root, recursive, pattern);
    n_threads = std::max(1, std::min<int>(n_threads, 64));
    for (int i = 0; i < n_threads; ++i) {
      workers_.emplace_back([this] { work(); });
    }
  }

  ~Reader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_work_.notify_all();
    cv_done_.notify_all();
    for (auto& t : workers_) t.join();
  }

  // 1 = record delivered, 0 = end of stream, -1 = error (see last_error)
  int next(const char** path, const void** data, int64_t* size) {
    while (true) {
      if (rec_idx_ < current_.records.size()) {
        const Record& r = current_.records[rec_idx_++];
        *path = r.path.c_str();
        *data = r.data.data();
        *size = static_cast<int64_t>(r.data.size());
        return 1;
      }
      // current file exhausted: fetch the next file's results in order
      std::unique_lock<std::mutex> lk(mu_);
      if (next_to_deliver_ >= files_.size()) return 0;
      cv_done_.wait(lk, [this] {
        return stop_ || done_.count(next_to_deliver_) > 0;
      });
      if (stop_) return 0;
      current_ = std::move(done_[next_to_deliver_]);
      done_.erase(next_to_deliver_);
      ++next_to_deliver_;
      rec_idx_ = 0;
      cv_work_.notify_all();  // an outstanding slot freed
      if (!current_.error.empty()) {
        last_error_ = files_[next_to_deliver_ - 1] + ": " + current_.error;
        return -1;
      }
    }
  }

  const char* last_error() const { return last_error_.c_str(); }
  int64_t n_files() const { return static_cast<int64_t>(files_.size()); }

 private:
  void scan(const std::string& root, bool recursive,
            const std::string& pattern) {
    std::error_code ec;
    if (fs::is_regular_file(root, ec)) {
      files_.push_back(root);
      return;
    }
    auto match = [&](const fs::path& p) {
      return pattern.empty() ||
             fnmatch(pattern.c_str(), p.filename().c_str(), 0) == 0;
    };
    if (recursive) {
      for (fs::recursive_directory_iterator it(root, ec), end; it != end;
           it.increment(ec)) {
        if (!ec && it->is_regular_file(ec) && match(it->path())) {
          files_.push_back(it->path().string());
        }
      }
    } else {
      for (fs::directory_iterator it(root, ec), end; it != end;
           it.increment(ec)) {
        if (!ec && it->is_regular_file(ec) && match(it->path())) {
          files_.push_back(it->path().string());
        }
      }
    }
    std::sort(files_.begin(), files_.end());
  }

  void work() {
    while (true) {
      size_t idx;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_work_.wait(lk, [this] {
          return stop_ || (next_to_read_ < files_.size() &&
                           next_to_read_ - next_to_deliver_ <
                               static_cast<size_t>(max_outstanding_));
        });
        if (stop_) return;
        idx = next_to_read_++;
      }
      // any escape (bad_alloc on a huge file, filesystem surprise) must
      // surface as a record error, not std::terminate the host process
      FileResult res;
      try {
        res = read_one(idx);
      } catch (const std::exception& e) {
        res.records.clear();
        res.error = std::string("native reader exception: ") + e.what();
      } catch (...) {
        res.records.clear();
        res.error = "native reader exception";
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        done_[idx] = std::move(res);
      }
      cv_done_.notify_all();
    }
  }

  FileResult read_one(size_t idx) {
    FileResult res;
    const std::string& fp = files_[idx];
    std::ifstream f(fp, std::ios::binary | std::ios::ate);
    if (!f) {
      res.error = "cannot open";
      return res;
    }
    auto size = f.tellg();
    f.seekg(0);
    std::vector<uint8_t> blob(static_cast<size_t>(size));
    if (size > 0 && !f.read(reinterpret_cast<char*>(blob.data()), size)) {
      res.error = "short read";
      return res;
    }
    std::vector<Record> recs;
    if (inspect_zip_ && ends_with_nocase(fp, ".zip")) {
      std::string err;
      if (!expand_zip(fp, blob, &recs, &err)) {
        res.error = err;
        return res;
      }
    } else {
      recs.push_back(Record{fp, std::move(blob)});
    }
    if (sample_ratio_ < 1.0) {
      std::mt19937_64 rng(seed_ * 0x9e3779b97f4a7c15ULL + idx);
      std::uniform_real_distribution<double> uni(0.0, 1.0);
      std::vector<Record> kept;
      for (auto& r : recs) {
        if (uni(rng) < sample_ratio_) kept.push_back(std::move(r));
      }
      recs = std::move(kept);
    }
    res.records = std::move(recs);
    return res;
  }

  std::vector<std::string> files_;
  double sample_ratio_;
  uint64_t seed_;
  bool inspect_zip_;
  int max_outstanding_;

  std::mutex mu_;
  std::condition_variable cv_work_, cv_done_;
  std::vector<std::thread> workers_;
  std::map<size_t, FileResult> done_;
  size_t next_to_read_ = 0;     // next file index handed to a worker
  size_t next_to_deliver_ = 0;  // next file index owed to the consumer
  bool stop_ = false;

  // consumer-side state (single-threaded consumer)
  FileResult current_;
  size_t rec_idx_ = 0;
  std::string last_error_;
};

}  // namespace

extern "C" {

void* mml_open_reader(const char* root, int recursive, const char* pattern,
                      double sample_ratio, uint64_t seed, int inspect_zip,
                      int n_threads, int max_outstanding) {
  try {
    return new Reader(root ? root : "", recursive != 0,
                      pattern ? pattern : "", sample_ratio, seed,
                      inspect_zip != 0, n_threads, max_outstanding);
  } catch (...) {
    return nullptr;
  }
}

int mml_next_record(void* r, const char** path, const void** data,
                    int64_t* size) {
  return static_cast<Reader*>(r)->next(path, data, size);
}

const char* mml_last_error(void* r) {
  return static_cast<Reader*>(r)->last_error();
}

int64_t mml_n_files(void* r) { return static_cast<Reader*>(r)->n_files(); }

void mml_close_reader(void* r) { delete static_cast<Reader*>(r); }

int mml_abi_version() { return 1; }

}  // extern "C"
