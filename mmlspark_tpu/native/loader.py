"""Build-and-load machinery for the bundled C++ runtime components.

Parity: `core/env/src/main/scala/NativeLoader.java:28,48-62` — the
reference extracts named ``.so``s (plus a ``NATIVE_MANIFEST`` of
dependencies) from jar resources into a temp dir and ``System.load``s
them, preferring ``java.library.path``. The TPU framework instead ships
C++ *sources* inside the package and compiles them on first use:

search order for ``load_library_by_name(name)``:
1. ``$MMLSPARK_TPU_NATIVE_DIR/lib<name>.so`` (operator-provided prebuilt,
   the ``java.library.path`` analogue),
2. the package build cache (``native/_build``), rebuilt whenever the
   source is newer than the cached binary,
3. fresh compile via ``g++`` (declared in ``_SOURCES``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")

# name -> (sources, extra link flags); the NATIVE_MANIFEST analogue
_SOURCES: Dict[str, List[str]] = {
    "mmlbinary": ["binary_reader.cpp"],
}
_LINK_FLAGS: Dict[str, List[str]] = {
    "mmlbinary": ["-lz"],
}

_lock = threading.Lock()
# name -> CDLL, or the Exception a previous attempt raised (negative cache:
# a missing toolchain must not re-run g++ on every read)
_cache: Dict[str, object] = {}


class NativeLoader:
    """Loads (building if needed) a named native library."""

    @staticmethod
    def load_library_by_name(name: str) -> ctypes.CDLL:
        with _lock:
            hit = _cache.get(name)
            if isinstance(hit, ctypes.CDLL):
                return hit
            if isinstance(hit, Exception):
                raise hit
            try:
                lib = ctypes.CDLL(_find_or_build(name))
            except Exception as e:
                _cache[name] = e
                raise
            _cache[name] = lib
            return lib


def _find_or_build(name: str) -> str:
    so_name = f"lib{name}.so"
    override = os.environ.get("MMLSPARK_TPU_NATIVE_DIR")
    if override:
        cand = os.path.join(override, so_name)
        if os.path.exists(cand):
            return cand
    if name not in _SOURCES:
        raise FileNotFoundError(f"unknown native library {name!r}")
    sources = [os.path.join(_SRC_DIR, s) for s in _SOURCES[name]]
    built = os.path.join(_BUILD_DIR, so_name)
    if os.path.exists(built) and all(
            os.path.getmtime(built) >= os.path.getmtime(s) for s in sources):
        return built
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # compile to a private temp name, then atomically publish: concurrent
    # builders (pytest-xdist, two cold-starting services) must never see
    # a half-written .so
    tmp = f"{built}.tmp.{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           *sources, "-o", tmp, *_LINK_FLAGS.get(name, [])]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise RuntimeError(
            f"native build of {name} failed:\n{proc.stderr[-2000:]}")
    os.replace(tmp, built)
    return built


def native_available(name: str = "mmlbinary") -> bool:
    """True when the named native library can be loaded (builds on demand)."""
    try:
        NativeLoader.load_library_by_name(name)
        return True
    except Exception:
        return False
