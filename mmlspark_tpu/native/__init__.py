"""Native host-side runtime components (C++ behind ctypes).

The reference ships its native engines as prebuilt ``.so``s inside jars,
extracted and loaded by `core/env/src/main/scala/NativeLoader.java:28`.
Here the native layer is built from bundled C++ sources on first use
(g++ is part of the supported toolchain) and cached; every consumer has
a pure-Python fallback so the framework degrades gracefully when no
compiler is present.
"""

from mmlspark_tpu.native.loader import NativeLoader, native_available
from mmlspark_tpu.native.binary import native_read_records

__all__ = ["NativeLoader", "native_available", "native_read_records"]
