"""Ring attention: sequence/context parallelism over a named mesh axis.

Long-context support is first-class in this framework (the reference has
no sequence dimension at all — SURVEY.md §5 "long-context" — so this is
a TPU-native capability extension, not a port). Sequences are sharded
over the ``seq`` mesh axis; each device holds its local block of
queries/keys/values, and key/value blocks rotate around the ring with
``jax.lax.ppermute`` (one ICI hop per step) while a streaming
(online-softmax) accumulator builds the exact attention output —
numerically identical to full attention, with O(S/n) memory per device
and compute/communication overlap left to XLA.

All functions here are *per-device* bodies meant to run inside
``jax.shard_map``; `ring_attention` is the convenience wrapper that
builds the shard_map for a standalone call.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_NEG_INF = -1e30  # large-negative instead of -inf: keeps fully-masked
                  # blocks (causal, future-only) free of inf-inf NaNs


def _mm(spec: str, a, b, compute_dtype):
    """Attention matmul with the shared mixed-precision policy: inputs
    cast to ``compute_dtype`` (e.g. bf16 hits the MXU fast path) with
    f32 MXU accumulation via ``preferred_element_type`` — no separate
    upcast pass over the result; None = plain einsum."""
    if compute_dtype is None:
        return jnp.einsum(spec, a, b)
    return jnp.einsum(spec, a.astype(compute_dtype),
                      b.astype(compute_dtype),
                      preferred_element_type=jnp.float32)


def _resolve_block_impl(s_local: int, dh: int,
                        trainable: bool = False, h: int = None) -> str:
    """``auto`` policy, shared by every ring entry point: the folded
    (feature-major) kernel where its layout pays off — eligible shape,
    short head dim, and the same measured ``s >= 256`` floor as
    ``transformer._attention``'s un-sharded auto (below it, XLA dense
    wins) — else flash on TPU, else the dense path.
    ``trainable=True`` (the ``auto_train`` mode) never resolves to the
    forward-only flash kernel: folded or dense, both differentiable."""
    from mmlspark_tpu.parallel.pallas_attention import (
        flash_available, folded_block_available)
    if (folded_block_available(s_local, s_local, dh, h) and dh < 128
            and s_local >= 256):
        return "folded"
    if not trainable and flash_available():
        return "flash"
    return "dense"


def _block_attn(q, k, v, scale, q_pos, k_pos, causal, compute_dtype=None):
    """One (q-block × kv-block) streaming-attention partial.

    Returns (m, l, o): running max, normalizer, unnormalized output for
    this block, to be merged by the online-softmax accumulator.
    q: [B, Sq, H, Dh]; k, v: [B, Sk, H, Dh]; *_pos: global positions.
    ``compute_dtype``: as in :func:`dense_attention` — matmul inputs in
    that dtype, f32 MXU accumulation, softmax math f32.
    """
    s = _mm("bqhd,bkhd->bhqk", q, k, compute_dtype) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]          # [Sq, Sk]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                              # [B, H, Sq]
    p = jnp.exp(s - m[..., None])
    if causal:
        # rows with no visible key: kill the exp(0)=1 garbage
        p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)                              # [B, H, Sq]
    o = _mm("bhqk,bkhd->bqhd", p, v, compute_dtype)      # [B, Sq, H, Dh]
    return m, l, o


def ring_attention_local(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None,
                         block_impl: str = "dense",
                         compute_dtype=None):
    """Exact attention with sequence sharded over ``axis_name`` (per-device).

    Must run inside ``shard_map``. ``q/k/v``: [B, S_local, H, Dh] — the
    local sequence block. KV blocks rotate around the ring; after step t
    a rank holds the block that started ``t`` ranks behind it. Replaces
    nothing in the reference (no analogue); designed per the blockwise
    ring-attention recipe so context length scales with the ``seq`` axis.

    ``block_impl``: the per-step block attention. ``dense`` (default)
    materializes the (Sq × Sk_local) scores in XLA and is
    differentiable; ``folded`` is the feature-major Pallas path — no
    lane padding at short head dims, scores stay in VMEM, and it is
    ALSO differentiable (:func:`ring_attention_folded_local`'s custom
    VJP — the training-grade long-context engine); ``flash`` is the
    head-per-program Pallas kernel, forward-only (scoring/serving);
    ``*_interpret`` runs the Pallas paths interpreted (CPU debugging;
    requires ``check_vma=False`` on the enclosing shard_map); ``auto``
    picks folded on TPU where eligible, else flash, else dense.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, dh = q.shape
    if block_impl in ("auto", "auto_train"):
        block_impl = _resolve_block_impl(
            s_local, dh, trainable=(block_impl == "auto_train"), h=h)
    if block_impl in ("folded", "folded_interpret"):
        # the folded path is DIFFERENTIABLE (custom VJP over the whole
        # ring — scores stay in VMEM in both directions); mixed
        # precision casts the inputs (the kernels' matmuls accumulate
        # f32 via preferred_element_type, partials stay f32)
        if compute_dtype is not None:
            q, k, v = (q.astype(compute_dtype), k.astype(compute_dtype),
                       v.astype(compute_dtype))
        return ring_attention_folded_local(
            q, k, v, axis_name, causal, scale,
            block_impl == "folded_interpret")
    elif block_impl in ("flash", "flash_interpret"):
        from mmlspark_tpu.parallel.pallas_attention import flash_block_attn
        block_fn = functools.partial(
            flash_block_attn, interpret=(block_impl == "flash_interpret"))
    elif block_impl == "dense":
        block_fn = functools.partial(_block_attn,
                                     compute_dtype=compute_dtype)
    else:
        raise ValueError(f"unknown block_impl {block_impl!r}")
    scale = scale if scale is not None else dh ** -0.5
    q_pos = idx * s_local + jnp.arange(s_local)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(t, carry):
        m, l, o, k_t, v_t = carry
        src = (idx - t) % n                               # origin rank of block
        k_pos = src * s_local + jnp.arange(s_local)
        bm, bl, bo = block_fn(q, k_t, v_t, scale, q_pos, k_pos, causal)
        m_new = jnp.maximum(m, bm)
        c_old = jnp.exp(m - m_new)                        # rescale old state
        c_blk = jnp.exp(bm - m_new)
        l = l * c_old + bl * c_blk
        o = o * c_old[..., None].swapaxes(1, 2) \
            + bo * c_blk[..., None].swapaxes(1, 2)        # [B,Sq,H,Dh] scale
        k_t = jax.lax.ppermute(k_t, axis_name, perm)
        v_t = jax.lax.ppermute(v_t, axis_name, perm)
        return m_new, l, o, k_t, v_t

    # initial accumulators are constants (unvarying); cast them to q's
    # varying-manual-axes set so the loop carry type is stable under VMA
    vma = tuple(jax.typeof(q).vma)
    m0 = jax.lax.pcast(jnp.full((b, h, s_local), _NEG_INF, q.dtype),
                       vma, to="varying")
    l0 = jax.lax.pcast(jnp.zeros((b, h, s_local), q.dtype),
                       vma, to="varying")
    o0 = jnp.zeros_like(q)
    m, l, o, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    l = jnp.maximum(l, 1e-30)                             # fully-masked rows
    return o / l[..., None].swapaxes(1, 2)


# ---------------------------------------------------------------------------
# Differentiable folded ring attention (custom VJP)
# ---------------------------------------------------------------------------
#
# The dense ring path is differentiable but materializes the
# (Sq × Sk_local) scores per ring step; the folded block kernels keep
# them in VMEM but Pallas has no autodiff — so the trainable version is
# a custom VJP over the WHOLE ring: the forward runs the online-softmax
# merge over folded block partials and saves (q, k, v, out, lse); the
# backward runs a SECOND ring pass in which (dk, dv) accumulators
# travel WITH their kv block — each rank adds its q-block's
# FlashAttention-2 contribution to the visiting block's gradients, and
# after n rotations the accumulators arrive home. Everything stays in
# the folded (B, H·Dh, S) layout across steps, so the per-step cost is
# the two Pallas calls plus the ppermutes.


def _scale_of(of, c, h):
    """of (B, H*D, S) * c (B, H, S) broadcast over each head's D."""
    b, hd, s = of.shape
    return (of.reshape(b, h, hd // h, s) * c[:, :, None, :]
            ).reshape(b, hd, s)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_attention_folded_local(q, k, v, axis_name: str,
                                causal: bool = True, scale=None,
                                interpret: bool = False):
    """Differentiable ring attention with the folded block kernels.

    Same contract as :func:`ring_attention_local` (must run inside
    ``shard_map``; q/k/v ``[B, S_local, H, Dh]``), but the (Sq × Sk)
    scores never reach HBM in EITHER direction — the training-grade
    long-context path for short head dims. Gradient parity vs the dense
    ring is pinned in tests/test_transformer.py.
    """
    out, _ = _ring_folded_fwd(q, k, v, axis_name, causal, scale,
                              interpret)
    return out


def _ring_folded_fwd(q, k, v, axis_name, causal, scale, interpret):
    from mmlspark_tpu.parallel.pallas_attention import (
        _fring_call, _to_folded, _from_folded)
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, dh = q.shape
    scale_f = float(scale) if scale is not None else dh ** -0.5
    qpos = (idx * s_local
            + jnp.arange(s_local, dtype=jnp.int32))[None]      # (1, S)
    perm = [(i, (i + 1) % n) for i in range(n)]
    qf, kf, vf = _to_folded(q), _to_folded(k), _to_folded(v)

    def body(t, carry):
        m, l, of, kf_t, vf_t = carry
        src = (idx - t) % n
        kpos = (src * s_local
                + jnp.arange(s_local, dtype=jnp.int32))[:, None]
        bo, bm, bl = _fring_call(qf, kf_t, vf_t, qpos, kpos, h,
                                 scale_f, causal, interpret)
        m_new = jnp.maximum(m, bm)
        c_old = jnp.exp(m - m_new)
        c_blk = jnp.exp(bm - m_new)
        l = l * c_old + bl * c_blk
        of = (_scale_of(of, c_old, h)
              + _scale_of(bo.astype(jnp.float32), c_blk, h))
        kf_t = jax.lax.ppermute(kf_t, axis_name, perm)
        vf_t = jax.lax.ppermute(vf_t, axis_name, perm)
        return m_new, l, of, kf_t, vf_t

    vma = tuple(jax.typeof(q).vma)

    def varying(x):
        return jax.lax.pcast(x, vma, to="varying")

    m0 = varying(jnp.full((b, h, s_local), _NEG_INF, jnp.float32))
    l0 = varying(jnp.zeros((b, h, s_local), jnp.float32))
    of0 = varying(jnp.zeros((b, h * dh, s_local), jnp.float32))
    m, l, of, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, of0, kf, vf))
    l_safe = jnp.maximum(l, 1e-30)
    out_f = _scale_of(of, 1.0 / l_safe, h)
    # +BIG sentinel on no-visibility rows: the backward's
    # exp(st - lse) then underflows to exactly 0 for them
    lse = jnp.where(l > 0, m + jnp.log(l_safe), 1e30)      # (B, H, S)
    out = _from_folded(out_f, h).astype(q.dtype)
    return out, (qf, kf, vf, out_f, lse)


def _ring_folded_bwd(axis_name, causal, scale, interpret, res, dout):
    from mmlspark_tpu.parallel.pallas_attention import (
        _fring_bwd_call, _to_folded, _from_folded)
    qf, kf, vf, out_f, lse = res
    b, hd, s_local = qf.shape
    h = lse.shape[1]
    dh = hd // h
    scale_f = float(scale) if scale is not None else dh ** -0.5
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    qpos = (idx * s_local
            + jnp.arange(s_local, dtype=jnp.int32))[None]
    perm = [(i, (i + 1) % n) for i in range(n)]
    dof = _to_folded(dout).astype(qf.dtype)
    delta = jnp.sum((dof.astype(jnp.float32) * out_f)
                    .reshape(b, h, dh, s_local), axis=2)    # (B, H, S)

    def body(t, carry):
        dq, kf_t, vf_t, dk_acc, dv_acc = carry
        src = (idx - t) % n
        kpos = (src * s_local
                + jnp.arange(s_local, dtype=jnp.int32))[:, None]
        dqb, dkb, dvb = _fring_bwd_call(qf, kf_t, vf_t, dof, lse,
                                        delta, qpos, kpos, h, scale_f,
                                        causal, interpret)
        dq = dq + dqb
        dk_acc = dk_acc + dkb
        dv_acc = dv_acc + dvb
        # gradients travel WITH their kv block: after the full cycle
        # of n rotations each accumulator is back at its owner rank
        kf_t = jax.lax.ppermute(kf_t, axis_name, perm)
        vf_t = jax.lax.ppermute(vf_t, axis_name, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        return dq, kf_t, vf_t, dk_acc, dv_acc

    vma = tuple(jax.typeof(qf).vma)

    def varying(x):
        return jax.lax.pcast(x, vma, to="varying")

    z = varying(jnp.zeros((b, hd, s_local), jnp.float32))
    dq, _, _, dk, dv = jax.lax.fori_loop(
        0, n, body, (z, kf, vf, z, z))
    return (_from_folded(dq, h).astype(qf.dtype),
            _from_folded(dk, h).astype(kf.dtype),
            _from_folded(dv, h).astype(vf.dtype))


ring_attention_folded_local.defvjp(_ring_folded_fwd, _ring_folded_bwd)


def dense_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    compute_dtype=None):
    """Unsharded reference attention (tests + single-device fallback).

    ``compute_dtype`` (e.g. ``jnp.bfloat16``): run the two matmuls with
    inputs cast to it and ``preferred_element_type=float32`` — the MXU
    accumulates in f32 natively, so this hits the bf16 fast path with NO
    separate upcast pass over the [B,H,S,S] scores, while the softmax
    stays f32. This is where half a small LM's training FLOPs live;
    leaving the scores matmul in f32 halves attention MFU on TPU.
    """
    dh = q.shape[-1]
    scale = scale if scale is not None else dh ** -0.5
    s = _mm("bqhd,bkhd->bhqk", q, k, compute_dtype) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return _mm("bhqk,bkhd->bqhd", p, v, compute_dtype)


def ring_attention(q, k, v, mesh, axis_name: str = "seq",
                   causal: bool = True, block_impl: str = "dense"):
    """Standalone sharded ring attention over ``mesh`` (convenience).

    q/k/v: full arrays [B, S, H, Dh]; batch over ``data`` if that axis
    exists in the mesh, sequence over ``axis_name``. ``block_impl`` as
    in :func:`ring_attention_local` — ``folded`` is differentiable
    (custom VJP), ``flash`` forward-only; both Pallas paths run with
    VMA checking off.
    """
    from jax.sharding import PartitionSpec as P
    from mmlspark_tpu.parallel.collectives import shard_map_fn

    if block_impl == "auto":  # resolve BEFORE wiring check_vma so the
        # dense resolution keeps VMA type-checking enabled
        n_seq = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
            axis_name, 1)
        block_impl = _resolve_block_impl(q.shape[1] // max(n_seq, 1),
                                         q.shape[-1], h=q.shape[-2])
    batch_axis = "data" if "data" in mesh.axis_names else None
    spec = P(batch_axis, axis_name)
    fn = shard_map_fn(
        lambda q_, k_, v_: ring_attention_local(q_, k_, v_, axis_name,
                                                causal,
                                                block_impl=block_impl),
        mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=(block_impl == "dense"))
    return fn(q, k, v)
