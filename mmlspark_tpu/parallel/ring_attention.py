"""Ring attention: sequence/context parallelism over a named mesh axis.

Long-context support is first-class in this framework (the reference has
no sequence dimension at all — SURVEY.md §5 "long-context" — so this is
a TPU-native capability extension, not a port). Sequences are sharded
over the ``seq`` mesh axis; each device holds its local block of
queries/keys/values, and key/value blocks rotate around the ring with
``jax.lax.ppermute`` (one ICI hop per step) while a streaming
(online-softmax) accumulator builds the exact attention output —
numerically identical to full attention, with O(S/n) memory per device
and compute/communication overlap left to XLA.

All functions here are *per-device* bodies meant to run inside
``jax.shard_map``; `ring_attention` is the convenience wrapper that
builds the shard_map for a standalone call.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_NEG_INF = -1e30  # large-negative instead of -inf: keeps fully-masked
                  # blocks (causal, future-only) free of inf-inf NaNs


def _mm(spec: str, a, b, compute_dtype):
    """Attention matmul with the shared mixed-precision policy: inputs
    cast to ``compute_dtype`` (e.g. bf16 hits the MXU fast path) with
    f32 MXU accumulation via ``preferred_element_type`` — no separate
    upcast pass over the result; None = plain einsum."""
    if compute_dtype is None:
        return jnp.einsum(spec, a, b)
    return jnp.einsum(spec, a.astype(compute_dtype),
                      b.astype(compute_dtype),
                      preferred_element_type=jnp.float32)


def _resolve_block_impl(s_local: int, dh: int) -> str:
    """``auto`` policy, shared by both ring entry points: the folded
    (feature-major) kernel where its layout pays off (eligible shape,
    short head dim — the same dh < 128 rule as
    ``transformer._attention``'s auto), else flash on TPU, else the
    differentiable dense path."""
    from mmlspark_tpu.parallel.pallas_attention import (
        flash_available, folded_block_available)
    if folded_block_available(s_local, s_local, dh) and dh < 128:
        return "folded"
    if flash_available():
        return "flash"
    return "dense"


def _block_attn(q, k, v, scale, q_pos, k_pos, causal, compute_dtype=None):
    """One (q-block × kv-block) streaming-attention partial.

    Returns (m, l, o): running max, normalizer, unnormalized output for
    this block, to be merged by the online-softmax accumulator.
    q: [B, Sq, H, Dh]; k, v: [B, Sk, H, Dh]; *_pos: global positions.
    ``compute_dtype``: as in :func:`dense_attention` — matmul inputs in
    that dtype, f32 MXU accumulation, softmax math f32.
    """
    s = _mm("bqhd,bkhd->bhqk", q, k, compute_dtype) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]          # [Sq, Sk]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                              # [B, H, Sq]
    p = jnp.exp(s - m[..., None])
    if causal:
        # rows with no visible key: kill the exp(0)=1 garbage
        p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)                              # [B, H, Sq]
    o = _mm("bhqk,bkhd->bqhd", p, v, compute_dtype)      # [B, Sq, H, Dh]
    return m, l, o


def ring_attention_local(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None,
                         block_impl: str = "dense",
                         compute_dtype=None):
    """Exact attention with sequence sharded over ``axis_name`` (per-device).

    Must run inside ``shard_map``. ``q/k/v``: [B, S_local, H, Dh] — the
    local sequence block. KV blocks rotate around the ring; after step t
    a rank holds the block that started ``t`` ranks behind it. Replaces
    nothing in the reference (no analogue); designed per the blockwise
    ring-attention recipe so context length scales with the ``seq`` axis.

    ``block_impl``: the per-step block attention. ``dense`` (default)
    materializes the (Sq × Sk_local) scores in XLA and is
    differentiable — training uses it; ``folded`` is the feature-major
    Pallas streaming kernel (``pallas_attention.folded_block_attn`` —
    no lane padding at short head dims) and ``flash`` the
    head-per-program one; both keep the (Sq × Sk) scores out of HBM
    and are forward-only (no VJP yet — use for scoring/serving);
    ``*_interpret`` runs them interpreted (CPU debugging; requires
    ``check_vma=False`` on the enclosing shard_map); ``auto`` picks
    folded on TPU where eligible, else flash, else dense.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, dh = q.shape
    if block_impl == "auto":
        block_impl = _resolve_block_impl(s_local, dh)
    if block_impl in ("folded", "folded_interpret"):
        from mmlspark_tpu.parallel.pallas_attention import folded_block_attn
        block_fn = functools.partial(
            folded_block_attn,
            interpret=(block_impl == "folded_interpret"))
    elif block_impl in ("flash", "flash_interpret"):
        from mmlspark_tpu.parallel.pallas_attention import flash_block_attn
        block_fn = functools.partial(
            flash_block_attn, interpret=(block_impl == "flash_interpret"))
    elif block_impl == "dense":
        block_fn = functools.partial(_block_attn,
                                     compute_dtype=compute_dtype)
    else:
        raise ValueError(f"unknown block_impl {block_impl!r}")
    scale = scale if scale is not None else dh ** -0.5
    q_pos = idx * s_local + jnp.arange(s_local)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(t, carry):
        m, l, o, k_t, v_t = carry
        src = (idx - t) % n                               # origin rank of block
        k_pos = src * s_local + jnp.arange(s_local)
        bm, bl, bo = block_fn(q, k_t, v_t, scale, q_pos, k_pos, causal)
        m_new = jnp.maximum(m, bm)
        c_old = jnp.exp(m - m_new)                        # rescale old state
        c_blk = jnp.exp(bm - m_new)
        l = l * c_old + bl * c_blk
        o = o * c_old[..., None].swapaxes(1, 2) \
            + bo * c_blk[..., None].swapaxes(1, 2)        # [B,Sq,H,Dh] scale
        k_t = jax.lax.ppermute(k_t, axis_name, perm)
        v_t = jax.lax.ppermute(v_t, axis_name, perm)
        return m_new, l, o, k_t, v_t

    # initial accumulators are constants (unvarying); cast them to q's
    # varying-manual-axes set so the loop carry type is stable under VMA
    vma = tuple(jax.typeof(q).vma)
    m0 = jax.lax.pcast(jnp.full((b, h, s_local), _NEG_INF, q.dtype),
                       vma, to="varying")
    l0 = jax.lax.pcast(jnp.zeros((b, h, s_local), q.dtype),
                       vma, to="varying")
    o0 = jnp.zeros_like(q)
    m, l, o, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    l = jnp.maximum(l, 1e-30)                             # fully-masked rows
    return o / l[..., None].swapaxes(1, 2)


def dense_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    compute_dtype=None):
    """Unsharded reference attention (tests + single-device fallback).

    ``compute_dtype`` (e.g. ``jnp.bfloat16``): run the two matmuls with
    inputs cast to it and ``preferred_element_type=float32`` — the MXU
    accumulates in f32 natively, so this hits the bf16 fast path with NO
    separate upcast pass over the [B,H,S,S] scores, while the softmax
    stays f32. This is where half a small LM's training FLOPs live;
    leaving the scores matmul in f32 halves attention MFU on TPU.
    """
    dh = q.shape[-1]
    scale = scale if scale is not None else dh ** -0.5
    s = _mm("bqhd,bkhd->bhqk", q, k, compute_dtype) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return _mm("bhqk,bkhd->bqhd", p, v, compute_dtype)


def ring_attention(q, k, v, mesh, axis_name: str = "seq",
                   causal: bool = True, block_impl: str = "dense"):
    """Standalone sharded ring attention over ``mesh`` (convenience).

    q/k/v: full arrays [B, S, H, Dh]; batch over ``data`` if that axis
    exists in the mesh, sequence over ``axis_name``. ``block_impl`` as
    in :func:`ring_attention_local` (``folded``/``flash`` variants are
    forward-only and run with VMA checking off).
    """
    from jax.sharding import PartitionSpec as P
    from mmlspark_tpu.parallel.collectives import shard_map_fn

    if block_impl == "auto":  # resolve BEFORE wiring check_vma so the
        # dense resolution keeps VMA type-checking enabled
        n_seq = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
            axis_name, 1)
        block_impl = _resolve_block_impl(q.shape[1] // max(n_seq, 1),
                                         q.shape[-1])
    batch_axis = "data" if "data" in mesh.axis_names else None
    spec = P(batch_axis, axis_name)
    fn = shard_map_fn(
        lambda q_, k_, v_: ring_attention_local(q_, k_, v_, axis_name,
                                                causal,
                                                block_impl=block_impl),
        mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=(block_impl == "dense"))
    return fn(q, k, v)
