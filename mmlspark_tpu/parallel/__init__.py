from mmlspark_tpu.parallel import compat as _compat  # jax.shard_map shim
from mmlspark_tpu.parallel.topology import (
    MeshSpec,
    build_mesh,
    distributed_init,
    local_device_count,
)
from mmlspark_tpu.parallel.sharding import (
    batch_sharding,
    bucket_ladder,
    bucket_target,
    replicated_sharding,
    named_sharding,
    pad_to_bucket,
    pad_to_multiple,
    padded_device_batch,
    round_to_multiple,
    shard_batch,
    unpad,
)
from mmlspark_tpu.parallel.dist import (
    placement_label,
    placement_report,
    put_batch,
    shard_state,
    state_shardings,
    state_specs,
    train_mesh,
)
from mmlspark_tpu.parallel.pipeline import (
    PipelineRunner,
    StagePlan,
    bubble_ratio,
    plan_stages,
    split_rows,
)
from mmlspark_tpu.parallel.ring_attention import (
    dense_attention,
    ring_attention,
    ring_attention_local,
)
from mmlspark_tpu.parallel.pallas_attention import (
    flash_attention,
    flash_attention_folded,
    flash_block_attn,
    folded_block_attn,
)

__all__ = [
    "MeshSpec",
    "dense_attention",
    "flash_attention",
    "flash_attention_folded",
    "flash_block_attn",
    "folded_block_attn",
    "ring_attention",
    "ring_attention_local",
    "build_mesh",
    "distributed_init",
    "local_device_count",
    "batch_sharding",
    "replicated_sharding",
    "named_sharding",
    "bucket_ladder",
    "bucket_target",
    "pad_to_bucket",
    "pad_to_multiple",
    "padded_device_batch",
    "round_to_multiple",
    "shard_batch",
    "unpad",
    "placement_label",
    "placement_report",
    "put_batch",
    "shard_state",
    "state_shardings",
    "state_specs",
    "train_mesh",
]
