"""Pallas flash-attention kernel for the ring-attention block step.

Drop-in replacement for ``ring_attention._block_attn`` (same
``(m, l, o)`` streaming-softmax partials contract) that never
materializes the (Sq × Sk) score matrix in HBM: the KV dimension is the
innermost grid axis, with the running max / normalizer / unnormalized
accumulator carried in VMEM scratch across KV tiles (the canonical TPU
flash pattern — see the pallas guide's grid/scratch sections). QK^T and
P·V run on the MXU per (128 × 128) tile.

Masking uses *global position* operands rather than block indices so the
one kernel serves every ring step: each device's local Q block carries
its global positions, the rotating KV block carries the origin rank's,
and the causal rule ``q_pos >= k_pos`` reproduces full visibility /
no visibility / the diagonal automatically. Sequence padding rides the
same mechanism (padded keys get the INT32-max sentinel position, masked
out even in bidirectional mode).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Q_TILE = 128
KV_TILE = 128
LANE = 128           # pad head_dim to the lane width
_NEG_INF = -1e30
_PAD_POS = np.iinfo(np.int32).max  # sentinel: padded key, always masked


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _tile_live(qpos, kpos, causal: bool):
    """False when the whole (q-tile x kv-tile) is masked out: all-padding
    keys, or (causal) every key strictly in every query's future."""
    kmin = jnp.min(kpos)
    live = kmin != _PAD_POS
    if causal:
        live = live & (jnp.max(qpos) >= kmin)
    return live


def _vma(x):
    """Varying-manual-axes of ``x`` (empty outside shard_map)."""
    return getattr(jax.typeof(x), "vma", frozenset()) or frozenset()


def _flash_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref,
                  o_ref, m_ref, l_ref,
                  acc, m_scr, l_scr, *, scale: float, causal: bool):
    """One (batch*head, q-tile, kv-tile) step of streaming attention."""
    kv_idx = pl.program_id(2)

    @pl.when(kv_idx == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    qpos = qpos_ref[0]                                 # (TQ,)
    kpos = kpos_ref[0]                                 # (TK,)

    # tile skipping: a tile whose every key is padding, or (causal)
    # whose every key is in the future of every query, contributes
    # nothing — skip its two matmuls (half of all tiles under causal)
    live = _tile_live(qpos, kpos, causal)

    @pl.when(live)
    def _():
        q = q_ref[0]                                   # (TQ, D)
        s = jax.lax.dot_general(q, k_ref[0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = (kpos != _PAD_POS)[None, :]
        if causal:
            mask = mask & (qpos[:, None] >= kpos[None, :])
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:]                              # (TQ, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        # fully-masked rows: m_new == -1e30 makes exp(s - m_new) = exp(0);
        # kill those ones so l stays 0 and the ring merge sees "no data"
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)                # (TQ, 1)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        # P·V in the inputs' dtype (bf16 inputs keep the MXU fast path),
        # f32 accumulation via preferred_element_type
        acc[:] = acc[:] * alpha + jnp.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(kv_idx == pl.num_programs(2) - 1)
    def _():
        o_ref[0] = acc[:]                              # unnormalized
        m_ref[0] = m_scr[:]                            # (TQ, 1)
        l_ref[0] = l_scr[:]


@functools.partial(jax.jit,
                   static_argnames=("scale", "causal", "interpret"))
def _flash_call(q, k, v, q_pos, k_pos, scale: float, causal: bool,
                interpret: bool):
    """q (BH, Sq, D), k/v (BH, Sk, D), positions (1, S*) int32 (padded)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // Q_TILE, sk // KV_TILE)
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, Q_TILE), lambda b, i, j: (0, i)),
            pl.BlockSpec((1, KV_TILE), lambda b, i, j: (0, j)),
            pl.BlockSpec((1, Q_TILE, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, KV_TILE, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, KV_TILE, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, Q_TILE, d), lambda b, i, j: (b, i, 0)),
            # stats as (.., TQ, 1) blocks: a trailing dim equal to the
            # full array dim satisfies the TPU (8, 128) tiling rule
            pl.BlockSpec((1, Q_TILE, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, Q_TILE, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            # propagate the varying-manual-axes type so the kernel also
            # composes inside VMA-checked shard_map (the ring body)
            jax.ShapeDtypeStruct((bh, sq, d), jnp.float32, vma=_vma(q)),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32, vma=_vma(q)),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32, vma=_vma(q)),
        ],
        scratch_shapes=[
            # acc / running-max / normalizer live across KV tiles
            pltpu.VMEM((Q_TILE, d), jnp.float32),
            pltpu.VMEM((Q_TILE, 1), jnp.float32),
            pltpu.VMEM((Q_TILE, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q_pos, k_pos, q, k, v)


def flash_block_attn(q, k, v, scale, q_pos, k_pos, causal: bool,
                     interpret: bool = False):
    """``_block_attn`` twin: returns (m (B,H,Sq), l (B,H,Sq),
    o (B,Sq,H,Dh) unnormalized) for the online-softmax ring merge.

    q (B, Sq, H, Dh); k, v (B, Sk, H, Dh); *_pos (S*,) int32 global
    positions. Handles arbitrary (unaligned) Sq/Sk/Dh by padding to the
    (128, 128) flash tiles; padded keys carry a sentinel position and
    can never contribute.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    sq_p, sk_p, d_p = (_round_up(sq, Q_TILE), _round_up(sk, KV_TILE),
                       _round_up(d, LANE))

    def to_bh(x, s, s_pad):                    # (B,S,H,D) -> (B*H, S_p, D_p)
        x = jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)
        return jnp.pad(x, ((0, 0), (0, s_pad - s), (0, d_p - d)))

    qpos_p = jnp.pad(jnp.asarray(q_pos, jnp.int32), (0, sq_p - sq))[None]
    kpos_p = jnp.pad(jnp.asarray(k_pos, jnp.int32), (0, sk_p - sk),
                     constant_values=_PAD_POS)[None]
    o, m, l = _flash_call(to_bh(q, sq, sq_p), to_bh(k, sk, sk_p),
                          to_bh(v, sk, sk_p), qpos_p, kpos_p,
                          float(scale), causal, interpret)
    o = o[:, :sq, :d].reshape(b, h, sq, d).swapaxes(1, 2)  # (B,Sq,H,Dh)
    m = m[:, :sq, 0].reshape(b, h, sq)
    l = l[:, :sq, 0].reshape(b, h, sq)
    return m.astype(q.dtype), l.astype(q.dtype), o.astype(q.dtype)


def flash_available() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# Full flash attention with a Pallas backward (custom VJP)
# ---------------------------------------------------------------------------
#
# The ring path above streams (m, l, o) partials and is forward-only; this
# is the standalone differentiable kernel for the un-ring-sharded (dense)
# attention path in models/transformer.py — the path the single-chip train
# bench measures. Forward reuses _flash_call; backward is the
# FlashAttention-2 recipe: save (q, k, v, out, lse), recompute each score
# tile in VMEM, and accumulate dq (kv-innermost grid) and dk/dv
# (q-innermost grid) in scratch. No (S x S) matrix ever reaches HBM in
# either direction — at seq 1024 x 8 heads x 8 layers the dense path
# round-trips ~2 GB of scores+probabilities per train step, which is pure
# HBM-bandwidth stall on a TPU.


def _flash_dq_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref, do_ref,
                     lse_ref, delta_ref, dq_ref, dq_acc,
                     *, scale: float, causal: bool):
    kv_idx = pl.program_id(2)

    @pl.when(kv_idx == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(_tile_live(qpos_ref[0], kpos_ref[0], causal))
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = (kpos_ref[0] != _PAD_POS)[None, :]
        if causal:
            mask = mask & (qpos_ref[0][:, None] >= kpos_ref[0][None, :])
        p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)   # (TQ, TK)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0])).astype(k.dtype)      # (TQ, TK)
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(kv_idx == pl.num_programs(2) - 1)
    def _():
        dq_ref[0] = dq_acc[:]


def _flash_dkv_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref, do_ref,
                      lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                      *, scale: float, causal: bool):
    q_idx = pl.program_id(2)

    @pl.when(q_idx == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_tile_live(qpos_ref[0], kpos_ref[0], causal))
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = (kpos_ref[0] != _PAD_POS)[None, :]
        if causal:
            mask = mask & (qpos_ref[0][:, None] >= kpos_ref[0][None, :])
        p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)   # (TQ, TK)
        dv_acc[:] += jax.lax.dot_general(                   # p^T @ do
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0])).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(                   # ds^T @ q
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(q_idx == pl.num_programs(2) - 1)
    def _():
        dk_ref[0] = dk_acc[:]
        dv_ref[0] = dv_acc[:]


@functools.partial(jax.jit,
                   static_argnames=("scale", "causal", "interpret"))
def _flash_bwd_call(q, k, v, do, lse, delta, q_pos, k_pos,
                    scale: float, causal: bool, interpret: bool):
    """All (BH, S_pad, D_pad) f32; lse/delta (BH, S_pad, 1)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // Q_TILE, sk // KV_TILE
    q_spec = pl.BlockSpec((1, Q_TILE, d), lambda b, i, j: (b, i, 0))
    kv_spec_dq = pl.BlockSpec((1, KV_TILE, d), lambda b, i, j: (b, j, 0))
    stat_spec = pl.BlockSpec((1, Q_TILE, 1), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, scale=scale, causal=causal),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, Q_TILE), lambda b, i, j: (0, i)),
            pl.BlockSpec((1, KV_TILE), lambda b, i, j: (0, j)),
            q_spec, kv_spec_dq, kv_spec_dq, q_spec, stat_spec, stat_spec,
        ],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), jnp.float32,
                                       vma=_vma(q)),
        scratch_shapes=[pltpu.VMEM((Q_TILE, d), jnp.float32)],
        interpret=interpret,
    )(q_pos, k_pos, q, k, v, do, lse, delta)

    # dk/dv accumulate across q tiles -> q is the innermost grid axis
    q_spec2 = pl.BlockSpec((1, Q_TILE, d), lambda b, j, i: (b, i, 0))
    kv_spec2 = pl.BlockSpec((1, KV_TILE, d), lambda b, j, i: (b, j, 0))
    stat_spec2 = pl.BlockSpec((1, Q_TILE, 1), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, scale=scale, causal=causal),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, Q_TILE), lambda b, j, i: (0, i)),
            pl.BlockSpec((1, KV_TILE), lambda b, j, i: (0, j)),
            q_spec2, kv_spec2, kv_spec2, q_spec2, stat_spec2, stat_spec2,
        ],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32, vma=_vma(q)),
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32, vma=_vma(q)),
        ],
        scratch_shapes=[pltpu.VMEM((KV_TILE, d), jnp.float32),
                        pltpu.VMEM((KV_TILE, d), jnp.float32)],
        interpret=interpret,
    )(q_pos, k_pos, q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, scale=None,
                    interpret: bool = False, bwd_impl: str = "xla"):
    """Differentiable flash attention, [B, S, H, Dh] in/out.

    Forward = the streaming kernel above (normalized, saves the
    log-sum-exp). Backward recomputes ``p = exp(s - lse)`` and applies
    the FlashAttention-2 gradient algebra, via one of two engines:

    - ``bwd_impl="xla"`` (default): the recompute as XLA einsums. The
      (S x S) probabilities exist transiently but XLA fuses the chain;
      at head_dim 64 this is FASTER than the Pallas backward below,
      whose (128-lane) head padding doubles every matmul's work.
    - ``bwd_impl="pallas"``: dq and dk/dv Pallas kernels accumulating in
      VMEM scratch — nothing (S x S) ever reaches HBM, the right regime
      for long sequences where the dense recompute stops fitting.

    Numerically equivalent to :func:`ring_attention.dense_attention` in
    value and gradients to f32 tolerance (tests/test_transformer.py).
    ``interpret=True`` runs the kernels interpreted for CPU tests.
    """
    out, _ = _flash_fwd(q, k, v, causal, scale, interpret, bwd_impl)
    return out


def _layout(q, k, v):
    """Shared fwd/bwd padded (B*H, S_pad, D_pad) layout + positions."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    sq_p, sk_p, d_p = (_round_up(sq, Q_TILE), _round_up(sk, KV_TILE),
                       _round_up(d, LANE))

    def to_bh(x, s, s_pad):                 # keeps dtype (bf16 stays bf16)
        x = jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)
        return jnp.pad(x, ((0, 0), (0, s_pad - s), (0, d_p - d)))

    qpos = jnp.pad(jnp.arange(sq, dtype=jnp.int32), (0, sq_p - sq))[None]
    kpos = jnp.pad(jnp.arange(sk, dtype=jnp.int32), (0, sk_p - sk),
                   constant_values=_PAD_POS)[None]
    return (b, sq, sk, h, d, sq_p, sk_p, d_p, to_bh, qpos, kpos)


def _flash_fwd(q, k, v, causal, scale, interpret, bwd_impl):
    (b, sq, sk, h, d, sq_p, sk_p, d_p, to_bh, qpos, kpos) = _layout(q, k, v)
    scale_f = float(scale) if scale is not None else d ** -0.5
    o, m, l = _flash_call(to_bh(q, sq, sq_p), to_bh(k, sk, sk_p),
                          to_bh(v, sk, sk_p), qpos, kpos,
                          scale_f, causal, interpret)   # all f32 (BH,Sq_p,.)
    l_safe = jnp.maximum(l, 1e-30)
    out_bh = o / l_safe                                  # normalized
    # lse = m + log l reconstructs p = exp(s - lse) tile-locally in the
    # backward; fully-masked rows get +BIG so their p (and grads) are 0
    lse_bh = jnp.where(l > 0, m + jnp.log(l_safe), 1e30)  # (BH, Sq_p, 1)
    out = out_bh[:, :sq, :d].reshape(b, h, sq, d).swapaxes(1, 2)
    return out.astype(q.dtype), (q, k, v, out_bh, lse_bh)


def _flash_bwd(causal, scale, interpret, bwd_impl, res, dout):
    q, k, v, out_bh, lse_bh = res
    (b, sq, sk, h, d, sq_p, sk_p, d_p, to_bh, qpos, kpos) = _layout(q, k, v)
    scale_f = float(scale) if scale is not None else d ** -0.5

    if bwd_impl == "xla":
        # dense recompute: p from the saved lse, then the FA-2 gradient
        # algebra as einsums (bf16 matmuls, f32 accumulation)
        lse = lse_bh[:, :sq, 0].reshape(b, h, sq)        # (B, H, Sq)
        out = out_bh[:, :sq, :d].reshape(b, h, sq, d).swapaxes(1, 2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale_f
        p = jnp.exp(s - lse[..., None])                  # (B, H, Sq, Sk)
        if causal:
            mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
            p = jnp.where(mask[None, None], p, 0.0)
        do = dout.astype(jnp.float32)
        delta = jnp.sum(do * out, axis=-1)               # (B, Sq, H)
        pc = p.astype(q.dtype)
        dv = jnp.einsum("bhqk,bqhd->bkhd", pc, dout,
                        preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", dout, v,
                        preferred_element_type=jnp.float32)
        ds = (p * (dp - jnp.swapaxes(delta, 1, 2)[..., None])) \
            .astype(q.dtype)
        dq = jnp.einsum("bhqk,bkhd->bqhd", ds, k,
                        preferred_element_type=jnp.float32) * scale_f
        dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q,
                        preferred_element_type=jnp.float32) * scale_f
        return (dq.astype(q.dtype), dk.astype(k.dtype),
                dv.astype(v.dtype))

    do_bh = to_bh(dout, sq, sq_p)
    delta = jnp.sum(do_bh.astype(jnp.float32) * out_bh, axis=-1,
                    keepdims=True)                       # (BH, Sq_p, 1)
    dq, dk, dv = _flash_bwd_call(
        to_bh(q, sq, sq_p), to_bh(k, sk, sk_p), to_bh(v, sk, sk_p),
        do_bh, lse_bh, delta, qpos, kpos, scale_f, causal, interpret)

    def from_bh(x, s):
        return x[:, :s, :d].reshape(b, h, s, d).swapaxes(1, 2)

    return (from_bh(dq, sq).astype(q.dtype),
            from_bh(dk, sk).astype(k.dtype),
            from_bh(dv, sk).astype(v.dtype))


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Folded (feature-major) flash kernels — the short-head-dim regime
# ---------------------------------------------------------------------------
#
# The kernels above put head_dim on the LANE axis, so head_dim 64 pads to
# the 128-lane width: every DMA moves 2x the bytes and every d-output
# matmul does 2x the work. That is exactly the regime of the train bench
# (8 heads x 64), where the padded backward measures slower than XLA's
# dense attention. The folded layout dodges the padding entirely:
#
#   q, k, v, o:  (B, H*Dh, S)   — heads*features on the SUBLANE axis
#                                  (8-multiple, no 128 constraint),
#                                  sequence tiles on the lane axis
#   per head:    X[h*Dh:(h+1)*Dh, :] — a cheap sublane slice
#
# Every matmul runs in transposed form — s^T = k_h . q_h (contract the
# feature sublanes), o_h = v_h . p^T — so no operand or output ever has
# fewer than 128 live lanes, whatever Dh is (Dh % 8 == 0). The softmax
# runs over the SUBLANE axis of s^T with (1, TQ) running stats. One grid
# step processes every head of a (q-tile, kv-tile) block, so K/V tiles
# are DMA'd once per q-tile, not once per head.

F_TILE = 512   # q/kv tile edge (clamped to S; S must divide by it)


def _fold_tile(s: int) -> int:
    for t in (F_TILE, 256, 128):
        if s % t == 0:
            return t
    return 0


# VMEM the folded kernels' largest pass (dk/dv backward) may request:
# q/k/v/do blocks double-buffered + two f32 output blocks + two f32
# accumulator scratches, all (H*Dh, tile) — ~40 bytes per (H*Dh x tile)
# element. 14 MB keeps a healthy margin under the ~16 MB v5e VMEM.
_FOLDED_VMEM_BUDGET = 14 * 2**20


def _folded_shape_ok(sq: int, sk: int, d: int,
                     h: Optional[int] = None) -> bool:
    """Same-length self-attention, tileable S, sublane-aligned head —
    the shape half of the folded-kernel eligibility (backend-agnostic:
    interpret mode runs these shapes on CPU too). Pass ``h`` to also
    bound the folded (H*Dh, tile) working set against VMEM: every
    buffer in these kernels carries ALL heads, so wide-head configs
    (large H*Dh) can exceed VMEM even at short head dims — the auto
    policies must fall back rather than fail the Mosaic compile
    (r4 advisor)."""
    ok = sq == sk and d % 8 == 0 and _fold_tile(sq) > 0
    if ok and h is not None:
        ok = h * d * _fold_tile(sq) * 40 <= _FOLDED_VMEM_BUDGET
    return ok


def folded_available(sq: int, sk: int, d: int,
                     h: Optional[int] = None) -> bool:
    return _folded_shape_ok(sq, sk, d, h) and \
        jax.default_backend() == "tpu"


def _causal_mask_t(i, j, tq: int, tk: int):
    """Mask for the TRANSPOSED score tile s^T (TK, TQ): key pos <= q pos."""
    qpos = i * tq + jax.lax.broadcasted_iota(jnp.int32, (tk, tq), 1)
    kpos = j * tk + jax.lax.broadcasted_iota(jnp.int32, (tk, tq), 0)
    return kpos <= qpos


def _ffwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr,
                 *, scale: float, causal: bool, h: int, d: int,
                 tq: int, tk: int):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    live = (j * tk <= i * tq + tq - 1) if causal else (j >= 0)

    @pl.when(live)
    def _():
        mask = _causal_mask_t(i, j, tq, tk) if causal else None
        for hh in range(h):
            sl = slice(hh * d, (hh + 1) * d)
            st = jax.lax.dot_general(                      # (TK, TQ)
                k_ref[0, sl, :], q_ref[0, sl, :],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                st = jnp.where(mask, st, _NEG_INF)
            m_prev = m_scr[hh]                             # (1, TQ)
            m_new = jnp.maximum(m_prev,
                                jnp.max(st, axis=0, keepdims=True))
            pt = jnp.exp(st - m_new)                       # (TK, TQ)
            alpha = jnp.exp(m_prev - m_new)
            l_scr[hh] = l_scr[hh] * alpha + jnp.sum(pt, axis=0,
                                                    keepdims=True)
            acc[sl, :] = acc[sl, :] * alpha + jax.lax.dot_general(
                v_ref[0, sl, :], pt.astype(v_ref.dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[hh] = m_new

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        for hh in range(h):
            sl = slice(hh * d, (hh + 1) * d)
            l_safe = jnp.maximum(l_scr[hh], 1e-30)         # (1, TQ)
            o_ref[0, sl, :] = (acc[sl, :] / l_safe).astype(o_ref.dtype)
            lse_ref[0, hh] = (m_scr[hh] + jnp.log(l_safe))[0]


def _fdq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dq_ref, dq_acc, *, scale: float, causal: bool, h: int,
                d: int, tq: int, tk: int):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = (j * tk <= i * tq + tq - 1) if causal else (j >= 0)

    @pl.when(live)
    def _():
        mask = _causal_mask_t(i, j, tq, tk) if causal else None
        for hh in range(h):
            sl = slice(hh * d, (hh + 1) * d)
            kh, qh = k_ref[0, sl, :], q_ref[0, sl, :]
            st = jax.lax.dot_general(
                kh, qh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                st = jnp.where(mask, st, _NEG_INF)
            lse = lse_ref[0, hh].reshape(1, tq)
            pt = jnp.exp(st - lse)                         # (TK, TQ)
            dpt = jax.lax.dot_general(                     # do . v
                v_ref[0, sl, :], do_ref[0, sl, :],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dst = pt * (dpt - delta_ref[0, hh].reshape(1, tq))
            dq_acc[sl, :] += jax.lax.dot_general(          # (D, TQ)
                kh, dst.astype(kh.dtype), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _fdkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                 causal: bool, h: int, d: int, tq: int, tk: int):
    j, i = pl.program_id(1), pl.program_id(2)

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = (j * tk <= i * tq + tq - 1) if causal else (j >= 0)

    @pl.when(live)
    def _():
        mask = _causal_mask_t(i, j, tq, tk) if causal else None
        for hh in range(h):
            sl = slice(hh * d, (hh + 1) * d)
            qh, doh = q_ref[0, sl, :], do_ref[0, sl, :]
            st = jax.lax.dot_general(
                k_ref[0, sl, :], qh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                st = jnp.where(mask, st, _NEG_INF)
            pt = jnp.exp(st - lse_ref[0, hh].reshape(1, tq))
            dv_acc[sl, :] += jax.lax.dot_general(          # do . p
                doh, pt.astype(doh.dtype), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            dpt = jax.lax.dot_general(
                v_ref[0, sl, :], doh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dst = (pt * (dpt - delta_ref[0, hh].reshape(1, tq))
                   ).astype(qh.dtype)
            dk_acc[sl, :] += jax.lax.dot_general(          # (D, TK)
                qh, dst, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale

    @pl.when(i == pl.num_programs(2) - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("h", "scale", "causal",
                                             "interpret"))
def _ffwd_call(qf, kf, vf, h: int, scale: float, causal: bool,
               interpret: bool):
    """qf/kf/vf (B, H*D, S) -> (o (B, H*D, S), lse (B, H, S) f32)."""
    b, hd, s = qf.shape
    d = hd // h
    t = _fold_tile(s)
    grid = (b, s // t, s // t)
    kernel = functools.partial(_ffwd_kernel, scale=scale, causal=causal,
                               h=h, d=d, tq=t, tk=t)
    seq_spec = pl.BlockSpec((1, hd, t), lambda b_, i, j: (b_, 0, i))
    kv_spec = pl.BlockSpec((1, hd, t), lambda b_, i, j: (b_, 0, j))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[seq_spec, kv_spec, kv_spec],
        out_specs=[seq_spec,
                   pl.BlockSpec((1, h, t), lambda b_, i, j: (b_, 0, i))],
        out_shape=[
            jax.ShapeDtypeStruct((b, hd, s), qf.dtype, vma=_vma(qf)),
            jax.ShapeDtypeStruct((b, h, s), jnp.float32, vma=_vma(qf)),
        ],
        scratch_shapes=[pltpu.VMEM((hd, t), jnp.float32),
                        pltpu.VMEM((h, 1, t), jnp.float32),
                        pltpu.VMEM((h, 1, t), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf)


@functools.partial(jax.jit, static_argnames=("h", "scale", "causal",
                                             "interpret"))
def _fbwd_call(qf, kf, vf, dof, lse, delta, h: int, scale: float,
               causal: bool, interpret: bool):
    """Folded backward: all (B, H*D, S); lse/delta (B, H, S) f32."""
    b, hd, s = qf.shape
    d = hd // h
    t = _fold_tile(s)
    n = s // t
    f32 = jnp.float32

    q_spec = pl.BlockSpec((1, hd, t), lambda b_, i, j: (b_, 0, i))
    kv_spec = pl.BlockSpec((1, hd, t), lambda b_, i, j: (b_, 0, j))
    st_spec = pl.BlockSpec((1, h, t), lambda b_, i, j: (b_, 0, i))
    dq = pl.pallas_call(
        functools.partial(_fdq_kernel, scale=scale, causal=causal,
                          h=h, d=d, tq=t, tk=t),
        grid=(b, n, n),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, st_spec, st_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, hd, s), f32, vma=_vma(qf)),
        scratch_shapes=[pltpu.VMEM((hd, t), f32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    # dk/dv accumulate across q tiles -> q innermost; note the index
    # maps swap (b, j, i)
    q_spec2 = pl.BlockSpec((1, hd, t), lambda b_, j, i: (b_, 0, i))
    kv_spec2 = pl.BlockSpec((1, hd, t), lambda b_, j, i: (b_, 0, j))
    st_spec2 = pl.BlockSpec((1, h, t), lambda b_, j, i: (b_, 0, i))
    dk, dv = pl.pallas_call(
        functools.partial(_fdkv_kernel, scale=scale, causal=causal,
                          h=h, d=d, tq=t, tk=t),
        grid=(b, n, n),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, st_spec2, st_spec2],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct((b, hd, s), f32, vma=_vma(qf)),
                   jax.ShapeDtypeStruct((b, hd, s), f32, vma=_vma(qf))],
        scratch_shapes=[pltpu.VMEM((hd, t), f32),
                        pltpu.VMEM((hd, t), f32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)
    return dq, dk, dv


def _to_folded(x):
    """(B, S, H, D) -> (B, H*D, S)."""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 3, 1).reshape(b, h * d, s)


def _from_folded(x, h: int):
    """(B, H*D, S) -> (B, S, H, D)."""
    b, hd, s = x.shape
    return x.reshape(b, h, hd // h, s).transpose(0, 3, 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_folded(q, k, v, causal: bool = True, scale=None,
                           interpret: bool = False):
    """Differentiable folded flash attention, [B, S, H, Dh] in/out.

    The short-head-dim twin of :func:`flash_attention`: same streaming
    algorithm and FA-2 backward algebra, feature-major kernels (heads on
    the sublane axis — see the section comment). Use when
    :func:`folded_available`; numerics match ``dense_attention`` to f32
    tolerance (tests/test_transformer.py).
    """
    out, _ = _ffold_fwd(q, k, v, causal, scale, interpret)
    return out


def _ffold_fwd(q, k, v, causal, scale, interpret):
    b, s, h, d = q.shape
    scale_f = float(scale) if scale is not None else d ** -0.5
    qf, kf, vf = _to_folded(q), _to_folded(k), _to_folded(v)
    of, lse = _ffwd_call(qf, kf, vf, h, scale_f, causal, interpret)
    return _from_folded(of, h).astype(q.dtype), (qf, kf, vf, of, lse)


def _ffold_bwd(causal, scale, interpret, res, dout):
    qf, kf, vf, of, lse = res
    b, hd, s = qf.shape
    h = lse.shape[1]
    d = hd // h
    scale_f = float(scale) if scale is not None else d ** -0.5
    dof = _to_folded(dout).astype(qf.dtype)
    # delta_h = sum_d do * out, per (b, h, s) — cast BEFORE the product
    # so bf16 inputs multiply in f32 (matching _flash_bwd's numerics)
    delta = jnp.sum((dof.astype(jnp.float32) * of.astype(jnp.float32))
                    .reshape(b, h, d, s), axis=2)          # (B, H, S)
    dq, dk, dv = _fbwd_call(qf, kf, vf, dof, lse, delta, h, scale_f,
                            causal, interpret)
    return (_from_folded(dq, h).astype(qf.dtype),
            _from_folded(dk, h).astype(kf.dtype),
            _from_folded(dv, h).astype(vf.dtype))


flash_attention_folded.defvjp(_ffold_fwd, _ffold_bwd)


# ---------------------------------------------------------------------------
# Folded ring-block kernel (position-aware, forward-only)
# ---------------------------------------------------------------------------
#
# The :func:`flash_block_attn` twin in the feature-major layout: the ring
# path's per-step block attention for short head dims. Positions are
# kernel operands — the query block's as a lane-oriented (1, S) row, the
# rotating KV block's as a sublane-oriented (S, 1) column (the transposed
# score tile s^T (TK, TQ) masks with kpos on sublanes, qpos on lanes) —
# so one kernel serves every ring step: full / diagonal / no visibility
# fall out of ``k_pos <= q_pos``, padded keys carry the sentinel.
# Returns the ring merge's (m, l, o-unnormalized) partials contract.


def _fring_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref,
                  o_ref, m_ref, l_ref, acc, m_scr, l_scr,
                  *, scale: float, causal: bool, h: int, d: int):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    qpos = qpos_ref[0]                                  # (TQ,) lanes
    kpos = kpos_ref[:, 0:1]                             # (TK, 1) sublanes
    kmin = jnp.min(kpos)
    live = kmin != _PAD_POS
    if causal:
        live = live & (jnp.max(qpos) >= kmin)

    @pl.when(live)
    def _():
        mask = kpos != _PAD_POS                         # (TK, 1)
        if causal:
            mask = mask & (kpos <= qpos[None, :])       # (TK, TQ)
        for hh in range(h):
            sl = slice(hh * d, (hh + 1) * d)
            st = jax.lax.dot_general(                   # (TK, TQ)
                k_ref[0, sl, :], q_ref[0, sl, :],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            st = jnp.where(mask, st, _NEG_INF)
            m_prev = m_scr[hh]                          # (1, TQ)
            m_new = jnp.maximum(m_prev,
                                jnp.max(st, axis=0, keepdims=True))
            # fully-masked columns: m_new == -1e30 makes exp(st - m_new)
            # = exp(0); kill those so l stays 0 (ring merge: "no data")
            pt = jnp.where(mask, jnp.exp(st - m_new), 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_scr[hh] = l_scr[hh] * alpha + jnp.sum(pt, axis=0,
                                                    keepdims=True)
            acc[sl, :] = acc[sl, :] * alpha + jax.lax.dot_general(
                v_ref[0, sl, :], pt.astype(v_ref.dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[hh] = m_new

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        for hh in range(h):
            sl = slice(hh * d, (hh + 1) * d)
            o_ref[0, sl, :] = acc[sl, :].astype(o_ref.dtype)  # UNnormalized
            m_ref[0, hh] = m_scr[hh][0]
            l_ref[0, hh] = l_scr[hh][0]


@functools.partial(jax.jit, static_argnames=("h", "scale", "causal",
                                             "interpret"))
def _fring_call(qf, kf, vf, qpos, kpos_t, h: int, scale: float,
                causal: bool, interpret: bool):
    """qf/kf/vf (B, H*D, S); qpos (1, S); kpos_t (S, 1) int32."""
    b, hd, s = qf.shape
    d = hd // h
    t = _fold_tile(s)
    grid = (b, s // t, s // t)
    seq_spec = pl.BlockSpec((1, hd, t), lambda b_, i, j: (b_, 0, i))
    kv_spec = pl.BlockSpec((1, hd, t), lambda b_, i, j: (b_, 0, j))
    st_spec = pl.BlockSpec((1, h, t), lambda b_, i, j: (b_, 0, i))
    return pl.pallas_call(
        functools.partial(_fring_kernel, scale=scale, causal=causal,
                          h=h, d=d),
        grid=grid,
        in_specs=[pl.BlockSpec((1, t), lambda b_, i, j: (0, i)),
                  pl.BlockSpec((t, 1), lambda b_, i, j: (j, 0)),
                  seq_spec, kv_spec, kv_spec],
        out_specs=[seq_spec, st_spec, st_spec],
        out_shape=[
            # the UNNORMALIZED accumulator stays f32 whatever the input
            # dtype: the ring merge rescales it across n steps, and
            # quantizing each step's partial to bf16 would compound
            # (the dense ring keeps f32 partials too)
            jax.ShapeDtypeStruct((b, hd, s), jnp.float32, vma=_vma(qf)),
            jax.ShapeDtypeStruct((b, h, s), jnp.float32, vma=_vma(qf)),
            jax.ShapeDtypeStruct((b, h, s), jnp.float32, vma=_vma(qf)),
        ],
        scratch_shapes=[pltpu.VMEM((hd, t), jnp.float32),
                        pltpu.VMEM((h, 1, t), jnp.float32),
                        pltpu.VMEM((h, 1, t), jnp.float32)],
        interpret=interpret,
    )(qpos, kpos_t, qf, kf, vf)


# same eligibility as the differentiable folded kernel (the ring's
# local blocks are same-length by construction)
folded_block_available = folded_available


def _frdq_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref, do_ref,
                 lse_ref, delta_ref, dq_ref, dq_acc,
                 *, scale: float, causal: bool, h: int, d: int):
    """Position-aware folded dq for one ring block pair (kv inner)."""
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    qpos = qpos_ref[0]
    kpos = kpos_ref[:, 0:1]
    kmin = jnp.min(kpos)
    live = kmin != _PAD_POS
    if causal:
        live = live & (jnp.max(qpos) >= kmin)

    @pl.when(live)
    def _():
        mask = kpos != _PAD_POS
        if causal:
            mask = mask & (kpos <= qpos[None, :])
        for hh in range(h):
            sl = slice(hh * d, (hh + 1) * d)
            kh, qh = k_ref[0, sl, :], q_ref[0, sl, :]
            st = jax.lax.dot_general(
                kh, qh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            st = jnp.where(mask, st, _NEG_INF)
            # lse rows with no visible key carry the +BIG sentinel, so
            # exp(-inf - BIG) underflows to exactly 0 — no garbage flows
            pt = jnp.exp(st - lse_ref[0, hh].reshape(1, -1))
            dpt = jax.lax.dot_general(
                v_ref[0, sl, :], do_ref[0, sl, :],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dst = pt * (dpt - delta_ref[0, hh].reshape(1, -1))
            dq_acc[sl, :] += jax.lax.dot_general(
                kh, dst.astype(kh.dtype), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _frdkv_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref, do_ref,
                  lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                  *, scale: float, causal: bool, h: int, d: int):
    """Position-aware folded dk/dv for one ring block pair (q inner)."""
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    qpos = qpos_ref[0]
    kpos = kpos_ref[:, 0:1]
    kmin = jnp.min(kpos)
    live = kmin != _PAD_POS
    if causal:
        live = live & (jnp.max(qpos) >= kmin)

    @pl.when(live)
    def _():
        mask = kpos != _PAD_POS
        if causal:
            mask = mask & (kpos <= qpos[None, :])
        for hh in range(h):
            sl = slice(hh * d, (hh + 1) * d)
            qh, doh = q_ref[0, sl, :], do_ref[0, sl, :]
            st = jax.lax.dot_general(
                k_ref[0, sl, :], qh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            st = jnp.where(mask, st, _NEG_INF)
            pt = jnp.exp(st - lse_ref[0, hh].reshape(1, -1))
            dv_acc[sl, :] += jax.lax.dot_general(
                doh, pt.astype(doh.dtype), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            dpt = jax.lax.dot_general(
                v_ref[0, sl, :], doh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dst = (pt * (dpt - delta_ref[0, hh].reshape(1, -1))
                   ).astype(qh.dtype)
            dk_acc[sl, :] += jax.lax.dot_general(
                qh, dst, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale

    @pl.when(i == pl.num_programs(2) - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("h", "scale", "causal",
                                             "interpret"))
def _fring_bwd_call(qf, kf, vf, dof, lse, delta, qpos, kpos_t,
                    h: int, scale: float, causal: bool, interpret: bool):
    """Folded ring-block backward: one (q-block, kv-block) pair.
    qf/kf/vf/dof (B, H*D, S); lse/delta (B, H, S) f32 (lse carries +BIG
    on no-visibility rows); qpos (1, S); kpos_t (S, 1) int32."""
    b, hd, s = qf.shape
    d = hd // h
    t = _fold_tile(s)
    n = s // t
    f32 = jnp.float32

    q_spec = pl.BlockSpec((1, hd, t), lambda b_, i, j: (b_, 0, i))
    kv_spec = pl.BlockSpec((1, hd, t), lambda b_, i, j: (b_, 0, j))
    st_spec = pl.BlockSpec((1, h, t), lambda b_, i, j: (b_, 0, i))
    dq = pl.pallas_call(
        functools.partial(_frdq_kernel, scale=scale, causal=causal,
                          h=h, d=d),
        grid=(b, n, n),
        in_specs=[pl.BlockSpec((1, t), lambda b_, i, j: (0, i)),
                  pl.BlockSpec((t, 1), lambda b_, i, j: (j, 0)),
                  q_spec, kv_spec, kv_spec, q_spec, st_spec, st_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, hd, s), f32, vma=_vma(qf)),
        scratch_shapes=[pltpu.VMEM((hd, t), f32)],
        interpret=interpret,
    )(qpos, kpos_t, qf, kf, vf, dof, lse, delta)

    q_spec2 = pl.BlockSpec((1, hd, t), lambda b_, j, i: (b_, 0, i))
    kv_spec2 = pl.BlockSpec((1, hd, t), lambda b_, j, i: (b_, 0, j))
    st_spec2 = pl.BlockSpec((1, h, t), lambda b_, j, i: (b_, 0, i))
    dk, dv = pl.pallas_call(
        functools.partial(_frdkv_kernel, scale=scale, causal=causal,
                          h=h, d=d),
        grid=(b, n, n),
        in_specs=[pl.BlockSpec((1, t), lambda b_, j, i: (0, i)),
                  pl.BlockSpec((t, 1), lambda b_, j, i: (j, 0)),
                  q_spec2, kv_spec2, kv_spec2, q_spec2, st_spec2,
                  st_spec2],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct((b, hd, s), f32, vma=_vma(qf)),
                   jax.ShapeDtypeStruct((b, hd, s), f32, vma=_vma(qf))],
        scratch_shapes=[pltpu.VMEM((hd, t), f32),
                        pltpu.VMEM((hd, t), f32)],
        interpret=interpret,
    )(qpos, kpos_t, qf, kf, vf, dof, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Paged decode attention: the block-table gather kernel
# ---------------------------------------------------------------------------
#
# The decode plane's paged KV cache (models/transformer.py) reads each
# slot's K/V through a per-slot page table. The XLA path materializes
# every slot's FULL virtual lane per layer per step
# (``c_l[page_tables]`` — an [N, pages_per_slot, page, H, Dh] gather
# written back to HBM) before one masked attention over it: at decode
# the op is bandwidth-bound, and that intermediate doubles the bytes
# every step moves. This kernel fuses gather + streaming-softmax
# attention: the page table rides SCALAR PREFETCH (the index map reads
# ``table[n, p]`` to aim each K/V page DMA), so pages stream
# HBM -> VMEM exactly once, scores and the running (m, l, acc) stats
# live in VMEM, and nothing lane-shaped ever lands in HBM. Dead pages
# (whole page past the slot's position — including every unclaimed
# entry aimed at the scratch page) skip their compute entirely.
#
# The dense gather stays the CPU/interpret fallback with token-for-
# token parity pinned (tests/test_transformer.py TestPagedAttnKernel).


def _paged_attn_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                       acc, m_scr, l_scr, *, scale: float,
                       page_size: int):
    """One (slot, page) step: q (1, H, Dh) against the slot's p-th
    claimed page (1, page, H, Dh), streaming-softmax stats carried in
    VMEM scratch across the page axis."""
    n, p = pl.program_id(0), pl.program_id(1)

    @pl.when(p == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    pos = pos_ref[n]
    base = p * page_size

    # dead-page skip: the whole page is past this slot's position
    # (scratch-aimed unclaimed entries always are) — no DMA was free,
    # but the compute is
    @pl.when(base <= pos)
    def _():
        q = q_ref[0]                                    # (H, Dh)
        k = k_ref[0]                                    # (page, H, Dh)
        v = v_ref[0]
        # per-head scores via broadcast-multiply-reduce (the op is
        # bandwidth-bound at decode widths; no MXU tile pays off at
        # page_size x head_dim)
        s = jnp.sum(k.astype(jnp.float32) * q[None].astype(jnp.float32),
                    axis=2) * scale                     # (page, H)
        idx = base + jax.lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0)               # (page, 1)
        s = jnp.where(idx <= pos, s, _NEG_INF)
        m_prev = m_scr[:]                               # (1, H)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=0, keepdims=True))
        pw = jnp.where(idx <= pos, jnp.exp(s - m_new), 0.0)  # (page, H)
        alpha = jnp.exp(m_prev - m_new)                 # (1, H)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(pw, axis=0,
                                              keepdims=True)
        acc[:] = acc[:] * alpha.T + jnp.sum(
            pw[:, :, None] * v.astype(jnp.float32), axis=0)  # (H, Dh)
        m_scr[:] = m_new

    @pl.when(p == pl.num_programs(1) - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:], 1e-30)           # (1, H)
        o_ref[0] = (acc[:] / l_safe.T).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "page_size",
                                             "interpret"))
def paged_decode_attention(q, k_pages, v_pages, page_tables, pos,
                           scale: float, page_size: int,
                           interpret: bool = False):
    """Fused paged-attention for one decode step of one layer.

    ``q`` (N, H, Dh) — each slot's single query (rope applied);
    ``k_pages``/``v_pages`` (n_pages, page_size, H, Dh) — the layer's
    shared page pool AFTER this step's K/V write; ``page_tables``
    (N, pages_per_slot) int32; ``pos`` (N,) int32. Returns the
    normalized attention output (N, H, Dh) — numerically the paged
    dense-gather path (softmax over ``index <= pos`` of the virtual
    lane), computed without ever materializing the lane."""
    n, h, d = q.shape
    pps = page_tables.shape[1]
    kernel = functools.partial(_paged_attn_kernel, scale=float(scale),
                               page_size=int(page_size))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n, pps),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda n_, p_, tbl, ps_: (n_, 0, 0)),
            # the paged gather itself: the page DMA is AIMED by the
            # scalar-prefetched table — block (table[n, p], ...) of the
            # shared pool streams in, no host- or HBM-side gather
            pl.BlockSpec((1, page_size, h, d),
                         lambda n_, p_, tbl, ps_: (tbl[n_, p_], 0, 0, 0)),
            pl.BlockSpec((1, page_size, h, d),
                         lambda n_, p_, tbl, ps_: (tbl[n_, p_], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d),
                               lambda n_, p_, tbl, ps_: (n_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),    # acc
            pltpu.VMEM((1, h), jnp.float32),    # running max
            pltpu.VMEM((1, h), jnp.float32),    # normalizer
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, h, d), q.dtype),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), pos.astype(jnp.int32),
      q, k_pages, v_pages)


def paged_attention_available() -> bool:
    """Whether the fused paged-attention kernel can run compiled on
    this backend (TPU); everywhere else the dense gather is the
    fallback and ``interpret=True`` serves the parity tests."""
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# Flash prefill: streaming-softmax attention for the prefill builders
# ---------------------------------------------------------------------------
#
# All three prefill builders in models/transformer.py historically
# materialized the full score matrix through ``jax.nn.softmax`` — [S, S]
# for the in-flight builders, [S, V] (V = pages_per_slot * page_size)
# for the offset/prefix builder's whole-virtual-lane attention. At long
# prompt buckets that intermediate dominates prefill HBM traffic the
# same way the dense lane gather dominated decode. Two engines replace
# it behind ``attn_impl``:
#
# * in-flight prefill (build_prefill / build_paged_prefill) attends
#   over the q/k/v it just computed — :func:`flash_prefill_attention`,
#   the (m, l, acc) streaming kernel above, normalized, forward-only;
# * the prefix prefill attends over the slot's PAGED virtual lane —
#   :func:`paged_prefix_prefill_attention` extends the
#   ``paged_decode_attention`` scalar-prefetch idiom along the query
#   axis: grid (q-tile, page), each page's DMA aimed by the table,
#   running stats carried in VMEM scratch across pages, causal mask
#   ``virtual_index <= hit_len + row`` — the scratch-page overshoot
#   convention (dead pages skip compute; unclaimed entries aim at
#   page 0 and are always dead) is preserved exactly.


def flash_prefill_attention(q, k, v, scale=None,
                            interpret: bool = False):
    """Normalized causal flash self-attention for the in-flight
    prefill path: ``q``/``k``/``v`` [B, S, H, Dh] -> [B, S, H, Dh],
    forward-only, no [S, S] score matrix in HBM. Numerics match
    ``dense_attention(q, k, v, causal=True)`` (same default
    ``Dh**-0.5`` scale, f32 accumulation) to streaming-softmax
    reassociation tolerance; token-for-token argmax parity is
    test-pinned."""
    return flash_attention(q, k, v, True, scale, interpret)


def _paged_prefix_kernel(tbl_ref, hit_ref, q_ref, k_ref, v_ref, o_ref,
                         acc, m_scr, l_scr, *, scale: float,
                         page_size: int, s_real: int, q_tile: int):
    """One (q-tile, page) step of prefix-prefill attention: queries
    (H, TQ, Dh) at virtual positions ``hit_len + row`` against the
    slot's p-th table page, streaming-softmax stats carried in VMEM
    scratch across the page axis (the innermost grid dim)."""
    i, p = pl.program_id(0), pl.program_id(1)

    @pl.when(p == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    hit = hit_ref[0]
    # bucket-pad rows past the real suffix clamp to the LAST real row:
    # they become harmless duplicates (sliced off outside) and the
    # dead-page liveness bound below stays exactly hit + s_real - 1 —
    # padding never drags extra pages live
    row = jnp.minimum(
        i * q_tile + jax.lax.broadcasted_iota(jnp.int32,
                                              (1, q_tile, 1), 1),
        s_real - 1)
    qpos = hit + row                                    # (1, TQ, 1)
    base = p * page_size

    # dead-page skip: the whole page starts past every query's
    # position (every unclaimed scratch-aimed entry does) — the DMA
    # was free-running but the compute is skipped
    @pl.when(base <= hit + s_real - 1)
    def _():
        q = q_ref[:]                                    # (H, TQ, Dh)
        k = k_ref[0]                                    # (page, H, Dh)
        v = v_ref[0]
        # per-head MXU scores: contract Dh, batch H -> (H, TQ, page)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale
        idx = base + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2)            # (1, 1, page)
        mask = idx <= qpos                              # (1, TQ, page)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_scr[:]                               # (H, TQ, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        pw = jnp.where(mask, jnp.exp(s - m_new), 0.0)   # (H, TQ, page)
        alpha = jnp.exp(m_prev - m_new)                 # (H, TQ, 1)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(pw, axis=2,
                                              keepdims=True)
        # P·V: contract the page axis, batch H -> (H, TQ, Dh)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            pw, v.astype(jnp.float32), (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(p == pl.num_programs(1) - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:], 1e-30)           # (H, TQ, 1)
        o_ref[:] = (acc[:] / l_safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "page_size",
                                             "interpret"))
def paged_prefix_prefill_attention(q, k_pages, v_pages, page_table,
                                   hit_len, scale: float,
                                   page_size: int,
                                   interpret: bool = False):
    """Fused prefix-prefill attention for one layer of one slot.

    ``q`` (S, H, Dh) — the suffix queries (rope applied at virtual
    positions ``hit_len + j``); ``k_pages``/``v_pages``
    (n_pages, page_size, H, Dh) — the layer's shared page pool AFTER
    the suffix K/V scatter; ``page_table`` (pages_per_slot,) int32 —
    the slot's full table (shared prefix pages first, then private
    pages; unclaimed entries aim at scratch page 0); ``hit_len`` a
    TRACED int32 scalar (hit depth is data, not shape). Returns the
    normalized attention output (S, H, Dh) — numerically the dense
    whole-virtual-lane gather+softmax path of
    ``build_paged_prefix_prefill``, computed without ever
    materializing the [S, V] score matrix or the gathered lane."""
    s, h, d = q.shape
    pps = page_table.shape[0]
    # q tiles on the sublane axis: 128 for MXU-sized buckets, the
    # 8-aligned minimum for short suffix buckets (Dh rides the lane
    # axis unpadded, the decode kernel's convention)
    q_tile = min(Q_TILE, _round_up(s, 8))
    s_pad = _round_up(s, q_tile)
    qt = jnp.pad(q.astype(jnp.float32), ((0, s_pad - s), (0, 0),
                                         (0, 0))).transpose(1, 0, 2)
    kernel = functools.partial(
        _paged_prefix_kernel, scale=float(scale),
        page_size=int(page_size), s_real=s, q_tile=q_tile)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_pad // q_tile, pps),
        in_specs=[
            pl.BlockSpec((h, q_tile, d),
                         lambda i_, p_, tbl, hl_: (0, i_, 0)),
            # the paged gather: each page DMA aimed by the
            # scalar-prefetched table, exactly the decode kernel's idiom
            pl.BlockSpec((1, page_size, h, d),
                         lambda i_, p_, tbl, hl_: (tbl[p_], 0, 0, 0)),
            pl.BlockSpec((1, page_size, h, d),
                         lambda i_, p_, tbl, hl_: (tbl[p_], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((h, q_tile, d),
                               lambda i_, p_, tbl, hl_: (0, i_, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, q_tile, d), jnp.float32),    # acc
            pltpu.VMEM((h, q_tile, 1), jnp.float32),    # running max
            pltpu.VMEM((h, q_tile, 1), jnp.float32),    # normalizer
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((h, s_pad, d), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32),
      jnp.reshape(hit_len, (1,)).astype(jnp.int32),
      qt, k_pages, v_pages)
    return out.transpose(1, 0, 2)[:s]


def flash_prefill_available() -> bool:
    """Whether the flash prefill kernels can run compiled on this
    backend (TPU); everywhere else the dense-softmax paths are the
    fallback and ``interpret=True`` serves the parity tests."""
    return jax.default_backend() == "tpu"


def folded_block_attn(q, k, v, scale, q_pos, k_pos, causal: bool,
                      interpret: bool = False):
    """:func:`flash_block_attn` twin in the folded layout: returns
    (m (B,H,Sq), l (B,H,Sq), o (B,Sq,H,Dh) unnormalized) for the
    online-softmax ring merge. Requires
    :func:`folded_block_available` shapes (the ring's local blocks are
    same-length by construction)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if not _folded_shape_ok(sq, sk, d, h):
        # the flash twin pads arbitrary shapes; this layout cannot —
        # fail with the rule, not a ZeroDivisionError inside the trace
        raise ValueError(
            f"folded_block_attn needs same-length blocks (sq={sq}, "
            f"sk={sk}), head_dim % 8 == 0 (got {d}), a 128-tileable "
            f"sequence, and an (H*Dh x tile) working set inside the "
            f"VMEM budget (H*Dh={h * d}); use block_impl='flash' (or "
            f"'auto') for other shapes")
    qf, kf, vf = _to_folded(q), _to_folded(k), _to_folded(v)
    qpos = jnp.asarray(q_pos, jnp.int32)[None]            # (1, S)
    kpos_t = jnp.asarray(k_pos, jnp.int32)[:, None]       # (S, 1)
    o, m, l = _fring_call(qf, kf, vf, qpos, kpos_t, h, float(scale),
                          causal, interpret)
    return (m.astype(q.dtype), l.astype(q.dtype),
            _from_folded(o, h).astype(q.dtype))
