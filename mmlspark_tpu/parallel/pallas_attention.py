"""Pallas flash-attention kernel for the ring-attention block step.

Drop-in replacement for ``ring_attention._block_attn`` (same
``(m, l, o)`` streaming-softmax partials contract) that never
materializes the (Sq × Sk) score matrix in HBM: the KV dimension is the
innermost grid axis, with the running max / normalizer / unnormalized
accumulator carried in VMEM scratch across KV tiles (the canonical TPU
flash pattern — see the pallas guide's grid/scratch sections). QK^T and
P·V run on the MXU per (128 × 128) tile.

Masking uses *global position* operands rather than block indices so the
one kernel serves every ring step: each device's local Q block carries
its global positions, the rotating KV block carries the origin rank's,
and the causal rule ``q_pos >= k_pos`` reproduces full visibility /
no visibility / the diagonal automatically. Sequence padding rides the
same mechanism (padded keys get the INT32-max sentinel position, masked
out even in bidirectional mode).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Q_TILE = 128
KV_TILE = 128
LANE = 128           # pad head_dim to the lane width
_NEG_INF = -1e30
_PAD_POS = np.iinfo(np.int32).max  # sentinel: padded key, always masked


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _vma(x):
    """Varying-manual-axes of ``x`` (empty outside shard_map)."""
    return getattr(jax.typeof(x), "vma", frozenset()) or frozenset()


def _flash_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref,
                  o_ref, m_ref, l_ref,
                  acc, m_scr, l_scr, *, scale: float, causal: bool):
    """One (batch*head, q-tile, kv-tile) step of streaming attention."""
    kv_idx = pl.program_id(2)

    @pl.when(kv_idx == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    q = q_ref[0]                                       # (TQ, D)
    s = jax.lax.dot_general(q, k_ref[0],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    qpos = qpos_ref[0]                                 # (TQ,)
    kpos = kpos_ref[0]                                 # (TK,)
    mask = (kpos != _PAD_POS)[None, :]
    if causal:
        mask = mask & (qpos[:, None] >= kpos[None, :])
    s = jnp.where(mask, s, _NEG_INF)

    m_prev = m_scr[:]                                  # (TQ, 1)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    # fully-masked rows: m_new == -1e30 makes exp(s - m_new) = exp(0);
    # kill those ones so l stays 0 and the ring merge sees "no data"
    p = jnp.where(mask, p, 0.0)
    alpha = jnp.exp(m_prev - m_new)                    # (TQ, 1)
    l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc[:] = acc[:] * alpha + jnp.dot(
        p, v_ref[0], preferred_element_type=jnp.float32)
    m_scr[:] = m_new

    @pl.when(kv_idx == pl.num_programs(2) - 1)
    def _():
        o_ref[0] = acc[:]                              # unnormalized
        m_ref[0] = m_scr[:]                            # (TQ, 1)
        l_ref[0] = l_scr[:]


@functools.partial(jax.jit,
                   static_argnames=("scale", "causal", "interpret"))
def _flash_call(q, k, v, q_pos, k_pos, scale: float, causal: bool,
                interpret: bool):
    """q (BH, Sq, D), k/v (BH, Sk, D), positions (1, S*) int32 (padded)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // Q_TILE, sk // KV_TILE)
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, Q_TILE), lambda b, i, j: (0, i)),
            pl.BlockSpec((1, KV_TILE), lambda b, i, j: (0, j)),
            pl.BlockSpec((1, Q_TILE, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, KV_TILE, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, KV_TILE, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, Q_TILE, d), lambda b, i, j: (b, i, 0)),
            # stats as (.., TQ, 1) blocks: a trailing dim equal to the
            # full array dim satisfies the TPU (8, 128) tiling rule
            pl.BlockSpec((1, Q_TILE, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, Q_TILE, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            # propagate the varying-manual-axes type so the kernel also
            # composes inside VMA-checked shard_map (the ring body)
            jax.ShapeDtypeStruct((bh, sq, d), jnp.float32, vma=_vma(q)),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32, vma=_vma(q)),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32, vma=_vma(q)),
        ],
        scratch_shapes=[
            # acc / running-max / normalizer live across KV tiles
            pltpu.VMEM((Q_TILE, d), jnp.float32),
            pltpu.VMEM((Q_TILE, 1), jnp.float32),
            pltpu.VMEM((Q_TILE, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q_pos, k_pos, q, k, v)


def flash_block_attn(q, k, v, scale, q_pos, k_pos, causal: bool,
                     interpret: bool = False):
    """``_block_attn`` twin: returns (m (B,H,Sq), l (B,H,Sq),
    o (B,Sq,H,Dh) unnormalized) for the online-softmax ring merge.

    q (B, Sq, H, Dh); k, v (B, Sk, H, Dh); *_pos (S*,) int32 global
    positions. Handles arbitrary (unaligned) Sq/Sk/Dh by padding to the
    (128, 128) flash tiles; padded keys carry a sentinel position and
    can never contribute.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    sq_p, sk_p, d_p = (_round_up(sq, Q_TILE), _round_up(sk, KV_TILE),
                       _round_up(d, LANE))

    def to_bh(x, s, s_pad):                    # (B,S,H,D) -> (B*H, S_p, D_p)
        x = jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)
        return jnp.pad(x, ((0, 0), (0, s_pad - s), (0, d_p - d)))

    qpos_p = jnp.pad(jnp.asarray(q_pos, jnp.int32), (0, sq_p - sq))[None]
    kpos_p = jnp.pad(jnp.asarray(k_pos, jnp.int32), (0, sk_p - sk),
                     constant_values=_PAD_POS)[None]
    o, m, l = _flash_call(to_bh(q, sq, sq_p), to_bh(k, sk, sk_p),
                          to_bh(v, sk, sk_p), qpos_p, kpos_p,
                          float(scale), causal, interpret)
    o = o[:, :sq, :d].reshape(b, h, sq, d).swapaxes(1, 2)  # (B,Sq,H,Dh)
    m = m[:, :sq, 0].reshape(b, h, sq)
    l = l[:, :sq, 0].reshape(b, h, sq)
    return m.astype(q.dtype), l.astype(q.dtype), o.astype(q.dtype)


def flash_available() -> bool:
    return jax.default_backend() == "tpu"
