"""Pipeline parallelism over mesh slices: the third serving axis.

Data parallelism replicates a model per device; tensor parallelism
shards one copy across a mesh; both cap out when a model does not fit
(or does not divide) one slice. Pipeline parallelism partitions the
model's **stage graph** — an ordered chain of layers — across device
slices and drives **micro-batched frames** through the stages: while
slice 1 runs micro-batch *i* through its layers, slice 0 is already
running micro-batch *i+1* through the earlier layers. Steady state
keeps every slice busy except for the fill/drain **bubble**, whose
fraction for a balanced K-stage pipeline over M micro-batches is the
GPipe number ``(K-1)/(M+K-1)``.

This module owns the three mechanical pieces:

* :func:`plan_stages` — the **stage placement rule**: a contiguous
  partition of per-layer costs minimizing the slowest stage (classic
  linear-partition DP), mapped onto contiguous device slices.
* :class:`PipelineRunner` — the **micro-batch driver**: dispatches
  each micro-batch through the stage chain with a ``device_put``
  boundary transfer between slices. JAX dispatch is asynchronous, so
  one host thread (the serving plane's executor stage thread, when a
  :class:`~mmlspark_tpu.models.nn.NNModel` with ``pipeline_parallel``
  is dispatched) keeps every slice's queue full — the inter-stage
  overlap happens on the devices, exactly as on real chips.
* **bubble accounting** — per-stage service times from a blocked probe
  pass plus the schedule model give a measured ``bubble_ratio`` (the
  ``/stats`` "pipeline" block; dispatch spans carry
  ``pipeline_stage=k``).

The boundary buffers ride donation where the stage functions donate
(jit-level concern of the stage builder); ragged tail micro-batches
reuse one padded staging buffer via ``dist.put_batch(pad_cache=...)``
semantics (see :func:`split_rows` — sizes are derived once from the
bucketed frame, so the tail never re-pads per call).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["StagePlan", "plan_stages", "split_rows", "PipelineRunner",
           "bubble_ratio"]


@dataclasses.dataclass(frozen=True)
class StagePlan:
    """A contiguous layer partition mapped onto device slices."""

    #: per-stage ``(start, stop)`` layer index ranges (python slices)
    boundaries: Tuple[Tuple[int, int], ...]
    #: per-stage device lists (contiguous slices of the host's devices)
    devices: Tuple[Tuple[Any, ...], ...]
    #: per-stage summed layer costs (the balance evidence)
    costs: Tuple[float, ...]

    @property
    def n_stages(self) -> int:
        return len(self.boundaries)


def _partition_costs(costs: Sequence[float], k: int) -> List[int]:
    """Contiguous k-partition of ``costs`` minimizing the max part sum
    (linear-partition DP, O(n^2 k) — layer counts are tens, not
    millions). Returns the k-1 cut points."""
    n = len(costs)
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + float(c))

    def part_sum(i, j):               # costs[i:j]
        return prefix[j] - prefix[i]

    # dp[j][p] = minimal max-part-sum partitioning costs[:j] into p parts
    INF = float("inf")
    dp = [[INF] * (k + 1) for _ in range(n + 1)]
    cut = [[0] * (k + 1) for _ in range(n + 1)]
    dp[0][0] = 0.0
    for j in range(1, n + 1):
        for p in range(1, min(j, k) + 1):
            for i in range(p - 1, j):
                cand = max(dp[i][p - 1], part_sum(i, j))
                if cand < dp[j][p]:
                    dp[j][p] = cand
                    cut[j][p] = i
    cuts = []
    j, p = n, k
    while p > 1:
        i = cut[j][p]
        cuts.append(i)
        j, p = i, p - 1
    return sorted(cuts)


def plan_stages(costs: Sequence[float], n_stages: int,
                devices: Optional[Sequence[Any]] = None) -> StagePlan:
    """The stage placement rule: partition a layer chain's ``costs``
    into ``n_stages`` contiguous stages minimizing the slowest stage
    (the pipeline's pace-setter), and map stage *k* onto the *k*-th
    contiguous slice of ``devices``.

    ``costs`` is one number per layer — the stage builder passes param
    bytes (a serviceable proxy for per-layer work on the serving
    forward; paramless activation layers cost an epsilon so they glue
    to their neighbors). Every stage gets at least one layer and every
    slice the same device count (``len(devices)`` must divide by
    ``n_stages``)."""
    import jax
    n_stages = int(n_stages)
    if n_stages < 2:
        raise ValueError(f"pipeline needs n_stages >= 2 (got {n_stages})")
    if len(costs) < n_stages:
        raise ValueError(
            f"cannot split {len(costs)} layers into {n_stages} stages")
    devices = list(devices) if devices is not None else list(jax.devices())
    if len(devices) < n_stages:
        raise ValueError(
            f"{n_stages} stages need >= {n_stages} devices "
            f"(have {len(devices)})")
    if len(devices) % n_stages:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_stages} "
            f"equal slices")
    per = len(devices) // n_stages
    cuts = _partition_costs(list(costs), n_stages)
    bounds = []
    start = 0
    for c in cuts + [len(costs)]:
        bounds.append((start, c))
        start = c
    slices = tuple(tuple(devices[k * per:(k + 1) * per])
                   for k in range(n_stages))
    stage_costs = tuple(float(sum(costs[a:b])) for a, b in bounds)
    return StagePlan(boundaries=tuple(bounds), devices=slices,
                     costs=stage_costs)


def split_rows(n_rows: int, microbatches: int, multiple: int = 1
               ) -> List[Tuple[int, int]]:
    """Micro-batch row ranges for an ``n_rows`` frame: up to
    ``microbatches`` contiguous ranges, every range divisible by
    ``multiple`` (the stage mesh's data-axis size) except possibly by
    construction none — the frame arrives bucket-padded to the
    multiple, so ranges derived here never force a re-pad. Sizes are a
    deterministic function of (n_rows, microbatches, multiple): for a
    fixed bucket ladder the micro-batch shape set is fixed, which is
    what keeps the compiled-executable set bounded."""
    multiple = max(int(multiple), 1)
    if n_rows <= 0:
        return []
    if n_rows % multiple:
        raise ValueError(
            f"pipeline frames must arrive padded to the stage multiple "
            f"({multiple}); got {n_rows} rows — the bucket ladder "
            f"should have rounded this up")
    units = n_rows // multiple
    m = max(min(int(microbatches), units), 1)
    per = (units + m - 1) // m * multiple     # equal-ish, multiple-divisible
    out = []
    start = 0
    while start < n_rows:
        stop = min(start + per, n_rows)
        out.append((start, stop))
        start = stop
    return out


def bubble_ratio(stage_ms: Sequence[float], n_micro: int) -> float:
    """Measured steady-state bubble fraction of one pipelined frame.

    With per-stage service times ``t_k`` and ``M`` micro-batches, the
    schedule's wall bound is ``(M-1) * t_max + sum_k t_k`` (the slowest
    stage paces steady state; the chain sum is the fill+drain) and the
    busy device-time is ``M * sum_k t_k`` over ``K`` slices:
    ``bubble = 1 - busy / (K * wall)``. For balanced stages this is
    exactly GPipe's ``(K-1)/(M+K-1)``."""
    ts = [max(float(t), 1e-9) for t in stage_ms]
    K, M = len(ts), max(int(n_micro), 1)
    if K < 2:
        return 0.0
    t_max, t_sum = max(ts), sum(ts)
    wall = (M - 1) * t_max + t_sum
    return max(0.0, min(1.0, 1.0 - (M * t_sum) / (K * wall)))


class PipelineRunner:
    """Drive micro-batches through a chain of placed stage functions.

    ``stages`` is a list of ``(fn, params, placement, devices)``:
    ``fn(params, x) -> y`` (jitted, bound to its slice via the
    placements), ``placement`` the sharding/device its INPUT must be
    transferred to (the ``device_put`` boundary), ``devices`` the
    human-readable slice for reports. The driver dispatches mb-major
    (the GPipe order); JAX's async dispatch keeps all slices busy from
    one host thread. ``probe()`` runs one micro-batch through the
    chain *blocked* to measure per-stage service times — the bubble
    evidence — and is called once at warmup, never on the live path.
    """

    def __init__(self, stages: List[Tuple[Callable, Any, Any, Tuple[str, ...]]],
                 microbatches: int = 4):
        if len(stages) < 2:
            raise ValueError("PipelineRunner needs >= 2 stages")
        self.stages = stages
        self.microbatches = max(int(microbatches), 2)
        self.stage_ms: List[float] = [0.0] * len(stages)
        self._probed = False
        self.last_n_micro = 0
        self.last_wall_ms = 0.0
        self.last_rows = 0
        self.n_frames = 0
        #: micro-batches the IN-PROGRESS frame has dispatched so far —
        #: a live mid-frame gauge only (0 between frames); completed
        #: frames report their schedule via last_n_micro
        self.in_flight = 0

    @property
    def n_stages(self) -> int:
        return len(self.stages)

    def probe(self, mb) -> List[float]:
        """One blocked pass: per-stage service times in ms (device
        compute + boundary transfer, measured synchronously). Warmup
        calls this after compiling; the live path never blocks."""
        import jax
        times = []
        y = mb
        for fn, params, placement, _ in self.stages:
            y = jax.device_put(y, placement)
            jax.block_until_ready(y)
            t0 = time.perf_counter()
            y = fn(params, y)
            jax.block_until_ready(y)
            times.append((time.perf_counter() - t0) * 1000.0)
        self.stage_ms = times
        self._probed = True
        return times

    def run(self, microbatches: List[Any], tracer=None, span_attrs=None
            ) -> List[Any]:
        """Dispatch every micro-batch through the stage chain; returns
        the per-micro-batch outputs (device arrays, NOT fetched — the
        caller unpads/concatenates/fetches like any async dispatch).
        Records one ``pipeline_stage`` span per stage (host dispatch
        window, ``pipeline_stage=k`` attr) under the ambient span when
        a tracer rides along."""
        import jax
        t_wall = time.perf_counter()
        windows = [[None, None] for _ in self.stages]
        ys: List[Any] = []
        self.in_flight = 0
        for mb in microbatches:
            y = mb
            for k, (fn, params, placement, _) in enumerate(self.stages):
                t0 = time.perf_counter()
                y = jax.device_put(y, placement)
                y = fn(params, y)
                t1 = time.perf_counter()
                if windows[k][0] is None:
                    windows[k][0] = t0
                windows[k][1] = t1
            ys.append(y)
            self.in_flight += 1
        self.last_n_micro = len(microbatches)
        self.last_wall_ms = (time.perf_counter() - t_wall) * 1000.0
        self.n_frames += 1
        # dispatched work is handed back to the caller here; the live
        # gauge returns to idle
        self.in_flight = 0
        if tracer is not None:
            # one child span per stage under the ambient (batch-
            # representative) span: the host-side dispatch window with
            # pipeline_stage=k — a captured slow dispatch says which
            # stage backed up. Probe-measured service times live in
            # report(); these windows are dispatch evidence, not
            # compute times (dispatch is async).
            from mmlspark_tpu.core.tracing import current_span
            parent = current_span()
            if parent is not None:
                for k, (w0, w1) in enumerate(windows):
                    if w0 is not None:
                        tracer.add("pipeline_stage", w0, w1,
                                   parent=parent, pipeline_stage=k,
                                   devices=",".join(self.stages[k][3]),
                                   **(span_attrs or {}))
        return ys

    def report(self) -> Dict[str, Any]:
        """The ``/stats`` "pipeline" block."""
        m = self.last_n_micro or self.microbatches
        return {
            "n_stages": self.n_stages,
            "microbatches": self.microbatches,
            "last_n_micro": self.last_n_micro,
            "in_flight_micro_batches": self.in_flight,
            "stages": [{
                "stage": k,
                "devices": list(devs),
                "service_ms": round(self.stage_ms[k], 3),
            } for k, (_, _, _, devs) in enumerate(self.stages)],
            "stage_probe_valid": self._probed,
            "bubble_ratio": round(bubble_ratio(self.stage_ms, m), 4)
            if self._probed else None,
            "last_wall_ms": round(self.last_wall_ms, 3),
            "n_frames": self.n_frames,
        }
