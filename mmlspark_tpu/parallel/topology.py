"""Device mesh topology and multi-host initialization.

This is the framework's single communication story, replacing every
coordination mechanism in the reference: the Spark-driver ServerSocket
rendezvous + LightGBM TCP allreduce mesh (`LightGBMUtils.scala:97-142`,
`TrainUtils.scala:217-267`), the `mpirun --hostfile` ring for CNTK
(`CommandBuilders.scala:102-128`), and Spark broadcast. Within a slice,
XLA collectives ride ICI; across hosts, the JAX distributed runtime
coordinates over DCN.

Axis conventions (reserved from day one so TP/PP/SP/EP are addable without
API change — SURVEY.md §7 "hard parts"):

- ``data``   — batch/data parallelism (the reference's only strategy)
- ``model``  — tensor parallelism
- ``seq``    — sequence/context parallelism (ring attention)
- ``expert`` — expert parallelism
- ``pipe``   — pipeline parallelism
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_PIPE = "pipe"

ALL_AXES = (AXIS_DATA, AXIS_MODEL, AXIS_SEQ, AXIS_EXPERT, AXIS_PIPE)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape over named axes; -1 on one axis means 'the rest'."""

    axes: Tuple[Tuple[str, int], ...] = ((AXIS_DATA, -1),)

    @staticmethod
    def data_parallel() -> "MeshSpec":
        return MeshSpec(((AXIS_DATA, -1),))

    @staticmethod
    def from_dict(shape: Dict[str, int]) -> "MeshSpec":
        return MeshSpec(tuple(shape.items()))

    @staticmethod
    def full_spmd(n_devices: int) -> "MeshSpec":
        """All five axes over ``n_devices``: factors of 2 are handed to
        ``model``, ``pipe``, ``seq``, ``expert`` in that order; the
        remainder becomes ``data``. Every axis is always present so the
        complete tp/pp/sp/ep/dp code path compiles and runs at any
        device count (size-1 axes degenerate gracefully)."""
        sizes = {AXIS_DATA: 1, AXIS_SEQ: 1, AXIS_MODEL: 1,
                 AXIS_EXPERT: 1, AXIS_PIPE: 1}
        rest = n_devices
        for axis in (AXIS_MODEL, AXIS_PIPE, AXIS_SEQ, AXIS_EXPERT):
            if rest % 2 == 0 and rest > 1:
                sizes[axis] = 2
                rest //= 2
        sizes[AXIS_DATA] = rest
        return MeshSpec.from_dict(sizes)

    def resolve(self, n_devices: int) -> Dict[str, int]:
        """Concrete per-axis sizes for a device count."""
        sizes = dict(self.axes)
        wildcards = [a for a, s in sizes.items() if s == -1]
        if len(wildcards) > 1:
            raise ValueError("at most one axis may be -1")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcards:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[wildcards[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(a for a, _ in self.axes)


def local_device_count() -> int:
    import jax
    return len(jax.devices())


def use_cpu_devices(n: int = 8) -> None:
    """Switch this process to ``n`` virtual CPU devices (test/dev mode).

    Must run before any jax backend is initialized (first device touch),
    but works even if jax was already *imported* — e.g. by an image
    sitecustomize that pins a TPU platform — because backends init lazily.
    This is how the distributed code paths run unchanged from laptop to pod.
    """
    import jax
    os.environ["XLA_FLAGS"] = bump_host_device_count(
        os.environ.get("XLA_FLAGS", ""), n)
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")


def bump_host_device_count(flags: str, n: int) -> str:
    """Return ``flags`` with ``xla_force_host_platform_device_count >= n``.

    A missing count is appended; a smaller one is raised; a larger one is
    preserved (a caller prepping a bigger mesh keeps it).
    """
    import re
    m = re.search(r"xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        return (flags + f" --xla_force_host_platform_device_count={n}").strip()
    if int(m.group(1)) < n:
        return re.sub(r"xla_force_host_platform_device_count=\d+",
                      f"xla_force_host_platform_device_count={n}", flags)
    return flags


_scope_state = threading.local()


@contextlib.contextmanager
def single_device_scope():
    """Context manager confining framework stages to one device.

    Inside the scope, :func:`in_single_device_scope` is True and
    framework stages (GBDT stages, NNLearner, NNModel scoring) skip
    building multi-device mesh shardings — their device work stays on
    the thread's default device. Used by
    ``TuneHyperparameters(trial_devices=True)`` so concurrently
    dispatched trials can't interleave full-mesh collectives across
    threads (which deadlocks on real chips). The flag is thread-local:
    other threads keep their sharded behavior.
    """
    prev = getattr(_scope_state, "single", False)
    _scope_state.single = True
    try:
        yield
    finally:
        _scope_state.single = prev


def in_single_device_scope() -> bool:
    return getattr(_scope_state, "single", False)


def build_mesh(spec: Optional[MeshSpec] = None, devices=None):
    """Build a ``jax.sharding.Mesh`` over the given (default: all) devices.

    A fully fixed spec smaller than the host's device count takes the
    leading subset (``{"data": 1}`` on an 8-device host is a 1-device
    mesh, not an error) — what lets one process build the 1/2/4/8-
    device meshes of a scaling curve, or pin a small fit while the
    rest of the chips serve."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    spec = spec or MeshSpec.data_parallel()
    devices = list(devices) if devices is not None else list(jax.devices())
    fixed = [s for _, s in spec.axes if s != -1]
    if len(fixed) == len(spec.axes):
        need = math.prod(fixed)
        if 0 < need < len(devices):
            if jax.process_count() > 1:
                # a leading subset of the GLOBAL device list can leave
                # a process with a mesh containing none of its local
                # devices — collectives then fail obscurely or hang;
                # multi-process meshes must name every device
                raise ValueError(
                    f"mesh {dict(spec.axes)} needs {need} devices but "
                    f"the multi-process runtime has {len(devices)}: "
                    f"subsetting is single-process only — size the "
                    f"mesh to the pod (or use -1 for one axis)")
            from mmlspark_tpu.core.logs import get_logger
            get_logger("parallel.topology").info(
                "mesh %s uses the leading %d of %d devices",
                dict(spec.axes), need, len(devices))
            devices = devices[:need]
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in spec.axis_names)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, spec.axis_names)


def distributed_init(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Join the multi-host JAX distributed runtime (DCN rendezvous).

    The one-call replacement for the reference's entire driver-socket
    rendezvous + ssh/scp/MPI machinery. No-ops when single-process (env
    unset), so the same program runs unchanged from laptop to pod.

    On a CPU backend this also selects the **gloo** TCP collectives
    implementation (when this jax ships it): XLA:CPU's default refuses
    multi-process computations outright ("Multiprocess computations
    aren't implemented on the CPU backend"), so without gloo a CPU
    "multi-host" run could rendezvous but never execute a
    cross-process psum — the gap that kept the 2-process DCN drill
    simulated. Gloo rides the same coordinator the rendezvous uses; on
    TPU the flag is irrelevant (collectives ride ICI/DCN natively).
    Must run before the backend initializes, like ``use_cpu_devices``.
    """
    import jax
    addr = coordinator_address or os.environ.get("MMLSPARK_TPU_COORDINATOR")
    if addr is None and num_processes is None:
        return  # single-process
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu" \
            or jax.config.jax_platforms == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            from mmlspark_tpu.core.logs import get_logger
            get_logger("parallel.topology").warning(
                "this jax has no gloo CPU collectives: cross-process "
                "computations will fail on the CPU backend")
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=num_processes,
                               process_id=process_id)
