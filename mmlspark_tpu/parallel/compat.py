"""jax version compatibility for the mesh-parallel layer.

The SPMD programs target the VMA-era API (``jax.shard_map`` with
``check_vma``). On a jax that predates it (<= 0.4.x) the same
functionality lives at ``jax.experimental.shard_map.shard_map`` with
the ``check_rep`` flag — semantically the predecessor of ``check_vma``
(replication checking is what makes the transpose insert the
cross-shard psums for replicated-parameter gradients; ``False``
likewise matches the interpret-mode escape hatch both eras need).
:func:`install` bridges the gap by publishing a ``jax.shard_map``
wrapper, so every call site — library and tests — speaks one API and
the whole parallel layer runs unchanged across jax versions.

Imported (and installed) by :mod:`mmlspark_tpu.parallel` package init,
i.e. before any mesh program can be built.
"""

from __future__ import annotations

#: names of the shims :func:`install` actually installed on this jax —
#: the honest record of what is bridged vs native. ``"shard_map"`` in
#: here means this jax predates the VMA type system (check_rep era).
SHIMMED: set = set()


def vma_native() -> bool:
    """True when this jax carries the VMA-era ``jax.shard_map``
    natively (varying-manual-axes types; ``check_vma``). On a pre-VMA
    jax the manual 5-axis shard_map trainer cannot build (check_rep
    cannot infer its replicated-grad psums), so
    ``build_spmd_train_step`` re-expresses itself as pjit instead —
    the selection this predicate drives."""
    return "shard_map" not in SHIMMED


def install() -> bool:
    """Publish ``jax.shard_map`` / ``jax.lax.axis_size`` on jaxes that
    predate them. Returns True when any shim was installed (False:
    native support exists)."""
    import jax

    if not hasattr(jax.lax, "axis_size"):
        import jax.core as _core

        def axis_size(axis_name):
            """Static size of a named mesh axis (compat: the VMA-era
            ``jax.lax.axis_size``; ``jax.core.axis_frame`` returns the
            bound size as a plain int on this jax)."""
            if isinstance(axis_name, (tuple, list)):
                n = 1
                for a in axis_name:
                    n *= int(_core.axis_frame(a))
                return n
            return int(_core.axis_frame(axis_name))

        jax.lax.axis_size = axis_size
        SHIMMED.add("axis_size")

    if not hasattr(jax, "typeof"):
        class _AvalView:
            """``jax.typeof`` stand-in: delegates to the abstract value
            and reports an empty varying-manual-axes set — the pre-VMA
            type system tracks replication via ``check_rep`` instead,
            so nothing is ever vma-typed."""
            __slots__ = ("_aval",)
            vma = frozenset()

            def __init__(self, aval):
                self._aval = aval

            def __getattr__(self, name):
                return getattr(self._aval, name)

        def typeof(x):
            return _AvalView(jax.core.get_aval(x))

        jax.typeof = typeof
        SHIMMED.add("typeof")

    import inspect as _inspect
    if "vma" not in _inspect.signature(
            jax.ShapeDtypeStruct.__init__).parameters:
        _SDS = jax.ShapeDtypeStruct

        class ShapeDtypeStruct(_SDS):  # noqa: N801 — drop-in stand-in
            """Accepts (and drops) the VMA-era ``vma=`` kwarg: pre-VMA
            avals carry no varying-axes set, so the annotation is
            meaningless here and the kernels' out_shape declarations
            keep working unchanged."""

            def __init__(self, shape, dtype, *args, vma=None, **kwargs):
                super().__init__(shape, dtype, *args, **kwargs)

        jax.ShapeDtypeStruct = ShapeDtypeStruct
        SHIMMED.add("ShapeDtypeStruct")

    if not hasattr(jax.lax, "pcast"):
        # with check_rep replication tracking there is no varying/
        # replicated *type* to cast between: the rewrite machinery
        # inserts pbroadcasts itself, so pcast is the identity
        def pcast(x, axes=None, *, to=None):
            return x

        jax.lax.pcast = pcast
        SHIMMED.add("pcast")

    if hasattr(jax, "shard_map"):
        return False
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma: bool = True, **kwargs):
        check_rep = kwargs.pop("check_rep", check_vma)
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_rep,
                              **kwargs)

    shard_map.__doc__ = (_exp_shard_map.__doc__ or "") + (
        "\n\n(compat wrapper: check_vma maps to check_rep — "
        "mmlspark_tpu.parallel.compat)")
    jax.shard_map = shard_map
    SHIMMED.add("shard_map")
    return True


INSTALLED = install()
