"""Thin, named wrappers over XLA collectives for use inside shard_map/pjit.

One coherent backend (parity inventory: SURVEY.md §2.9) replacing LightGBM's
TCP allreduce, CNTK's MPI ring, and Spark broadcast: psum/all_gather/
ppermute/reduce_scatter over ICI, DCN across slices — all inserted by XLA
from sharding annotations or called explicitly inside ``shard_map``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax


def allreduce_sum(x, axis: str = "data"):
    """Sum across an axis (LightGBM histogram-merge / MPI allreduce parity)."""
    return lax.psum(x, axis_name=axis)


def allreduce_mean(x, axis: str = "data"):
    return lax.pmean(x, axis_name=axis)


def allgather(x, axis: str = "data", tiled: bool = False):
    return lax.all_gather(x, axis_name=axis, tiled=tiled)


def reduce_scatter(x, axis: str = "data", scatter_dimension: int = 0):
    return lax.psum_scatter(x, axis_name=axis,
                            scatter_dimension=scatter_dimension, tiled=True)


def ring_permute(x, axis: str, shift: int = 1):
    """Send shard to the next device on a ring (ring-attention building block)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


def shard_map_fn(fn, mesh, in_specs, out_specs, check_vma: bool = True):
    """Wrap ``jax.shard_map`` with this framework's mesh conventions.

    VMA (varying-manual-axes) checking stays on by default: it is what
    makes autodiff through manual collectives type-correct
    (psum/ppermute transposes) — see models/transformer.py. Pass
    ``check_vma=False`` only for forward-only programs whose replicated
    outputs the type system cannot infer (e.g. returning an
    ``all_gather`` result with a replicated out_spec).
    """
    import jax
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=check_vma)
