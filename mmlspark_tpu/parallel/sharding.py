"""Sharding helpers: place columnar batches onto the mesh.

The TPU-native replacement for the reference's broadcast-model /
partitioned-data idiom: model params are replicated (or model-sharded)
in HBM once, batches are sharded over the ``data`` axis, and XLA inserts
the collectives.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def named_sharding(mesh, *axis_for_dim: Optional[str]):
    """NamedSharding placing dim i on mesh axis ``axis_for_dim[i]`` (None = replicate)."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(*axis_for_dim))


def batch_sharding(mesh, axis: str = "data"):
    """Shard the leading (batch) dimension over one mesh axis."""
    return named_sharding(mesh, axis)


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())


def _pad_axis(arr: np.ndarray, extra: int, axis: int, pad_value,
              pad_mode: str) -> np.ndarray:
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, extra)
    if pad_mode == "edge" and arr.shape[axis] > 0:
        # repeat the last row: stays valid for object/string columns and
        # for models that choke on all-zero rows (serving pad policy)
        return np.pad(arr, widths, mode="edge")
    return np.pad(arr, widths, constant_values=pad_value)


def pad_to_multiple(arr: np.ndarray, multiple: int,
                    axis: int = 0, pad_value=0,
                    pad_mode: str = "constant") -> Tuple[np.ndarray, int]:
    """Pad ``axis`` up to a multiple (XLA needs static, divisible shapes).

    Returns (padded, original_length). The padding strategy for ragged
    batch tails — chosen once here, used by every engine (SURVEY.md §7
    "dynamic shapes vs XLA" risk). ``pad_mode="edge"`` repeats the last
    row instead of writing ``pad_value`` (valid for any dtype, including
    object columns).
    """
    n = arr.shape[axis]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arr, n
    return _pad_axis(arr, target - n, axis, pad_value, pad_mode), n


def pad_to_bucket(arr: np.ndarray, cap: int = 1024,
                  axis: int = 0, pad_value=0,
                  pad_mode: str = "constant",
                  multiple: int = 1) -> Tuple[np.ndarray, int]:
    """Pad ``axis`` to a bounded shape bucket for jit shape-cache reuse.

    Small inputs round up to the next power of two, clamped at ``cap``
    (few distinct compiled shapes for serving micro-batches of assorted
    sizes, and never a dispatch larger than the operator's ceiling);
    inputs past ``cap`` pad to a multiple of ``cap`` instead, bounding
    the waste for large offline batches at ``cap - 1`` rows.
    ``multiple`` rounds every bucket up to a divisibility constraint
    (the mesh's data-axis size for TP/data-sharded dispatch), so a
    bucketed batch placed by ``dist.put_batch`` never re-pads.
    """
    n = arr.shape[axis]
    if n > cap:
        return pad_to_multiple(arr, _lcm(cap, multiple), axis=axis,
                               pad_value=pad_value, pad_mode=pad_mode)
    if n == 0:  # empty inputs still bucket to one row (a real jit shape)
        return _pad_axis(arr, max(int(multiple), 1), axis, pad_value,
                         "constant"), 0
    return pad_to_multiple(arr, bucket_target(n, cap, multiple=multiple),
                           axis=axis, pad_value=pad_value,
                           pad_mode=pad_mode)


def _lcm(a: int, b: int) -> int:
    import math
    a, b = max(int(a), 1), max(int(b), 1)
    return a * b // math.gcd(a, b)


def _effective_cap(cap: int, multiple: int) -> int:
    """The cap a divisibility-constrained ladder really serves: the
    operator ceiling rounded DOWN to the multiple (the ceiling is a
    budget — overshooting it to satisfy divisibility would be a memory
    lie), except a multiple larger than the cap IS the floor (there is
    no smaller dispatchable shape)."""
    cap, multiple = int(cap), max(int(multiple), 1)
    if multiple <= 1 or cap <= multiple:
        return max(cap, multiple) if multiple > 1 else cap
    return (cap // multiple) * multiple


def round_to_multiple(n: int, multiple: int, up: bool = True) -> int:
    """Round ``n`` to a multiple (up by default; ``up=False`` rounds
    down but never below ``multiple``). The one divisibility helper
    behind the TP-aware bucket ladder and NNModel's minibatch sizing —
    every layer that must honor a mesh data-axis constraint rounds the
    same way."""
    multiple = max(int(multiple), 1)
    n = int(n)
    if up:
        return ((max(n, 1) + multiple - 1) // multiple) * multiple
    return max((n // multiple) * multiple, multiple)


def bucket_target(n: int, cap: int = 1024, multiple: int = 1) -> int:
    """The bucket a batch of ``n`` rows pads to: next power of two,
    clamped at ``cap`` (a batch within the cap never pads past it —
    ``cap`` is an operator ceiling, e.g. a serving memory budget); above
    ``cap``, the next multiple of ``cap``. With ``multiple`` > 1 every
    bucket is additionally rounded up to that multiple (TP/data-sharded
    dispatch: the mesh's data axis must divide every placed batch, so
    rounding HERE — once, at assemble time — means ``dist.put_batch``
    never pads again). The ``cap`` stays an operator CEILING: with a
    multiple that does not divide it, the effective cap is ``cap``
    rounded DOWN to the multiple (a 100-row budget over 8 shards tops
    out at 96 — never a dispatch past the budget; when the multiple
    itself exceeds the cap it wins, as the smallest dispatchable
    shape). The single bucket policy behind :func:`pad_to_bucket`,
    serving's shape-bucketed data plane, and
    :class:`mmlspark_tpu.stages.batching.BucketBatcher` — one ladder,
    so every layer warms the same compiled shapes."""
    multiple = max(int(multiple), 1)
    cap = _effective_cap(cap, multiple)
    if n <= 0:
        return multiple
    if n > cap:
        return round_to_multiple(n, _lcm(cap, multiple))
    target = 1
    while target < n:
        target *= 2
    return min(round_to_multiple(min(target, cap), multiple), cap)


def bucket_ladder(cap: int, multiple: int = 1) -> List[int]:
    """Every bucket :func:`bucket_target` can return for ``n`` in
    ``[1, cap]``: the powers of two below ``cap`` plus ``cap`` itself,
    each rounded up to ``multiple`` (deduplicated — small pow2 buckets
    collapse onto the multiple). Derived directly — O(log cap) —
    instead of scanning every ``n`` (the ``sorted({bucket_target(n,
    cap) for n in range(1, cap+1)})`` idiom costs O(cap) set churn per
    caller init, which decoder/server construction paid at every
    ``max_len``/``max_batch_size``)."""
    cap = _effective_cap(cap, multiple)
    multiple = max(int(multiple), 1)
    if cap <= 1:
        return [bucket_target(1, cap, multiple=multiple)]
    ladder = []
    b = 1
    while b < cap:
        t = round_to_multiple(b, multiple)
        if not ladder or ladder[-1] != t:
            ladder.append(t)
        b *= 2
    if not ladder or ladder[-1] != cap:
        ladder.append(cap)
    return ladder


def padded_device_batch(chunk: np.ndarray, size: int, placement=None,
                        put=None, bucket: bool = False, axis: int = 0,
                        pad_value=0, pad_mode: str = "constant",
                        multiple: int = 1,
                        ) -> Tuple[Any, int]:
    """Pad a batch to its static shape and (optionally) place it on device.

    The one helper behind every ragged-tail call site: NNModel's scoring
    minibatches and its empty-input width probe (``size`` = the static
    minibatch), and the serving data plane's shape buckets
    (``bucket=True``, ``size`` = the bucket cap). Returns
    ``(padded, original_length)``; when ``placement`` is given the padded
    array is uploaded via ``put`` (default :func:`jax.device_put`).
    """
    if bucket:
        padded, n = pad_to_bucket(chunk, cap=size, axis=axis,
                                  pad_value=pad_value, pad_mode=pad_mode,
                                  multiple=multiple)
    else:
        padded, n = pad_to_multiple(chunk, size, axis=axis,
                                    pad_value=pad_value, pad_mode=pad_mode)
    if placement is not None:
        if put is None:
            import jax
            put = jax.device_put
        padded = put(padded, placement)
    return padded, n


def unpad(arr, n: int, axis: int = 0):
    """Slice padding back off (host- or device-side)."""
    index = [slice(None)] * arr.ndim
    index[axis] = slice(0, n)
    return arr[tuple(index)]


def shard_batch(batch: Dict[str, np.ndarray], mesh, axis: str = "data",
                pad_value=0) -> Tuple[Dict[str, Any], int]:
    """Device-put a dict of host arrays sharded over the batch axis.

    Pads every array's leading dim to a multiple of the axis size; returns
    the device pytree and the true row count for unpadding results.
    """
    import jax
    per_axis = mesh.shape[axis]
    sharding = batch_sharding(mesh, axis)
    out = {}
    n_true = None
    for name, arr in batch.items():
        arr = np.asarray(arr)
        padded, n = pad_to_multiple(arr, per_axis, pad_value=pad_value)
        if n_true is None:
            n_true = n
        out[name] = jax.device_put(padded, sharding)
    return out, int(n_true or 0)
