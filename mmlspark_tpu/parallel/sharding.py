"""Sharding helpers: place columnar batches onto the mesh.

The TPU-native replacement for the reference's broadcast-model /
partitioned-data idiom: model params are replicated (or model-sharded)
in HBM once, batches are sharded over the ``data`` axis, and XLA inserts
the collectives.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np


def named_sharding(mesh, *axis_for_dim: Optional[str]):
    """NamedSharding placing dim i on mesh axis ``axis_for_dim[i]`` (None = replicate)."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(*axis_for_dim))


def batch_sharding(mesh, axis: str = "data"):
    """Shard the leading (batch) dimension over one mesh axis."""
    return named_sharding(mesh, axis)


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())


def pad_to_multiple(arr: np.ndarray, multiple: int,
                    axis: int = 0, pad_value=0) -> Tuple[np.ndarray, int]:
    """Pad ``axis`` up to a multiple (XLA needs static, divisible shapes).

    Returns (padded, original_length). The padding strategy for ragged
    batch tails — chosen once here, used by every engine (SURVEY.md §7
    "dynamic shapes vs XLA" risk).
    """
    n = arr.shape[axis]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arr, n
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - n)
    return np.pad(arr, widths, constant_values=pad_value), n


def pad_to_bucket(arr: np.ndarray, cap: int = 1024,
                  axis: int = 0, pad_value=0) -> Tuple[np.ndarray, int]:
    """Pad ``axis`` to a bounded shape bucket for jit shape-cache reuse.

    Small inputs round up to the next power of two (few distinct compiled
    shapes for serving micro-batches of assorted sizes); inputs past
    ``cap`` pad to a multiple of ``cap`` instead, bounding the waste for
    large offline batches at ``cap - 1`` rows.
    """
    n = arr.shape[axis]
    if n > cap:
        return pad_to_multiple(arr, cap, axis=axis, pad_value=pad_value)
    target = 1
    while target < n:
        target *= 2
    if n == 0:  # empty inputs still bucket to one row (a real jit shape)
        widths = [(0, 0)] * arr.ndim
        widths[axis] = (0, 1)
        return np.pad(arr, widths, constant_values=pad_value), 0
    return pad_to_multiple(arr, target, axis=axis, pad_value=pad_value)


def unpad(arr, n: int, axis: int = 0):
    """Slice padding back off (host- or device-side)."""
    index = [slice(None)] * arr.ndim
    index[axis] = slice(0, n)
    return arr[tuple(index)]


def shard_batch(batch: Dict[str, np.ndarray], mesh, axis: str = "data",
                pad_value=0) -> Tuple[Dict[str, Any], int]:
    """Device-put a dict of host arrays sharded over the batch axis.

    Pads every array's leading dim to a multiple of the axis size; returns
    the device pytree and the true row count for unpadding results.
    """
    import jax
    per_axis = mesh.shape[axis]
    sharding = batch_sharding(mesh, axis)
    out = {}
    n_true = None
    for name, arr in batch.items():
        arr = np.asarray(arr)
        padded, n = pad_to_multiple(arr, per_axis, pad_value=pad_value)
        if n_true is None:
            n_true = n
        out[name] = jax.device_put(padded, sharding)
    return out, int(n_true or 0)
