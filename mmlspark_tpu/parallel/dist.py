"""The distributed execution layer: pjit/NamedSharding state placement.

`topology` builds the mesh and `collectives`/`transformer` own the
manual shard_map programs; what was missing is the layer that makes the
mesh *load-bearing* for the everyday trainer and the serving plane —
GSPMD (pjit) sharding of whole train/serve states, where XLA inserts
the collectives from ``NamedSharding`` annotations and the same code
runs at any device count. This module is that layer:

* **sharding rules** — :func:`spec_for_leaf` is one *shape-driven*
  rule (shard the largest mesh-divisible dim over ``model``, replicate
  the rest), applied uniformly to params AND optimizer state
  (:func:`state_shardings`): optimizer moments mirror their param's
  layout because the rule sees the same shape, never because a
  per-leaf table was kept in sync by hand.
* **batch-spec plumbing** — :func:`put_batch` pads the leading axis to
  the data-axis multiple and places host arrays as ``data``-sharded
  global arrays; on a multi-process runtime it builds them from
  process-local shards (per-host input pipelines: each host feeds only
  its slice, no host ever materializes the global batch).
* **placement visibility** — :func:`placement_report` summarizes how a
  state tree actually landed on the mesh (axis sizes, per-device
  bytes, sharded vs replicated leaf counts): what ``/stats`` and
  dispatch spans surface so an operator can see tensor parallelism,
  not infer it.

Training uses it through ``NNLearner(mesh_shape={"data": d, "model":
t})``; serving through ``NNModel(tensor_parallel=t)`` and
``TransformerDecoder(mesh=...)``. The sharded-checkpoint store
(:mod:`mmlspark_tpu.io.checkpoint`) writes these trees per-shard and
restores them onto *any* mesh, so a topology change between save and
restore is a placement decision, not a data migration.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from mmlspark_tpu.parallel.topology import (
    AXIS_DATA, AXIS_MODEL, MeshSpec, build_mesh,
)

__all__ = [
    "train_mesh", "spec_for_leaf", "state_specs", "state_shardings",
    "shard_state", "batch_shardings", "put_batch", "placement_report",
    "placement_label", "process_local_rows", "tree_bytes",
]


def train_mesh(mesh_shape: Optional[Dict[str, int]] = None, devices=None):
    """Build the trainer/serving GSPMD mesh: ``data`` × ``model``.

    ``mesh_shape`` may name any axes (``{"data": -1}`` default); a
    ``model`` axis turns tensor parallelism on. One ``-1`` axis takes
    the remaining devices (MeshSpec semantics)."""
    spec = (MeshSpec.from_dict(mesh_shape) if mesh_shape
            else MeshSpec.data_parallel())
    return build_mesh(spec, devices=devices)


def spec_for_leaf(shape: Tuple[int, ...], mesh,
                  model_axis: str = AXIS_MODEL):
    """The one sharding rule, driven by *shape alone*.

    Rank >= 2 leaves shard their largest ``model``-divisible dim over
    the ``model`` axis (ties prefer the trailing dim — the Megatron
    column split for the dominant ``[d_in, d_out]`` kernels); scalars,
    vectors, and undivisible leaves replicate. Because the rule never
    looks at *which* leaf it is, an optimizer moment of the same shape
    as its param always lands with the identical layout, and a shape
    that appears in both a checkpoint and a freshly initialized state
    resolves to the same placement on any mesh.
    """
    from jax.sharding import PartitionSpec as P

    n_model = mesh.shape.get(model_axis, 1) if mesh is not None else 1
    if n_model <= 1 or len(shape) < 2:
        return P()
    best_dim, best_size = None, 0
    for d in range(len(shape) - 1, -1, -1):   # trailing dim wins ties
        if shape[d] % n_model == 0 and shape[d] > best_size \
                and shape[d] >= 2 * n_model:
            best_dim, best_size = d, shape[d]
    if best_dim is None:
        return P()
    axes: list = [None] * len(shape)
    axes[best_dim] = model_axis
    return P(*axes)


def state_specs(tree, mesh, model_axis: str = AXIS_MODEL):
    """PartitionSpec tree for any state pytree (params, optimizer
    moments, velocity): :func:`spec_for_leaf` applied per leaf."""
    import jax
    return jax.tree.map(
        lambda leaf: spec_for_leaf(np.shape(leaf), mesh, model_axis),
        tree)


def state_shardings(tree, mesh, model_axis: str = AXIS_MODEL):
    """NamedSharding tree for a state pytree on ``mesh``."""
    import jax
    from jax.sharding import NamedSharding
    return jax.tree.map(
        lambda leaf: NamedSharding(
            mesh, spec_for_leaf(np.shape(leaf), mesh, model_axis)),
        tree)


def shard_state(tree, mesh, model_axis: str = AXIS_MODEL):
    """Device-put a host state tree with the canonical rule's layout."""
    import jax
    return jax.device_put(tree, state_shardings(tree, mesh, model_axis))


# ---------------------------------------------------------------------------
# batch plumbing


def batch_shardings(mesh, axis: str = AXIS_DATA):
    """The global-batch sharding: leading dim over ``data``, everything
    else replicated (model-axis devices all see the full feature dims).
    Delegates to the one existing helper — two spellings, one rule."""
    from mmlspark_tpu.parallel.sharding import batch_sharding
    return batch_sharding(mesh, axis)


def process_local_rows(n_global: int, mesh, axis: str = AXIS_DATA
                       ) -> Tuple[int, int]:
    """``(start, stop)`` of this process's row slice of a global batch
    sharded over ``axis`` — the per-host input-pipeline contract: each
    host loads only rows ``[start, stop)``. Single-process returns the
    full range."""
    import jax
    n_proc = jax.process_count()
    if n_proc <= 1:
        return 0, n_global
    if n_global % n_proc:
        raise ValueError(
            f"global batch {n_global} not divisible by process count "
            f"{n_proc}")
    per = n_global // n_proc
    pid = jax.process_index()
    return pid * per, (pid + 1) * per


def _pad_cached(cache: dict, name: str, arr: np.ndarray, multiple: int,
                pad_value) -> Tuple[np.ndarray, int]:
    """Pad ``arr``'s leading dim via a REUSED host staging buffer.

    A ragged tail (rows not divisible by the multiple) normally
    allocates a fresh padded array per call; here the padded buffer is
    allocated ONCE per (name, target-shape, dtype) — its pad rows are
    written at allocation and never again — and subsequent tails of
    the same shape just copy their real rows in. The pipeline driver's
    steady-state contract: the tail micro-batch of every frame reuses
    one buffer instead of re-allocating per micro-batch. Divisible
    batches pass through untouched (no copy at all)."""
    n = arr.shape[0]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arr, n
    key = (name, target) + arr.shape[1:] + (arr.dtype.str,)
    buf = cache.get(key)
    if buf is None:
        buf = np.full((target,) + arr.shape[1:], pad_value,
                      dtype=arr.dtype)
        cache[key] = buf
        cache[(key, "dirty_to")] = 0
    # a SMALLER tail reusing a buffer last filled by a LARGER one must
    # re-clean the rows the larger fill dirtied ([n, dirty_to)), or
    # the previous batch's data (e.g. nonzero sample weights) silently
    # rides into this dispatch; an empty slice when dirty_to <= n
    buf[:n] = arr
    buf[n:cache[(key, "dirty_to")]] = pad_value
    cache[(key, "dirty_to")] = n
    return buf, n


def put_batch(arrays: Dict[str, np.ndarray], mesh,
              axis: str = AXIS_DATA, pad_value=0,
              pad_cache: Optional[dict] = None
              ) -> Tuple[Dict[str, Any], int]:
    """Place a dict of host arrays as ``data``-sharded global arrays.

    Pads every leading dim to the data-axis multiple and returns
    ``(device_tree, true_row_count)``. Single-process placement is one
    ``device_put`` per array; on a multi-process runtime the host
    arrays are taken as *process-local* rows and assembled into global
    arrays (``jax.make_array_from_process_local_data``) — the per-host
    input-sharding path, where no host ever holds the global batch.

    ``pad_cache`` (any dict the caller keeps alive) opts into reused
    host staging buffers for ragged tails: a final micro-batch smaller
    than the data-axis multiple then never re-allocates its padded
    array (see :func:`_pad_cached`) — the pipeline driver and the
    trainer's steady-state loops pass one. The buffers are host-side
    staging only: ``device_put`` copies out of them, so reuse on the
    next call is safe.
    """
    import jax
    from mmlspark_tpu.parallel.sharding import pad_to_multiple

    n_data = mesh.shape.get(axis, 1)
    sharding = batch_shardings(mesh, axis)
    n_proc = jax.process_count()
    multi = n_proc > 1
    # multi-process arrays are PROCESS-LOCAL rows: each host pads to
    # its per-process share of the data axis (padding to the global
    # multiple here would inflate the assembled batch n_proc-fold and
    # retrace the step); single-process pads to the full axis
    if multi and n_data % n_proc:
        raise ValueError(
            f"data axis ({n_data}) not divisible by process count "
            f"({n_proc})")
    multiple = n_data // n_proc if multi else n_data
    out: Dict[str, Any] = {}
    n_true: Optional[int] = None
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        if pad_cache is not None:
            padded, n = _pad_cached(pad_cache, name, arr, multiple,
                                    pad_value)
        else:
            padded, n = pad_to_multiple(arr, multiple, pad_value=pad_value)
        if n_true is None:
            n_true = n
        if multi:
            out[name] = jax.make_array_from_process_local_data(
                sharding, padded)
        else:
            out[name] = jax.device_put(padded, sharding)
    return out, int(n_true or 0)


# ---------------------------------------------------------------------------
# placement visibility


def _leaf_nbytes(leaf) -> int:
    shape = np.shape(leaf)
    dtype = getattr(leaf, "dtype", np.dtype(np.float32))
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def _actual_spec(leaf, mesh, model_axis: str):
    """The leaf's REAL PartitionSpec when it is a placed array (its
    ``.sharding.spec`` — decode params, for instance, are laid out by
    ``decode_param_specs``, not the generic rule), falling back to the
    canonical rule for host arrays that have no placement yet."""
    spec = getattr(getattr(leaf, "sharding", None), "spec", None)
    if spec is not None:
        return spec
    return spec_for_leaf(np.shape(leaf), mesh, model_axis)


def _spec_axes(spec) -> Tuple[str, ...]:
    out = []
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.extend(entry)
        else:
            out.append(entry)
    return tuple(out)


def tree_bytes(tree) -> int:
    """Total bytes of a state pytree (shape × itemsize, no device
    sync). The KV-pool HBM accounting behind ``/decode/stats`` — the
    number a paged-vs-dense comparison holds fixed."""
    import jax
    return sum(_leaf_nbytes(leaf) for leaf in jax.tree.leaves(tree))


def placement_report(tree, mesh, model_axis: str = AXIS_MODEL
                     ) -> Dict[str, Any]:
    """How a state tree lands on ``mesh``: the ``/stats`` surface.

    Reports the mesh axis sizes, device names, sharded/replicated leaf
    counts, total state bytes, and per-device bytes — from each placed
    leaf's ACTUAL sharding (host arrays fall back to the canonical
    rule). Cheap (shapes + sharding metadata, no device sync), so a
    scrape can call it live."""
    import jax
    leaves = jax.tree.leaves(tree)
    sharded = replicated = 0
    total = per_device = 0
    for leaf in leaves:
        nbytes = _leaf_nbytes(leaf)
        total += nbytes
        axes = _spec_axes(_actual_spec(leaf, mesh, model_axis))
        if axes:
            sharded += 1
            factor = 1
            for a in axes:
                factor *= int(mesh.shape.get(a, 1))
            per_device += nbytes // max(factor, 1)
        else:
            replicated += 1
            per_device += nbytes
    return {
        "mesh": {a: int(s) for a, s in mesh.shape.items()},
        "n_devices": int(mesh.devices.size),
        "devices": [str(d) for d in mesh.devices.flat],
        "sharded_leaves": sharded,
        "replicated_leaves": replicated,
        "state_bytes": total,
        "state_bytes_per_device": per_device,
    }


def placement_label(mesh) -> str:
    """Compact span-attribute form: ``"data=4,model=2"``."""
    return ",".join(f"{a}={int(s)}" for a, s in mesh.shape.items())
