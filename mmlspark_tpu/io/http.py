"""HTTP-on-columns: requests/responses as first-class DataFrame columns.

Capability parity with the reference's HTTP-on-Spark core
(`io/http/src/main/scala/HTTPSchema.scala:25-230`, `HTTPTransformer.scala:78`,
`Clients.scala:66,91,102`, `HTTPClients.scala:55,107-133`,
`SimpleHTTPTransformer.scala:61`, `Parsers.scala`): a request column is sent
row-by-row with bounded async concurrency, responses land in a response
column, and parser stages map domain rows to requests / responses to rows.

Host-side by design: HTTP IO never touches the device; its role in the TPU
framework is feeding batched rows into jitted inference (see
:mod:`mmlspark_tpu.serving`).
"""

from __future__ import annotations

import json
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional
from urllib.parse import urlsplit

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, obj_col
from mmlspark_tpu.core.params import (
    Param, HasInputCol, HasOutputCol, in_range,
)
from mmlspark_tpu.core.resilience import (
    BreakerBoard, Deadline, RetryPolicy,
)
from mmlspark_tpu.core.stage import Transformer


# ---------------------------------------------------------------------------
# Request / response records (parity: HTTPSchema.scala SparkBindings)
# ---------------------------------------------------------------------------

@dataclass
class HTTPRequestData:
    """One HTTP request as plain data (parity: HTTPRequestData binding)."""

    url: str
    method: str = "GET"
    headers: Dict[str, str] = field(default_factory=dict)
    body: Optional[bytes] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"url": self.url, "method": self.method,
                "headers": dict(self.headers), "body": self.body}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "HTTPRequestData":
        body = d.get("body")
        if isinstance(body, str):
            body = body.encode()
        return HTTPRequestData(url=d["url"], method=d.get("method", "GET"),
                               headers=dict(d.get("headers") or {}),
                               body=body)

    @staticmethod
    def post_json(url: str, payload: Any,
                  headers: Optional[Dict[str, str]] = None
                  ) -> "HTTPRequestData":
        from mmlspark_tpu.core.serialize import _json_default
        h = {"Content-Type": "application/json"}
        h.update(headers or {})
        return HTTPRequestData(url=url, method="POST", headers=h,
                               body=json.dumps(payload,
                                               default=_json_default).encode())


@dataclass
class HTTPResponseData:
    """One HTTP response as plain data (parity: HTTPResponseData binding)."""

    status_code: int
    reason: str = ""
    body: Optional[bytes] = None
    headers: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"status_code": self.status_code, "reason": self.reason,
                "body": self.body, "headers": dict(self.headers)}

    @property
    def text(self) -> str:
        return (self.body or b"").decode("utf-8", errors="replace")

    def json(self) -> Any:
        return json.loads(self.text)


# ---------------------------------------------------------------------------
# Handlers: send one request with a retry policy
# (parity: HandlingUtils.basic/advanced, HTTPClients.scala:55,107-133)
# ---------------------------------------------------------------------------

def _metrics():
    """Lazily-bound global telemetry families (module-cached so the
    per-send cost is one dict lookup + a labels() cache hit)."""
    global _HTTP_METRICS
    if _HTTP_METRICS is None:
        from mmlspark_tpu.core.telemetry import BoundedLabelSet, REGISTRY
        _HTTP_METRICS = {
            "requests": REGISTRY.counter(
                "http_client_requests_total",
                "Egress HTTP sends by host and status class (transport "
                "failures land in class \"0xx\"; hosts beyond the "
                "tracked-label cap fold into host=\"other\").",
                labels=("host", "class")),
            "retries": REGISTRY.counter(
                "http_client_retries_total",
                "Egress sends re-attempted under a retry policy.",
                labels=("host",)),
            # a URL column with thousands of distinct domains must not
            # grow a long-lived worker's registry without limit
            "hosts": BoundedLabelSet(256),
        }
    return _HTTP_METRICS


_HTTP_METRICS = None


def _host_label(host: str) -> str:
    return _metrics()["hosts"].key(host)[0]


def _send_once(session, req: HTTPRequestData,
               timeout: float) -> HTTPResponseData:
    # one egress span per attempt, nested under the ambient span (a
    # served request whose model fans out HTTP shows each send in its
    # captured timeline, carrying the same injected trace id); a
    # transport failure finishes it with status=error before the
    # exception reaches the policy layer. A bound trace id WITHOUT an
    # ambient span (ServingClient's one-trace-per-failover-schedule
    # pattern) means this span is mid-trace, not a root: suppress the
    # capture decision, or a retry storm would churn the trace store
    # with one-span "http_egress" captures
    from mmlspark_tpu.core.telemetry import current_trace_id
    from mmlspark_tpu.core.tracing import (
        ambient_tracer, current_span, inject_span_context,
    )
    tracer = ambient_tracer()
    tid = current_trace_id()
    mid_trace = tid is not None and current_span() is None
    span = tracer.start("http_egress", host=_host_of(req.url),
                        method=req.method)
    headers = req.headers
    if tid:
        # distributed-trace context on the wire: the trace id PLUS this
        # attempt span's id as X-Parent-Span-Id, so an mmlspark_tpu
        # worker on the other end parents its root "request" span under
        # this exact attempt and the trees merge into one distributed
        # trace. Caller-supplied headers win (names are
        # case-insensitive on the wire — two conflicting trace headers
        # would fork downstream correlation).
        headers = inject_span_context(headers, span)
    try:
        resp = session.request(req.method, req.url, headers=headers,
                               data=req.body, timeout=timeout)
    except BaseException:
        tracer.finish(span, status="error", capture=not mid_trace)
        raise
    tracer.finish(span,
                  status="ok" if resp.status_code < 500 else "error",
                  capture=not mid_trace,
                  status_code=resp.status_code)
    return HTTPResponseData(status_code=resp.status_code,
                            reason=resp.reason, body=resp.content,
                            headers=dict(resp.headers))


def policy_handler(session, req: HTTPRequestData, timeout: float = 60.0,
                   policy: Optional[RetryPolicy] = None,
                   breaker=None, deadline: Optional[Deadline] = None
                   ) -> HTTPResponseData:
    """Send one request under a :class:`RetryPolicy`.

    The general handler the legacy fixed-list handlers now delegate to:
    transport failures (returned as status 0, same contract as before)
    and policy-retryable statuses back off per the policy (decorrelated
    jitter or explicit list, attempt + time budgets), honoring
    ``Retry-After``. An optional per-host :class:`CircuitBreaker` is
    consulted before every send — an open circuit returns immediately
    (status 0, reason ``"circuit open: ..."``) instead of burning the
    retry schedule against a dead host. An optional :class:`Deadline`
    bounds the whole exchange: it caps the per-attempt socket timeout
    and no retry is attempted that could not finish in time.
    """
    policy = policy or RetryPolicy()
    sched = policy.schedule(deadline)
    resp: Optional[HTTPResponseData] = None
    host = _host_label(_host_of(req.url))   # invariant across attempts
    while True:
        if deadline is not None and deadline.expired:
            return resp or HTTPResponseData(
                status_code=0, reason="deadline exceeded", body=None)
        if breaker is not None and not breaker.allow():
            return resp or HTTPResponseData(
                status_code=0,
                reason=f"circuit open: {breaker.name or req.url}",
                body=None)
        attempt_timeout = timeout
        if deadline is not None:
            attempt_timeout = min(timeout, max(deadline.remaining(), 1e-3))
        try:
            resp = _send_once(session, req, attempt_timeout)
        except Exception as e:  # transport-level failure
            resp = HTTPResponseData(status_code=0, reason=str(e), body=None)
        _metrics()["requests"].labels(
            host, f"{resp.status_code // 100}xx").inc()
        # breaker health tracks the HOST: transport failures and server
        # errors count against it even when the policy itself would not
        # retry that status (e.g. the basic policy returns 5xx as-is)
        if breaker is not None:
            if resp.status_code == 0 or resp.status_code >= 500:
                breaker.record_failure()
            else:
                breaker.record_success()
        if not policy.retryable_status(resp.status_code):
            return resp
        retry_after = resp.headers.get("Retry-After")
        if sched.give_up(retry_after):
            return resp
        _metrics()["retries"].labels(host).inc()


def basic_handler(session, req: HTTPRequestData, timeout: float = 60.0,
                  backoffs: List[float] = (0.1, 0.5, 1.0),
                  deadline: Optional[Deadline] = None) -> HTTPResponseData:
    """Retry only on transport errors; any status code is returned as-is."""
    return policy_handler(
        session, req, timeout,
        policy=RetryPolicy(backoffs=tuple(backoffs), retry_statuses=()),
        deadline=deadline)


def advanced_handler(session, req: HTTPRequestData, timeout: float = 60.0,
                     backoffs: List[float] = (0.1, 0.5, 1.0, 2.0),
                     retry_statuses: tuple = (429, 500, 502, 503, 504),
                     deadline: Optional[Deadline] = None
                     ) -> HTTPResponseData:
    """Also retry on throttling/server statuses with backoff.

    Parity: HandlingUtils.advanced (`HTTPClients.scala:107-133`) — 429s
    honor a Retry-After header when present.
    """
    return policy_handler(
        session, req, timeout,
        policy=RetryPolicy(backoffs=tuple(backoffs),
                           retry_statuses=tuple(retry_statuses)),
        deadline=deadline)


# ---------------------------------------------------------------------------
# Clients (parity: Clients.scala SingleThreadedClient / AsyncClient)
# ---------------------------------------------------------------------------

# per-host breakers shared by every policy-driven client in the process:
# a host that died during one stage's transform is already open when the
# next stage (or the next micro-batch) targets it
SHARED_BREAKERS = BreakerBoard(failure_threshold=5, reset_timeout=30.0)


def _host_of(url: str) -> str:
    return urlsplit(url).netloc or url


class HTTPClient:
    """Sends a list of requests, preserving order.

    ``concurrency > 1`` uses a bounded thread pool — the analogue of the
    reference's per-partition AsyncClient with bounded futures
    (`Clients.scala:102`, `AsyncUtils`).

    With ``policy`` set (or ``breakers``), sends go through
    :func:`policy_handler`: jittered/bounded retries, per-host circuit
    breaking (``breakers=True`` uses the process-wide
    :data:`SHARED_BREAKERS` board; pass a :class:`BreakerBoard` to
    isolate), and an optional per-send :class:`Deadline`. ``session``
    is injectable so chaos tests wrap it in a
    :class:`mmlspark_tpu.testing.faults.FaultySession`.
    """

    def __init__(self, concurrency: int = 1, timeout: float = 60.0,
                 handler: Callable = advanced_handler,
                 policy: Optional[RetryPolicy] = None,
                 breakers=None, session=None):
        self.concurrency = max(int(concurrency), 1)
        self.timeout = timeout
        self.handler = handler
        self.policy = policy
        if breakers is True:
            breakers = SHARED_BREAKERS
        self.breakers: Optional[BreakerBoard] = breakers or None
        import inspect
        try:
            self._handler_takes_deadline = "deadline" in \
                inspect.signature(handler).parameters
        except (TypeError, ValueError):
            self._handler_takes_deadline = False
        if session is None:
            import requests
            session = requests.Session()
        self._session = session

    def send(self, reqs: List[Optional[HTTPRequestData]],
             deadline: Optional[Deadline] = None
             ) -> List[Optional[HTTPResponseData]]:
        policy_driven = (self.policy is not None
                         or self.breakers is not None)

        def one(req):
            if req is None:
                return None
            if policy_driven:
                breaker = (self.breakers.get(_host_of(req.url))
                           if self.breakers is not None else None)
                return policy_handler(self._session, req, self.timeout,
                                      policy=self.policy, breaker=breaker,
                                      deadline=deadline)
            if deadline is not None and self._handler_takes_deadline:
                # a deadline must never silently swap the configured
                # handler's retry semantics for the default policy's
                # (basic must keep returning 5xx as-is): the stock
                # handlers thread the deadline through; a custom
                # handler that cannot take one keeps its exact contract
                return self.handler(self._session, req, self.timeout,
                                    deadline=deadline)
            return self.handler(self._session, req, self.timeout)

        if self.concurrency == 1:
            return [one(r) for r in reqs]
        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            return list(pool.map(one, reqs))

    def close(self):
        self._session.close()


# ---------------------------------------------------------------------------
# Transformer stages
# ---------------------------------------------------------------------------

class HTTPTransformer(Transformer, HasInputCol, HasOutputCol):
    """Send one HTTP request per row (parity: HTTPTransformer.scala:78).

    The input column holds request dicts (or :class:`HTTPRequestData`);
    the output column holds response dicts. Nulls pass through as nulls —
    same contract as the reference (`HTTPTransformer.scala:105`).
    """

    input_col = Param("request", "request column")
    output_col = Param("response", "response column")
    concurrency = Param(8, "max in-flight requests", in_range(lo=1))
    timeout = Param(60.0, "per-request timeout, seconds", in_range(lo=0.0))
    handler = Param("advanced", "retry policy: basic|advanced|policy "
                    "(policy = jittered/budgeted retries + per-host "
                    "circuit breakers)")
    budget = Param(None, "optional whole-transform deadline, seconds: "
                   "bounds retries AND per-attempt socket timeouts for "
                   "every row in this frame", ptype=float)

    def _client(self) -> HTTPClient:
        if self.handler == "policy":
            return HTTPClient(concurrency=self.concurrency,
                              timeout=self.timeout,
                              policy=RetryPolicy(), breakers=True)
        handler = advanced_handler if self.handler == "advanced" \
            else basic_handler
        return HTTPClient(concurrency=self.concurrency,
                          timeout=self.timeout, handler=handler)

    def transform(self, df: DataFrame) -> DataFrame:
        reqs = []
        for v in df[self.input_col]:
            if v is None:
                reqs.append(None)
            elif isinstance(v, HTTPRequestData):
                reqs.append(v)
            else:
                reqs.append(HTTPRequestData.from_dict(v))
        client = self._client()
        deadline = Deadline(self.budget) if self.budget else None
        try:
            resps = client.send(reqs, deadline=deadline)
        finally:
            client.close()
        out = [None if r is None else r.to_dict() for r in resps]
        return df.with_column(self.output_col, obj_col(out))


class JSONInputParser(Transformer, HasInputCol, HasOutputCol):
    """Row value -> POST request with JSON body (parity: Parsers.scala:30)."""

    input_col = Param("value", "column holding the JSON-able payload")
    output_col = Param("request", "request column out")
    url = Param(None, "target url", ptype=str)
    headers = Param(None, "extra headers dict")

    def transform(self, df: DataFrame) -> DataFrame:
        out = [HTTPRequestData.post_json(
                   self.url, v if not isinstance(v, np.ndarray) else v.tolist(),
                   self.headers).to_dict()
               for v in df[self.input_col]]
        return df.with_column(self.output_col, obj_col(out))


class CustomInputParser(Transformer, HasInputCol, HasOutputCol):
    """Row value -> request via a user function (parity: Parsers.scala:83)."""

    input_col = Param("value", "input column")
    output_col = Param("request", "request column out")
    udf = Param(None, "value -> HTTPRequestData (or dict)", complex=True)

    def transform(self, df: DataFrame) -> DataFrame:
        out = []
        for v in df[self.input_col]:
            r = self.udf(v)
            out.append(r.to_dict() if isinstance(r, HTTPRequestData) else r)
        return df.with_column(self.output_col, obj_col(out))


class JSONOutputParser(Transformer, HasInputCol, HasOutputCol):
    """Response -> parsed JSON body (parity: Parsers.scala:143).

    ``data_field`` optionally pulls one field out of the parsed object.
    """

    input_col = Param("response", "response column")
    output_col = Param("parsed", "parsed output column")
    data_field = Param(None, "field to extract from the JSON object")

    def transform(self, df: DataFrame) -> DataFrame:
        out = []
        for v in df[self.input_col]:
            if v is None:
                out.append(None)
                continue
            resp = v if isinstance(v, HTTPResponseData) else \
                HTTPResponseData(**v)
            try:
                parsed = resp.json()
            except (ValueError, AttributeError):
                out.append(None)
                continue
            if self.data_field is not None and isinstance(parsed, dict):
                parsed = parsed.get(self.data_field)
            out.append(parsed)
        return df.with_column(self.output_col, obj_col(out))


class StringOutputParser(Transformer, HasInputCol, HasOutputCol):
    """Response -> body text (parity: Parsers.scala:194)."""

    input_col = Param("response", "response column")
    output_col = Param("text", "text output column")

    def transform(self, df: DataFrame) -> DataFrame:
        out = []
        for v in df[self.input_col]:
            if v is None:
                out.append(None)
            else:
                resp = v if isinstance(v, HTTPResponseData) else \
                    HTTPResponseData(**v)
                out.append(resp.text)
        return df.with_column(self.output_col, obj_col(out))


class CustomOutputParser(Transformer, HasInputCol, HasOutputCol):
    """Response -> value via a user function (parity: Parsers.scala:212)."""

    input_col = Param("response", "response column")
    output_col = Param("parsed", "output column")
    udf = Param(None, "HTTPResponseData -> value", complex=True)

    def transform(self, df: DataFrame) -> DataFrame:
        out = []
        for v in df[self.input_col]:
            if v is None:
                out.append(None)
            else:
                resp = v if isinstance(v, HTTPResponseData) else \
                    HTTPResponseData(**v)
                out.append(self.udf(resp))
        return df.with_column(self.output_col, obj_col(out))


class SimpleHTTPTransformer(Transformer, HasInputCol, HasOutputCol):
    """input parser -> HTTP -> output parser, with an error column.

    Parity: `SimpleHTTPTransformer.scala:61` — composes the full
    request/response pipeline; non-2xx responses put
    ``{status_code, reason}`` into ``error_col`` and null into the output.
    """

    input_col = Param("value", "column fed to the input parser")
    output_col = Param("parsed", "final parsed output")
    input_parser = Param(None, "Transformer making requests", complex=True)
    output_parser = Param(None, "Transformer parsing responses", complex=True)
    error_col = Param("error", "column for failed-request info")
    concurrency = Param(8, "max in-flight requests", in_range(lo=1))
    timeout = Param(60.0, "per-request timeout, s", in_range(lo=0.0))
    handler = Param("advanced", "retry policy: basic|advanced|policy")
    budget = Param(None, "optional whole-transform deadline, seconds",
                   ptype=float)

    def transform(self, df: DataFrame) -> DataFrame:
        req_col = "__http_request"
        resp_col = "__http_response"
        in_parser = self.input_parser or JSONInputParser()
        in_parser = in_parser.copy(input_col=self.input_col,
                                   output_col=req_col)
        out_parser = (self.output_parser or JSONOutputParser()).copy(
            input_col=resp_col, output_col=self.output_col)

        work = in_parser.transform(df)
        work = HTTPTransformer(
            input_col=req_col, output_col=resp_col,
            concurrency=self.concurrency, timeout=self.timeout,
            handler=self.handler, budget=self.budget).transform(work)

        errors = []
        resps = []
        for v in work[resp_col]:
            if v is not None and 200 <= v["status_code"] < 300:
                errors.append(None)
                resps.append(v)
            else:
                errors.append(None if v is None else
                              {"status_code": v["status_code"],
                               "reason": v["reason"]})
                resps.append(None)
        work = work.with_column(resp_col, obj_col(resps))
        out = out_parser.transform(work)
        out = out.with_column(self.error_col, obj_col(errors))
        return out.drop(req_col, resp_col)

    def _save_extra(self, path, arrays):
        self._save_substage(path, "input_parser")
        self._save_substage(path, "output_parser")

    def _load_extra(self, path, arrays):
        self._load_substage(path, "input_parser")
        self._load_substage(path, "output_parser")
