"""Sharded step checkpoints + checkpoint integrity.

The native checkpoint engine both training stacks use (NNLearner step
checkpoints, the SPMD transformer's save/restore). The on-disk format
is **sharded and topology-independent**: every pytree leaf is written
as the set of device shards that actually hold it (one ``.npy`` per
unique shard — a replicated leaf writes once, a tensor-parallel kernel
writes one file per model-axis slice, and no host ever gathers the
global array), plus an ``index.json`` recording each leaf's global
shape/dtype and every shard's slice. Restore assembles any *requested*
slice from the overlapping saved shards, so a state saved on an
8-device mesh restores onto 4, 1, or a differently-factored mesh —
the topology change is a placement decision, not a data migration
(:func:`restore_sharded` builds device arrays shard-by-shard via
``jax.make_array_from_callback``; :class:`ShardedCheckpointManager`
adds the step directory/retention policy on top).

Integrity manifests: every directory checkpoint written through stage
persistence (:func:`mmlspark_tpu.core.serialize.save_stage`) gets a
``checkpoint.sha256.json`` manifest — a per-file SHA-256 listing plus
one combined tree digest — written LAST, so a save that died mid-way
can never present a complete-looking manifest. :func:`verify_digest`
re-hashes the tree against the manifest; the serving rollout path
(:mod:`mmlspark_tpu.serving.rollout`) runs it in **strict** mode before
a model version is flip-eligible, so a truncated or bit-rotted
checkpoint can never go live behind traffic. Restores of digest-less
legacy checkpoints degrade to a warning (``strict=False``), never a
failure — pre-manifest checkpoints keep loading.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.logs import get_logger

logger = get_logger("io.checkpoint")

#: the integrity manifest written beside every stage checkpoint
MANIFEST_FILE = "checkpoint.sha256.json"


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint's content does not match its digest manifest."""


def manager(path: str, max_to_keep: int = 3, create: bool = True
            ) -> "ShardedCheckpointManager":
    from mmlspark_tpu.io import fs as _fs
    if _fs.is_remote(path):
        # the native store writes with plain os/open: silently dropping
        # a gs:// checkpoint onto the VM's ephemeral disk would look
        # like it worked until the preemption it exists for
        raise NotImplementedError(
            f"the native sharded checkpoint store writes local "
            f"filesystem paths only; got {path!r} — point "
            f"checkpoint_dir at a local/NFS mount (remote-object "
            f"backends are a future arc)")
    return ShardedCheckpointManager(os.path.abspath(path),
                                    max_to_keep=max_to_keep,
                                    create=create)


# ---------------------------------------------------------------------------
# sharded leaf store
# ---------------------------------------------------------------------------

INDEX_FILE = "index.json"
_FORMAT = "mmlspark-sharded-v1"


def _leaf_names(tree) -> "Tuple[list, list, Any]":
    """``(leaf_name_list, leaf_list, treedef)`` from ONE flatten:
    stable file-safe names derived from the pytree paths (dict keys /
    sequence indices / NamedTuple fields), so a human can map files
    back to leaves; restore matches BY ORDER against a template's
    flatten, so exotic path objects can never break a round trip —
    and names/leaves coming from the same traversal can never
    desync."""
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = []
    for i, (path, _) in enumerate(flat):
        label = "".join(
            str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k))))
            .replace("/", "_").replace("\\", "_")[:24] + "."
            for k in path)
        names.append(f"leaf{i:05d}.{label.strip('.')}"
                     if label.strip(".") else f"leaf{i:05d}")
    return names, [leaf for _, leaf in flat], treedef


def _slice_key(index, shape):
    """Normalized ``((start, stop), ...)`` for a shard's index."""
    return tuple(
        (0 if sl.start is None else int(sl.start),
         int(shape[d]) if sl.stop is None else int(sl.stop))
        for d, sl in enumerate(index))


def _unique_shards(arr):
    """``[(index, np.ndarray), ...]`` covering ``arr`` without
    duplicates: one entry per distinct slice (replica 0 only). Host
    numpy arrays yield a single full-array shard."""
    import jax
    if not isinstance(arr, jax.Array):
        a = np.asarray(arr)
        return [(tuple((0, s) for s in a.shape), a)]
    out = []
    seen = set()
    for sh in arr.addressable_shards:
        idx = _slice_key(sh.index, arr.shape)
        if idx in seen:
            continue
        seen.add(idx)
        out.append((idx, np.asarray(sh.data)))
    return out


def _global_shard_plan(arr):
    """The GLOBAL unique-slice layout of a (possibly multi-process)
    array and each slice's writer: ``[(index, writer_device), ...]``
    in a deterministic order every process derives identically (sorted
    by slice). The writer is the lowest-id device holding the slice —
    on a multi-process runtime exactly one process owns it, so shard
    files never race across hosts. Derived from sharding METADATA
    (``devices_indices_map``), no device data is touched."""
    import jax
    if not isinstance(arr, jax.Array):
        a = np.asarray(arr)
        return [((tuple((0, s) for s in a.shape)), None)]
    by_slice: dict = {}
    for dev, index in arr.sharding.devices_indices_map(arr.shape).items():
        key = _slice_key(index, arr.shape)
        cur = by_slice.get(key)
        if cur is None or dev.id < cur.id:
            by_slice[key] = dev
    return sorted(by_slice.items())


def _dtype_token(dtype) -> str:
    """Serializable dtype name. Extension dtypes (bfloat16, fp8) have
    no stable ``.str`` descr — ``np.save`` would record a raw-void
    ``<V2`` that restores as garbage — so they travel by NAME and
    their shards are byte-encoded (see ``_save_shard``)."""
    dtype = np.dtype(dtype)
    return dtype.str if dtype.kind != "V" else dtype.name


def _resolve_dtype(token: str) -> np.dtype:
    try:
        return np.dtype(token)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, token))


class _HashingWriter:
    """File wrapper hashing every written byte: the shard's sha256
    falls out of the write itself, so the digest manifest never reads
    a multi-GB checkpoint back just to hash it."""

    __slots__ = ("_f", "hash")

    def __init__(self, f):
        self._f = f
        self.hash = hashlib.sha256()

    def write(self, b):
        self.hash.update(b)
        return self._f.write(b)


def _save_shard(fpath: str, data: np.ndarray) -> "Tuple[bool, str]":
    """Write one shard; returns ``(byte_encoded, sha256)`` —
    byte-encoded means an extension dtype stored as a flat uint8 view,
    reshaped on load from the index's shape + dtype."""
    raw = np.dtype(data.dtype).kind == "V"
    if raw:
        data = np.ascontiguousarray(data).reshape(-1).view(np.uint8)
    with open(fpath, "wb") as f:
        hw = _HashingWriter(f)
        np.save(hw, data, allow_pickle=False)
    return raw, hw.hash.hexdigest()


def save_sharded(path: str, tree, extra: Optional[Dict[str, object]] = None
                 ) -> None:
    """Write ``tree`` under ``path`` in the sharded leaf format.

    Each leaf's unique device shards land as ``<leaf>~<k>.npy`` with
    their global slice recorded in ``index.json``; the integrity
    manifest (:func:`write_digest`) is written LAST, so an interrupted
    save is detectably incomplete and a completed one is flip-eligible
    for the rollout plane exactly like any stage checkpoint. ``extra``
    rides in the index (step number, host metadata).

    Multi-process runtimes (a real DCN mesh) write ONE directory on a
    shared filesystem cooperatively: every process derives the same
    global shard plan from sharding metadata (:func:`_global_shard_plan`
    — each distinct slice is owned by exactly one process, so files
    never race), writes only the shards it owns, and process 0 writes
    the index + digest manifest after a cross-process barrier (the
    manifest-last contract holds globally: no process can observe a
    manifest over missing shards). Restore needs no multi-process
    awareness at all — a 2-process save restores in 1 process (or any
    other topology) exactly like any sharded checkpoint."""
    import jax as _jax
    n_proc = _jax.process_count()
    pid = _jax.process_index() if n_proc > 1 else 0
    os.makedirs(path, exist_ok=True)
    if n_proc > 1:
        # shared-filesystem probe: every process drops a marker, and
        # after the barrier every process verifies it can SEE all of
        # them — a per-host local disk (the misconfiguration the old
        # single-process refusal guarded against) fails HERE, loudly,
        # before any training work is spent on a checkpoint whose
        # index would reference shards that exist on another machine
        marker = os.path.join(path, f".host_marker_{pid}")
        with open(marker, "w") as f:
            f.write(str(pid))
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(
            f"save_sharded:{path}:fs_probe")
        missing = [p for p in range(n_proc)
                   if not os.path.exists(
                       os.path.join(path, f".host_marker_{p}"))]
        if missing:
            raise NotImplementedError(
                f"save_sharded needs a filesystem every process "
                f"shares: process {pid} cannot see the markers of "
                f"process(es) {missing} under {path!r} — point "
                f"checkpoint_dir at shared storage")
    names, flat, _ = _leaf_names(tree)
    leaves: Dict[str, dict] = {}
    digests: Dict[str, str] = {}
    for name, arr_like in zip(names, flat):
        shape = tuple(int(s) for s in np.shape(arr_like))
        shards = []
        # ONE plan-driven loop for every process count (single-process
        # is just "every writer is local" — pinned equivalent to the
        # old replica-0 dedup in TestGlobalShardPlan). Shard handles
        # are NOT materialized up front: a non-owner process must not
        # pay a device->host copy of replicas it will never write
        # (np.asarray happens only for owned slices).
        import jax as _j
        local = ({_slice_key(sh.index, arr_like.shape): sh
                  for sh in arr_like.addressable_shards}
                 if isinstance(arr_like, _j.Array) else {})
        for k, (idx, writer) in enumerate(_global_shard_plan(arr_like)):
            fname = f"{name}~{k}.npy"
            mine = (writer is None and pid == 0) or (
                writer is not None and writer.process_index == pid)
            if mine:
                sh = local.get(idx)
                data = (np.asarray(sh.data) if sh is not None
                        else np.asarray(arr_like))  # host leaf: p0
                raw, sha = _save_shard(os.path.join(path, fname),
                                       data)
                digests[fname] = sha
            else:
                # the index is identical on every process; only the
                # owner wrote the bytes. raw-ness is a dtype property,
                # derivable everywhere:
                raw = np.dtype(getattr(
                    arr_like, "dtype", np.float32)).kind == "V"
            entry = {"index": [list(p) for p in idx], "file": fname}
            if raw:
                entry["raw"] = True
            shards.append(entry)
        dtype = getattr(arr_like, "dtype", None)
        if dtype is None:
            dtype = np.asarray(arr_like).dtype
        leaves[name] = {"shape": list(shape),
                        "dtype": _dtype_token(dtype),
                        "shards": shards}
    if n_proc > 1:
        # all shards on disk before anyone writes (or trusts) the
        # index/manifest; and everyone returns only after the manifest
        # exists — both sides of the manifest-last contract
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"save_sharded:{path}:shards")
    if pid == 0:
        # the probe markers served their purpose; they must not land
        # in the digest manifest's file set
        for p in range(n_proc):
            try:
                os.remove(os.path.join(path, f".host_marker_{p}"))
            except FileNotFoundError:
                pass
        index = {"format": _FORMAT, "leaves": leaves,
                 "extra": dict(extra or {})}
        tmp = os.path.join(path, INDEX_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(index, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(path, INDEX_FILE))
        # shard digests were hashed during the writes; files other
        # processes wrote are hashed from disk (shared filesystem);
        # only index.json (small) is read back otherwise
        write_digest(path, precomputed=digests)
    if n_proc > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"save_sharded:{path}:done")


def read_index(path: str) -> Dict[str, object]:
    with open(os.path.join(path, INDEX_FILE)) as f:
        index = json.load(f)
    if index.get("format") != _FORMAT:
        raise CheckpointIntegrityError(
            f"unknown checkpoint format {index.get('format')!r} at "
            f"{path!r}")
    return index


def _load_shard(path: str, sh: dict, dtype, cache: Optional[dict],
                digests: Optional[Dict[str, str]] = None) -> np.ndarray:
    """Load one stored shard (memoized per restore call: with N
    addressable devices the callback runs N times, and a replicated
    leaf would otherwise re-read the identical file N times). With
    ``digests``, the shard's sha256 is checked against the manifest
    AS the bytes are read — every consumed byte verified in the same
    single disk pass."""
    import io

    fname = sh["file"]
    if cache is not None and fname in cache:
        return cache[fname]
    with open(os.path.join(path, fname), "rb") as f:
        blob = f.read()
    if digests is not None:
        actual = hashlib.sha256(blob).hexdigest()
        if actual != digests.get(fname):
            raise CheckpointIntegrityError(
                f"digest mismatch for {fname!r}: manifest "
                f"{str(digests.get(fname))[:12]}..., file "
                f"{actual[:12]}...")
    data = np.load(io.BytesIO(blob), allow_pickle=False)
    if sh.get("raw"):
        # byte-encoded extension dtype: flat uint8 back to typed shape
        s_shape = tuple(b - a for a, b in
                        (tuple(p) for p in sh["index"]))
        data = np.frombuffer(data.tobytes(), dtype=dtype).reshape(s_shape)
    if cache is not None:
        cache[fname] = data
    return data


def _assemble_slice(path: str, meta: dict, req: "Tuple[slice, ...]",
                    dtype, cache: Optional[dict] = None,
                    digests: Optional[Dict[str, str]] = None
                    ) -> np.ndarray:
    """Assemble the requested slice of one leaf from its saved shards
    (reading only overlapping files; a same-topology restore reads
    exactly its own shard back)."""
    shape = tuple(meta["shape"])
    lo = [0 if s.start is None else int(s.start) for s in req]
    hi = [shape[d] if s.stop is None else int(s.stop)
          for d, s in enumerate(req)]
    out = np.empty([h - l for l, h in zip(lo, hi)], dtype=dtype)
    filled = 0
    for sh in meta["shards"]:
        s_idx = [tuple(p) for p in sh["index"]]
        # overlap of the stored shard with the requested window
        o_lo = [max(l, a) for l, (a, _) in zip(lo, s_idx)]
        o_hi = [min(h, b) for h, (_, b) in zip(hi, s_idx)]
        if any(a >= b for a, b in zip(o_lo, o_hi)):
            continue
        data = _load_shard(path, sh, dtype, cache, digests)
        src = tuple(slice(a - s_lo, b - s_lo) for (a, b), (s_lo, _) in
                    zip(zip(o_lo, o_hi), s_idx))
        dst = tuple(slice(a - l, b - l) for (a, b), l in
                    zip(zip(o_lo, o_hi), lo))
        out[dst] = data[src]
        filled += int(np.prod([b - a for a, b in zip(o_lo, o_hi)],
                              dtype=np.int64))
    if filled < int(np.prod(out.shape, dtype=np.int64)):
        raise CheckpointIntegrityError(
            f"stored shards do not cover the requested slice "
            f"(leaf shape {shape}, requested {list(zip(lo, hi))})")
    return out


def restore_sharded(path: str, template, shardings=None,
                    strict_digest: bool = False):
    """Restore a tree saved by :func:`save_sharded`.

    ``template`` fixes the pytree structure (leaf order matches the
    save). With ``shardings`` (a matching tree of ``NamedSharding`` —
    typically :func:`mmlspark_tpu.parallel.dist.state_shardings` over
    the *restoring* mesh) each leaf is built directly as a sharded
    ``jax.Array``, every device shard assembled from only the saved
    files that overlap it — the topology-change path (save on 8
    devices, restore on 4 or 1, or re-factor the axes). Without
    ``shardings`` the full host arrays are returned.

    Integrity: with ``strict_digest`` the WHOLE tree is hashed up
    front (the rollout flip-eligibility contract — every file proven,
    read or not). Otherwise the manifest's file set is checked up
    front (missing/extra files fail fast) and each shard's digest is
    verified AS it is read — one disk pass over exactly the bytes the
    restore consumes; legacy digest-less directories load with a
    warning, never a failure.
    """
    digests: Optional[Dict[str, str]] = None
    if strict_digest:
        ok, detail = verify_digest(path, strict=True)
        if not ok:
            raise CheckpointIntegrityError(
                f"sharded checkpoint {path!r} failed digest "
                f"verification: {detail}")
    else:
        manifest_path = os.path.join(path, MANIFEST_FILE)
        if not os.path.exists(manifest_path):
            logger.warning(
                "checkpoint %s has no integrity manifest (legacy "
                "save before digests); loading unverified", path)
        else:
            try:
                with open(manifest_path) as f:
                    digests = dict(json.load(f)["files"])
            except (ValueError, KeyError, TypeError) as e:
                raise CheckpointIntegrityError(
                    f"unreadable manifest at {path!r}: {e}")
            have = set(_iter_files(path))
            missing = sorted(set(digests) - have)
            if missing:
                raise CheckpointIntegrityError(
                    f"files missing from checkpoint: {missing[:5]}")
            extra = sorted(have - set(digests))
            if extra:
                raise CheckpointIntegrityError(
                    f"files not in manifest: {extra[:5]}")
            # the index is the map everything else is read through:
            # check its (tiny) digest up front
            if INDEX_FILE in digests:
                actual = _sha256_file(os.path.join(path, INDEX_FILE))
                if actual != digests[INDEX_FILE]:
                    raise CheckpointIntegrityError(
                        f"digest mismatch for {INDEX_FILE!r}")
    index = read_index(path)
    leaves_meta = index["leaves"]
    import jax
    names, flat, treedef = _leaf_names(template)
    if len(names) != len(leaves_meta):
        raise CheckpointIntegrityError(
            f"checkpoint has {len(leaves_meta)} leaves; template "
            f"expects {len(names)}")
    shard_flat = None
    if shardings is not None:
        shard_flat = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: hasattr(x, "spec"))
        if len(shard_flat) != len(names):
            raise ValueError("shardings tree does not match template")
    out = []
    for i, name in enumerate(names):
        meta = leaves_meta.get(name)
        if meta is None:
            raise CheckpointIntegrityError(
                f"leaf {name!r} missing from checkpoint index")
        shape = tuple(meta["shape"])
        t_shape = tuple(int(s) for s in np.shape(flat[i]))
        if shape != t_shape:
            raise CheckpointIntegrityError(
                f"leaf {name!r}: checkpoint shape {shape} != template "
                f"shape {t_shape}")
        dtype = _resolve_dtype(meta["dtype"])
        t_dtype = getattr(flat[i], "dtype", None)
        if t_dtype is not None and np.dtype(t_dtype) != dtype:
            # dtype drift fails as loudly as shape drift: silently
            # restoring the saved precision into a reconfigured model
            # retraces the donated step and trains at the wrong dtype
            raise CheckpointIntegrityError(
                f"leaf {name!r}: checkpoint dtype {dtype} != template "
                f"dtype {np.dtype(t_dtype)}")
        if shard_flat is not None:
            sharding = shard_flat[i]
            cache: dict = {}   # one file read per LEAF restore
            arr = jax.make_array_from_callback(
                shape, sharding,
                lambda req, _m=meta, _d=dtype, _c=cache:
                    _assemble_slice(path, _m, req, _d, cache=_c,
                                    digests=digests))
        else:
            arr = _assemble_slice(
                path, meta, tuple(slice(0, s) for s in shape), dtype,
                digests=digests)
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# step manager
# ---------------------------------------------------------------------------

class ShardedCheckpointManager:
    """Step-directory retention over :func:`save_sharded` — the
    checkpoint-manager surface the trainer drives (``latest_step`` /
    ``save`` / ``restore`` / ``wait_until_finished``; saves are
    synchronous, so ``wait_until_finished`` is the durability no-op
    the call sites keep for interface parity)."""

    STEP_PREFIX = "step_"

    def __init__(self, path: str, max_to_keep: int = 3,
                 create: bool = True):
        self.path = path
        self.max_to_keep = int(max_to_keep)
        if create:
            os.makedirs(path, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.path, f"{self.STEP_PREFIX}{step:08d}")

    def all_steps(self) -> "list[int]":
        if not os.path.isdir(self.path):
            return []
        out = []
        for name in os.listdir(self.path):
            if not name.startswith(self.STEP_PREFIX):
                continue
            # only COMPLETE saves count: the manifest is written last,
            # so its absence marks an interrupted save (never restored,
            # swept by retention)
            if not os.path.exists(os.path.join(
                    self.path, name, MANIFEST_FILE)):
                continue
            try:
                out.append(int(name[len(self.STEP_PREFIX):]))
            except ValueError:
                continue
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, step: int, tree,
             extra: Optional[Dict[str, object]] = None) -> str:
        target = self._step_dir(int(step))
        save_sharded(target, tree,
                     extra={"step": int(step), **(extra or {})})
        # multi-process saves are cooperative (save_sharded barriers);
        # retention is process 0's job alone — two hosts rmtree-ing
        # the same step dir is a race with no winner
        import jax as _jax
        if _jax.process_count() == 1 or _jax.process_index() == 0:
            self._prune(current=int(step))
        return target

    def restore(self, step: Optional[int], template, shardings=None,
                strict_digest: bool = False):
        target = self.latest_step() if step is None else int(step)
        if target is None:
            raise FileNotFoundError(f"no checkpoint under {self.path!r}")
        return restore_sharded(self._step_dir(target), template,
                               shardings=shardings,
                               strict_digest=strict_digest)

    def _prune(self, current: Optional[int] = None) -> None:
        import shutil
        if self.max_to_keep > 0:
            for step in self.all_steps()[:-self.max_to_keep]:
                shutil.rmtree(self._step_dir(step), ignore_errors=True)
        if current is None:
            return
        # interrupted saves: a manifest-less step dir OLDER than the
        # one just written is a dead partial (the crash the
        # manifest-last contract detects) — sweep it, or repeated
        # preemptions accumulate unbounded shard data retention never
        # sees. Never touch dirs >= current: another manager could be
        # mid-save on a newer step
        complete = set(self.all_steps())
        for name in os.listdir(self.path):
            if not name.startswith(self.STEP_PREFIX):
                continue
            try:
                step = int(name[len(self.STEP_PREFIX):])
            except ValueError:
                continue
            if step < current and step not in complete:
                shutil.rmtree(os.path.join(self.path, name),
                              ignore_errors=True)

    def wait_until_finished(self) -> None:
        return None

    def close(self) -> None:
        return None


def _iter_files(path: str):
    """Relative paths of every regular file under ``path``, sorted, the
    top-level manifest excluded (it cannot hash itself; NESTED manifests
    — substage checkpoints are checkpoints too — are content like any
    other file)."""
    out = []
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for name in sorted(files):
            rel = os.path.relpath(os.path.join(root, name), path)
            if rel == MANIFEST_FILE:
                continue
            out.append(rel)
    return out


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def compute_digest(path: str,
                   precomputed: Optional[Dict[str, str]] = None
                   ) -> Dict[str, object]:
    """Hash every file under ``path`` into a manifest dict:
    ``{"files": {relpath: sha256}, "digest": <combined tree digest>}``.
    The combined digest hashes the sorted ``relpath:sha256`` lines, so
    it pins both contents AND the file set (a deleted file changes it
    as surely as a flipped bit). ``precomputed`` supplies digests a
    writer hashed while streaming the bytes out (the sharded save
    path), so a multi-GB checkpoint is not read back just to hash it;
    files not covered are hashed from disk as before."""
    precomputed = precomputed or {}
    files = {rel: precomputed.get(rel)
             or _sha256_file(os.path.join(path, rel))
             for rel in _iter_files(path)}
    tree = hashlib.sha256()
    for rel in sorted(files):
        tree.update(f"{rel}:{files[rel]}\n".encode())
    return {"files": files, "digest": tree.hexdigest()}


def write_digest(path: str,
                 precomputed: Optional[Dict[str, str]] = None
                 ) -> Dict[str, object]:
    """Write (atomically: temp file + rename) the integrity manifest
    for the checkpoint directory at ``path`` and return it. Call LAST
    in any save path — an interrupted save must leave a missing or
    stale manifest, never a valid-looking one. ``precomputed`` as in
    :func:`compute_digest`."""
    manifest = compute_digest(path, precomputed=precomputed)
    manifest["algorithm"] = "sha256"
    target = os.path.join(path, MANIFEST_FILE)
    tmp = target + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, target)
    return manifest


def verify_digest(path: str, strict: bool = False
                  ) -> Tuple[bool, Optional[str]]:
    """Verify the checkpoint at ``path`` against its manifest.

    Returns ``(ok, detail)``. A **missing** manifest is the legacy
    (pre-digest) case: with ``strict=False`` it logs a warning and
    passes (``detail`` says why), with ``strict=True`` it fails — the
    rollout flip-eligibility contract, where "cannot prove integrity"
    must read as "not safe to serve". A **mismatch** (changed bytes,
    missing files, extra files) always fails; callers that load the
    checkpoint raise :class:`CheckpointIntegrityError` on it.
    """
    manifest_path = os.path.join(path, MANIFEST_FILE)
    if not os.path.exists(manifest_path):
        detail = ("no integrity manifest (legacy checkpoint saved "
                  "before digests)")
        if strict:
            return False, detail
        logger.warning("checkpoint %s has %s; loading unverified",
                       path, detail)
        return True, detail
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        want = dict(manifest["files"])
    except (ValueError, KeyError, TypeError) as e:
        return False, f"unreadable manifest: {e}"
    have = set(_iter_files(path))
    missing = sorted(set(want) - have)
    if missing:
        return False, f"files missing from checkpoint: {missing[:5]}"
    extra = sorted(have - set(want))
    if extra:
        return False, f"files not in manifest: {extra[:5]}"
    for rel, digest in sorted(want.items()):
        actual = _sha256_file(os.path.join(path, rel))
        if actual != digest:
            return False, (f"digest mismatch for {rel!r}: "
                           f"manifest {digest[:12]}..., "
                           f"file {actual[:12]}...")
    return True, None
