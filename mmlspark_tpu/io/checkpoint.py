"""Shared orbax checkpoint-manager construction.

One place for the path rule both training stacks use (NNLearner step
checkpoints, the SPMD transformer's save/restore): remote URLs
(``gs://...``) pass through untouched — orbax's tensorstore backend
handles them natively on TPU VMs — and only local paths are
absolutized (parity: the reference checkpoints streaming state to
HDFS, `HadoopUtils.scala`).
"""

from __future__ import annotations

import os


def manager(path: str, max_to_keep: int = 3, create: bool = True):
    import orbax.checkpoint as ocp
    from mmlspark_tpu.io import fs as _fs
    path = path if _fs.is_remote(path) else os.path.abspath(path)
    return ocp.CheckpointManager(
        path, options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=create))
