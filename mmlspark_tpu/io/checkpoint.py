"""Shared orbax checkpoint-manager construction + checkpoint integrity.

One place for the path rule both training stacks use (NNLearner step
checkpoints, the SPMD transformer's save/restore): remote URLs
(``gs://...``) pass through untouched — orbax's tensorstore backend
handles them natively on TPU VMs — and only local paths are
absolutized (parity: the reference checkpoints streaming state to
HDFS, `HadoopUtils.scala`).

Integrity manifests: every directory checkpoint written through stage
persistence (:func:`mmlspark_tpu.core.serialize.save_stage`) gets a
``checkpoint.sha256.json`` manifest — a per-file SHA-256 listing plus
one combined tree digest — written LAST, so a save that died mid-way
can never present a complete-looking manifest. :func:`verify_digest`
re-hashes the tree against the manifest; the serving rollout path
(:mod:`mmlspark_tpu.serving.rollout`) runs it in **strict** mode before
a model version is flip-eligible, so a truncated or bit-rotted
checkpoint can never go live behind traffic. Restores of digest-less
legacy checkpoints degrade to a warning (``strict=False``), never a
failure — pre-manifest checkpoints keep loading.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Optional, Tuple

from mmlspark_tpu.core.logs import get_logger

logger = get_logger("io.checkpoint")

#: the integrity manifest written beside every stage checkpoint
MANIFEST_FILE = "checkpoint.sha256.json"


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint's content does not match its digest manifest."""


def manager(path: str, max_to_keep: int = 3, create: bool = True):
    import orbax.checkpoint as ocp
    from mmlspark_tpu.io import fs as _fs
    path = path if _fs.is_remote(path) else os.path.abspath(path)
    return ocp.CheckpointManager(
        path, options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=create))


def _iter_files(path: str):
    """Relative paths of every regular file under ``path``, sorted, the
    top-level manifest excluded (it cannot hash itself; NESTED manifests
    — substage checkpoints are checkpoints too — are content like any
    other file)."""
    out = []
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for name in sorted(files):
            rel = os.path.relpath(os.path.join(root, name), path)
            if rel == MANIFEST_FILE:
                continue
            out.append(rel)
    return out


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def compute_digest(path: str) -> Dict[str, object]:
    """Hash every file under ``path`` into a manifest dict:
    ``{"files": {relpath: sha256}, "digest": <combined tree digest>}``.
    The combined digest hashes the sorted ``relpath:sha256`` lines, so
    it pins both contents AND the file set (a deleted file changes it
    as surely as a flipped bit)."""
    files = {rel: _sha256_file(os.path.join(path, rel))
             for rel in _iter_files(path)}
    tree = hashlib.sha256()
    for rel in sorted(files):
        tree.update(f"{rel}:{files[rel]}\n".encode())
    return {"files": files, "digest": tree.hexdigest()}


def write_digest(path: str) -> Dict[str, object]:
    """Write (atomically: temp file + rename) the integrity manifest
    for the checkpoint directory at ``path`` and return it. Call LAST
    in any save path — an interrupted save must leave a missing or
    stale manifest, never a valid-looking one."""
    manifest = compute_digest(path)
    manifest["algorithm"] = "sha256"
    target = os.path.join(path, MANIFEST_FILE)
    tmp = target + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, target)
    return manifest


def verify_digest(path: str, strict: bool = False
                  ) -> Tuple[bool, Optional[str]]:
    """Verify the checkpoint at ``path`` against its manifest.

    Returns ``(ok, detail)``. A **missing** manifest is the legacy
    (pre-digest) case: with ``strict=False`` it logs a warning and
    passes (``detail`` says why), with ``strict=True`` it fails — the
    rollout flip-eligibility contract, where "cannot prove integrity"
    must read as "not safe to serve". A **mismatch** (changed bytes,
    missing files, extra files) always fails; callers that load the
    checkpoint raise :class:`CheckpointIntegrityError` on it.
    """
    manifest_path = os.path.join(path, MANIFEST_FILE)
    if not os.path.exists(manifest_path):
        detail = ("no integrity manifest (legacy checkpoint saved "
                  "before digests)")
        if strict:
            return False, detail
        logger.warning("checkpoint %s has %s; loading unverified",
                       path, detail)
        return True, detail
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        want = dict(manifest["files"])
    except (ValueError, KeyError, TypeError) as e:
        return False, f"unreadable manifest: {e}"
    have = set(_iter_files(path))
    missing = sorted(set(want) - have)
    if missing:
        return False, f"files missing from checkpoint: {missing[:5]}"
    extra = sorted(have - set(want))
    if extra:
        return False, f"files not in manifest: {extra[:5]}"
    for rel, digest in sorted(want.items()):
        actual = _sha256_file(os.path.join(path, rel))
        if actual != digest:
            return False, (f"digest mismatch for {rel!r}: "
                           f"manifest {digest[:12]}..., "
                           f"file {actual[:12]}...")
    return True, None
