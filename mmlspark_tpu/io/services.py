"""Cognitive-service-style transformers + PowerBI-style writer.

Capability parity with the reference's Cognitive Services layer
(`io/http/src/main/scala/CognitiveServiceBase.scala:25-241`,
`services/TextAnalytics.scala:184-248`, `services/ComputerVision.scala:180-474`,
`services/Face.scala:19-277`, `services/Speech.scala:23`,
`services/ImageSearch.scala:63`, `services/AzureSearch.scala:81,143`,
`services/AnamolyDetection.scala:118,131`) and the PowerBI writer
(`io/powerbi/src/main/scala/PowerBIWriter.scala:25`): text analytics,
computer vision, face, speech, anomaly detection, image search, plus the
two batch writers. Every stage takes an explicit ``url`` so they run
against any compatible endpoint (tests use localhost) rather than
hard-coding Azure regions.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, obj_col
from mmlspark_tpu.core.params import Param, HasOutputCol, in_range
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.io.http import (
    CustomInputParser, HTTPRequestData, JSONOutputParser,
    SimpleHTTPTransformer,
)


class CognitiveServiceBase(Transformer, HasOutputCol):
    """Shared plumbing: build a JSON request per row, send, parse.

    Parity: `CognitiveServiceBase.scala:25-241` (HasServiceParams /
    subscription key header / SimpleHTTPTransformer internals).
    """

    url = Param(None, "service endpoint", ptype=str)
    subscription_key = Param(None, "subscription key header value")
    concurrency = Param(4, "max in-flight requests", in_range(lo=1))
    timeout = Param(60.0, "request timeout, s", in_range(lo=0.0))
    error_col = Param("error", "failed-request info column")
    output_col = Param("result", "parsed output column")
    # policy-driven by default: jittered/budgeted retries + a per-host
    # circuit breaker, so a dead or throttling service endpoint sheds
    # the rest of the frame instead of timing out row by row
    handler = Param("policy", "retry policy: basic|advanced|policy")
    budget = Param(None, "optional whole-transform deadline, seconds",
                   ptype=float)

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.subscription_key:
            h["Ocp-Apim-Subscription-Key"] = self.subscription_key
        return h

    def _make_request(self, value: Any) -> Optional[HTTPRequestData]:
        """Row value -> request; override per service."""
        raise NotImplementedError

    def _input_column(self) -> str:
        raise NotImplementedError

    def _output_parser(self) -> Transformer:
        return JSONOutputParser()

    def transform(self, df: DataFrame) -> DataFrame:
        inner = SimpleHTTPTransformer(
            input_col=self._input_column(), output_col=self.output_col,
            input_parser=CustomInputParser(udf=self._make_request),
            output_parser=self._output_parser(),
            error_col=self.error_col, concurrency=self.concurrency,
            timeout=self.timeout, handler=self.handler,
            budget=self.budget)
        return inner.transform(df)


class _TextAnalyticsBase(CognitiveServiceBase):
    """Documents-array protocol shared by the text services.

    Parity: TextAnalyticsBase (`TextAnalytics.scala`): rows become
    ``{"documents": [{"id", "text", "language"?}]}`` requests.
    """

    text_col = Param("text", "input text column")
    language = Param(None, "language hint")

    def _input_column(self) -> str:
        return self.text_col

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        doc: Dict[str, Any] = {"id": "0", "text": str(value)}
        if self.language:
            doc["language"] = self.language
        return HTTPRequestData.post_json(
            self.url, {"documents": [doc]}, self._headers())

    def _shape_doc(self, doc: Dict[str, Any]) -> Any:
        """Per-service payload extraction from a response document;
        subclasses each mirror their reference response schema
        (`schemas/TextAnalyticsSchemas.scala`)."""
        return doc

    def _output_parser(self) -> Transformer:
        from mmlspark_tpu.io.http import CustomOutputParser

        def parse(resp):
            try:
                body = resp.json()
            except (ValueError, AttributeError):
                return None
            if not isinstance(body, dict):
                return None
            docs = body.get("documents") or []
            if not docs:
                # TAResponse.errors: surface the per-document message
                errs = body.get("errors") or []
                if errs and isinstance(errs[0], dict):
                    return {"error": errs[0].get("message", "")}
                return None
            return self._shape_doc(docs[0])

        return CustomOutputParser(udf=parse)


class TextSentiment(_TextAnalyticsBase):
    """Sentiment score in [0, 1] per row (0 = negative, 1 = positive).

    Output column holds the float score alone — the distinct
    ``SentimentScore(id, score)`` schema of the reference
    (`TextAnalytics.scala:184`, `TextAnalyticsSchemas.scala`
    SentimentResponse).
    """

    def _shape_doc(self, doc: Dict[str, Any]) -> Any:
        return doc.get("score")


class LanguageDetector(_TextAnalyticsBase):
    """Detected language per row: best guess + full candidate list.

    Output: ``{"language", "iso6391Name", "score", "detectedLanguages"}``
    (reference `DetectLanguageScore.detectedLanguages` with
    ``DetectedLanguage(name, iso6391Name, score)``).
    """

    def _shape_doc(self, doc: Dict[str, Any]) -> Any:
        langs = doc.get("detectedLanguages") or []
        best = max(langs, key=lambda d: d.get("score", 0.0)) if langs else {}
        return {"language": best.get("name"),
                "iso6391Name": best.get("iso6391Name"),
                "score": best.get("score"),
                "detectedLanguages": langs}


class EntityDetector(_TextAnalyticsBase):
    """Linked (wikipedia) entities per row.

    Output: the ``entities`` list — reference ``Entity(name, matches,
    wikipediaLanguage, wikipediaId, wikipediaUrl, bingId)``
    (DetectEntitiesResponse).
    """

    def _shape_doc(self, doc: Dict[str, Any]) -> Any:
        return doc.get("entities") or []


class NER(_TextAnalyticsBase):
    """Named entities with type/subtype per row.

    Output: the ``entities`` list — reference ``NEREntity(name, matches,
    type, subtype, ...)`` (NERResponse); distinct from
    :class:`EntityDetector`'s wikipedia-linking schema.
    """

    def _shape_doc(self, doc: Dict[str, Any]) -> Any:
        return doc.get("entities") or []


class KeyPhraseExtractor(_TextAnalyticsBase):
    """Key phrases per row as a list of strings.

    Output: ``keyPhrases`` (reference ``KeyPhraseScore.keyPhrases``,
    KeyPhraseResponse).
    """

    def _shape_doc(self, doc: Dict[str, Any]) -> Any:
        return doc.get("keyPhrases") or []


class _ImageServiceBase(CognitiveServiceBase):
    """Image-url protocol shared by the vision services."""

    image_url_col = Param("image_url", "column of image URLs")

    def _input_column(self) -> str:
        return self.image_url_col

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        return HTTPRequestData.post_json(
            self.url, {"url": str(value)}, self._headers())


class AnalyzeImage(_ImageServiceBase):
    """Parity: `ComputerVision.scala` AnalyzeImage."""


class OCR(_ImageServiceBase):
    """Parity: `ComputerVision.scala` OCR."""


class DescribeImage(_ImageServiceBase):
    """Parity: `ComputerVision.scala` DescribeImage."""


class TagImage(_ImageServiceBase):
    """Parity: `ComputerVision.scala` TagImage."""


class GenerateThumbnails(_ImageServiceBase):
    """Parity: `ComputerVision.scala` GenerateThumbnails (width/height/
    smartCropping as query params)."""

    width = Param(64, "thumbnail width", ptype=int)
    height = Param(64, "thumbnail height", ptype=int)
    smart_cropping = Param(True, "crop around region of interest",
                           ptype=bool)

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        q = (f"width={self.width}&height={self.height}"
             f"&smartCropping={str(self.smart_cropping).lower()}")
        sep = "&" if "?" in self.url else "?"
        return HTTPRequestData.post_json(
            f"{self.url}{sep}{q}", {"url": str(value)}, self._headers())


class RecognizeText(_ImageServiceBase):
    """Parity: `ComputerVision.scala` RecognizeText (mode query param)."""

    mode = Param("Printed", "Printed | Handwritten")

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        sep = "&" if "?" in self.url else "?"
        return HTTPRequestData.post_json(
            f"{self.url}{sep}mode={self.mode}", {"url": str(value)},
            self._headers())


class RecognizeDomainSpecificContent(_ImageServiceBase):
    """Parity: `ComputerVision.scala` RecognizeDomainSpecificContent
    (celebrity/landmark model in the path)."""

    model = Param("celebrities", "domain model name")

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        return HTTPRequestData.post_json(
            f"{self.url.rstrip('/')}/models/{self.model}/analyze",
            {"url": str(value)}, self._headers())


class DetectFace(_ImageServiceBase):
    """Parity: `Face.scala:19` DetectFace (returnFaceAttributes etc.)."""

    return_face_id = Param(True, "include faceId", ptype=bool)
    return_face_landmarks = Param(False, "include landmarks", ptype=bool)
    return_face_attributes = Param(None, "attribute list", ptype=list)

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        q = [f"returnFaceId={str(self.return_face_id).lower()}",
             f"returnFaceLandmarks={str(self.return_face_landmarks).lower()}"]
        if self.return_face_attributes:
            q.append("returnFaceAttributes="
                     + ",".join(self.return_face_attributes))
        sep = "&" if "?" in self.url else "?"
        return HTTPRequestData.post_json(
            f"{self.url}{sep}{'&'.join(q)}", {"url": str(value)},
            self._headers())


class FindSimilarFace(CognitiveServiceBase):
    """Parity: `Face.scala` FindSimilarFaces: one probe faceId per row
    against a fixed candidate list."""

    face_id_col = Param("face_id", "column of probe face ids")
    face_ids = Param(None, "candidate face ids", ptype=list)
    max_candidates = Param(20, "max returned matches", ptype=int)

    def _input_column(self) -> str:
        return self.face_id_col

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        return HTTPRequestData.post_json(
            self.url, {"faceId": str(value),
                       "faceIds": list(self.face_ids or []),
                       "maxNumOfCandidatesReturned": self.max_candidates},
            self._headers())


class GroupFaces(CognitiveServiceBase):
    """Parity: `Face.scala` GroupFaces: each row holds a faceIds list."""

    face_ids_col = Param("face_ids", "column of face-id lists")

    def _input_column(self) -> str:
        return self.face_ids_col

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        ids = value.tolist() if isinstance(value, np.ndarray) else list(value)
        return HTTPRequestData.post_json(
            self.url, {"faceIds": [str(v) for v in ids]}, self._headers())


class IdentifyFaces(GroupFaces):
    """Parity: `Face.scala` IdentifyFaces (faceIds + personGroupId)."""

    person_group_id = Param(None, "person group to search")
    max_candidates = Param(1, "candidates per face", ptype=int)

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        ids = value.tolist() if isinstance(value, np.ndarray) else list(value)
        return HTTPRequestData.post_json(
            self.url, {"faceIds": [str(v) for v in ids],
                       "personGroupId": self.person_group_id,
                       "maxNumOfCandidatesReturned": self.max_candidates},
            self._headers())


class VerifyFaces(CognitiveServiceBase):
    """Parity: `Face.scala` VerifyFaces — two face-id columns per row."""

    face_id1_col = Param("face_id1", "first face id column")
    face_id2_col = Param("face_id2", "second face id column")

    def _input_column(self) -> str:
        return "__verify_pair__"

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        f1, f2 = value
        if f1 is None or f2 is None:  # null skip, like every other binding
            return None
        return HTTPRequestData.post_json(
            self.url, {"faceId1": str(f1), "faceId2": str(f2)},
            self._headers())

    def transform(self, df: DataFrame) -> DataFrame:
        pairs = obj_col(list(zip(df[self.face_id1_col],
                                 df[self.face_id2_col])))
        out = super().transform(df.with_column("__verify_pair__", pairs))
        return out.drop("__verify_pair__")


class SpeechToText(CognitiveServiceBase):
    """Parity: `Speech.scala:23` SpeechToText — posts raw audio bytes."""

    audio_col = Param("audio", "column of raw audio bytes")
    audio_format = Param("wav", "audio container format")
    language = Param("en-US", "recognition language")

    def _input_column(self) -> str:
        return self.audio_col

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        h = self._headers()
        h["Content-Type"] = f"audio/{self.audio_format}"
        sep = "&" if "?" in self.url else "?"
        return HTTPRequestData(url=f"{self.url}{sep}language={self.language}",
                               method="POST", headers=h, body=bytes(value))


class BingImageSearch(CognitiveServiceBase):
    """Parity: `ImageSearch.scala:63` BingImageSearch — GET per query row;
    results land under the response's ``value`` array."""

    query_col = Param("query", "column of search queries")
    count = Param(10, "results per query", ptype=int)
    offset = Param(0, "result offset", ptype=int)

    def _input_column(self) -> str:
        return self.query_col

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        from urllib.parse import quote
        sep = "&" if "?" in self.url else "?"
        return HTTPRequestData(
            url=(f"{self.url}{sep}q={quote(str(value))}"
                 f"&count={self.count}&offset={self.offset}"),
            method="GET", headers=self._headers())

    def _output_parser(self) -> Transformer:
        return JSONOutputParser(data_field="value")


class BingImageSource:
    """Streaming image-search source: page through results for a set of
    search terms, one frame of ``(search_term, image)`` rows per batch.

    Parity: `BingImageSource.scala:83` — the reference pairs a counting
    streaming source with a vector-param BingImageSearch and explodes
    each response's image array; here each :meth:`batches` step queries
    every term at the current offset through :class:`BingImageSearch`,
    explodes the ``value`` arrays into rows, and advances the offset by
    ``imgs_per_batch``. The stream ends when every term comes back
    empty (results exhausted), mirroring `FileStreamSource.batches`.
    """

    def __init__(self, search_terms: List[str], url: str,
                 subscription_key: Optional[str] = None,
                 imgs_per_batch: int = 10,
                 concurrency: int = 4,
                 timeout: float = 60.0):
        if not search_terms:
            raise ValueError("search_terms must be non-empty")
        self.search_terms = list(search_terms)
        self.url = url
        self.subscription_key = subscription_key
        self.imgs_per_batch = int(imgs_per_batch)
        self.concurrency = concurrency
        self.timeout = timeout
        self._offset = 0

    def batches(self, max_batches: Optional[int] = None):
        """Yield frames of ``search_term`` / ``image`` (one row per image
        object) until exhausted or ``max_batches``."""
        yielded = 0
        while max_batches is None or yielded < max_batches:
            stage = BingImageSearch(
                url=self.url, subscription_key=self.subscription_key,
                count=self.imgs_per_batch, offset=self._offset,
                concurrency=self.concurrency, timeout=self.timeout)
            out = stage.transform(
                DataFrame({"query": np.array(self.search_terms,
                                             dtype=object)}))
            terms: List[str] = []
            images: List[Any] = []
            for term, imgs in zip(out["query"], out["result"]):
                for img in imgs or []:
                    terms.append(str(term))
                    images.append(img)
            if not terms:
                # empty page != failed page: exhaustion is only when every
                # term came back empty WITHOUT error. Any errored term on a
                # zero-row page means remaining pages may exist — raise
                # rather than silently dropping them (partial outages
                # previously masqueraded as end-of-stream).
                errs = [e for e in out[stage.error_col] if e is not None]
                if errs:
                    raise IOError(
                        f"image-search batch failed for {len(errs)}/"
                        f"{len(self.search_terms)} terms at offset "
                        f"{self._offset}: {errs[0]}")
                return
            self._offset += self.imgs_per_batch
            yielded += 1
            yield DataFrame({"search_term": np.array(terms, dtype=object),
                             "image": obj_col(images)})


def _post_batches(url: str, payloads: List[Any],
                  headers: Optional[Dict[str, str]] = None,
                  concurrency: int = 2,
                  timeout: float = 30.0) -> List[Dict[str, Any]]:
    """POST each payload (policy-driven: jittered retries with budget +
    per-host circuit breaking); returns the per-batch error dicts shared
    by the batch writers."""
    from mmlspark_tpu.core.resilience import RetryPolicy
    from mmlspark_tpu.io.http import HTTPClient

    reqs = [HTTPRequestData.post_json(url, p, headers) for p in payloads]
    client = HTTPClient(concurrency=concurrency, timeout=timeout,
                        policy=RetryPolicy(), breakers=True)
    try:
        resps = client.send(reqs)
    finally:
        client.close()
    return [{"batch": i, "status_code": getattr(r, "status_code", 0),
             "reason": getattr(r, "reason", "no response")}
            for i, r in enumerate(resps)
            if r is None or not (200 <= r.status_code < 300)]


class AzureSearchWriter:
    """Batch-POST rows as index actions (parity: `AzureSearch.scala:81,143`
    — rows wrapped as ``{"value": [{"@search.action": ...}, ...]}``)."""

    def __init__(self, url: str, action: str = "mergeOrUpload",
                 key: Optional[str] = None, batch_size: int = 100,
                 concurrency: int = 2, timeout: float = 30.0):
        self.url = url
        self.action = action
        self.key = key
        self.batch_size = int(batch_size)
        self.concurrency = concurrency
        self.timeout = timeout

    def write(self, df: DataFrame) -> List[Dict[str, Any]]:
        from mmlspark_tpu.core.serialize import _jsonify
        headers = {"Content-Type": "application/json"}
        if self.key:
            headers["api-key"] = self.key
        rows = [dict(_jsonify(row), **{"@search.action": self.action})
                for row in df.rows()]
        payloads = [{"value": rows[s:s + self.batch_size]}
                    for s in range(0, len(rows), self.batch_size)]
        return _post_batches(self.url, payloads, headers,
                             self.concurrency, self.timeout)


class DetectAnomalies(CognitiveServiceBase):
    """Series-in, anomalies-out (parity: `AnamolyDetection.scala:118`).

    The input column holds ``[{"timestamp": ..., "value": ...}, ...]``
    series per row; the request wraps it with granularity.
    """

    series_col = Param("series", "column of timestamp/value series")
    granularity = Param("daily", "series granularity")

    def _input_column(self) -> str:
        return self.series_col

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        if isinstance(value, np.ndarray):
            value = value.tolist()
        return HTTPRequestData.post_json(
            self.url, {"series": list(value),
                       "granularity": self.granularity}, self._headers())


class PowerBIWriter:
    """POST frame rows to a REST dataset endpoint in batches.

    Parity: `PowerBIWriter.scala:25` — rows serialized as a JSON array per
    batch with the advanced retry handler (throttling-aware).
    """

    def __init__(self, url: str, batch_size: int = 100,
                 concurrency: int = 2, timeout: float = 30.0):
        self.url = url
        self.batch_size = int(batch_size)
        self.concurrency = concurrency
        self.timeout = timeout

    def write(self, df: DataFrame) -> List[Dict[str, Any]]:
        """Send all rows; returns a list of per-batch error dicts (empty
        when everything succeeded)."""
        from mmlspark_tpu.core.serialize import _jsonify

        rows = [_jsonify(row) for row in df.rows()]
        payloads = [rows[s:s + self.batch_size]
                    for s in range(0, len(rows), self.batch_size)]
        return _post_batches(self.url, payloads, None,
                             self.concurrency, self.timeout)
