"""Cognitive-service-style transformers + PowerBI-style writer.

Capability parity with the reference's Cognitive Services layer
(`io/http/src/main/scala/CognitiveServiceBase.scala:25-241`,
`services/TextAnalytics.scala:184-248`, `services/ComputerVision.scala:180-474`,
`services/AnamolyDetection.scala:118,131`) and the PowerBI writer
(`io/powerbi/src/main/scala/PowerBIWriter.scala:25`). Per the build plan
(SURVEY §7) the full ~25-transformer Azure catalog is out of scope; this
provides the generic service base plus representative bindings as the
capability proof. Every stage takes an explicit ``url`` so they run
against any compatible endpoint (tests use localhost).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, obj_col
from mmlspark_tpu.core.params import Param, HasOutputCol, in_range
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.io.http import (
    CustomInputParser, HTTPRequestData, JSONOutputParser,
    SimpleHTTPTransformer,
)


class CognitiveServiceBase(Transformer, HasOutputCol):
    """Shared plumbing: build a JSON request per row, send, parse.

    Parity: `CognitiveServiceBase.scala:25-241` (HasServiceParams /
    subscription key header / SimpleHTTPTransformer internals).
    """

    url = Param(None, "service endpoint", ptype=str)
    subscription_key = Param(None, "subscription key header value")
    concurrency = Param(4, "max in-flight requests", in_range(lo=1))
    timeout = Param(60.0, "request timeout, s", in_range(lo=0.0))
    error_col = Param("error", "failed-request info column")
    output_col = Param("result", "parsed output column")

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.subscription_key:
            h["Ocp-Apim-Subscription-Key"] = self.subscription_key
        return h

    def _make_request(self, value: Any) -> Optional[HTTPRequestData]:
        """Row value -> request; override per service."""
        raise NotImplementedError

    def _input_column(self) -> str:
        raise NotImplementedError

    def _output_parser(self) -> Transformer:
        return JSONOutputParser()

    def transform(self, df: DataFrame) -> DataFrame:
        inner = SimpleHTTPTransformer(
            input_col=self._input_column(), output_col=self.output_col,
            input_parser=CustomInputParser(udf=self._make_request),
            output_parser=self._output_parser(),
            error_col=self.error_col, concurrency=self.concurrency,
            timeout=self.timeout)
        return inner.transform(df)


class _TextAnalyticsBase(CognitiveServiceBase):
    """Documents-array protocol shared by the text services.

    Parity: TextAnalyticsBase (`TextAnalytics.scala`): rows become
    ``{"documents": [{"id", "text", "language"?}]}`` requests.
    """

    text_col = Param("text", "input text column")
    language = Param(None, "language hint")

    def _input_column(self) -> str:
        return self.text_col

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        doc: Dict[str, Any] = {"id": "0", "text": str(value)}
        if self.language:
            doc["language"] = self.language
        return HTTPRequestData.post_json(
            self.url, {"documents": [doc]}, self._headers())

    def _output_parser(self) -> Transformer:
        return JSONOutputParser(data_field="documents")


class TextSentiment(_TextAnalyticsBase):
    """Parity: `TextAnalytics.scala:184` (TextSentiment)."""


class LanguageDetector(_TextAnalyticsBase):
    """Parity: `TextAnalytics.scala` LanguageDetector."""


class EntityDetector(_TextAnalyticsBase):
    """Parity: `TextAnalytics.scala` EntityDetector."""


class NER(_TextAnalyticsBase):
    """Parity: `TextAnalytics.scala` NER."""


class KeyPhraseExtractor(_TextAnalyticsBase):
    """Parity: `TextAnalytics.scala` KeyPhraseExtractor."""


class _ImageServiceBase(CognitiveServiceBase):
    """Image-url protocol shared by the vision services."""

    image_url_col = Param("image_url", "column of image URLs")

    def _input_column(self) -> str:
        return self.image_url_col

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        return HTTPRequestData.post_json(
            self.url, {"url": str(value)}, self._headers())


class AnalyzeImage(_ImageServiceBase):
    """Parity: `ComputerVision.scala` AnalyzeImage."""


class OCR(_ImageServiceBase):
    """Parity: `ComputerVision.scala` OCR."""


class DescribeImage(_ImageServiceBase):
    """Parity: `ComputerVision.scala` DescribeImage."""


class TagImage(_ImageServiceBase):
    """Parity: `ComputerVision.scala` TagImage."""


class DetectAnomalies(CognitiveServiceBase):
    """Series-in, anomalies-out (parity: `AnamolyDetection.scala:118`).

    The input column holds ``[{"timestamp": ..., "value": ...}, ...]``
    series per row; the request wraps it with granularity.
    """

    series_col = Param("series", "column of timestamp/value series")
    granularity = Param("daily", "series granularity")

    def _input_column(self) -> str:
        return self.series_col

    def _make_request(self, value) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        if isinstance(value, np.ndarray):
            value = value.tolist()
        return HTTPRequestData.post_json(
            self.url, {"series": list(value),
                       "granularity": self.granularity}, self._headers())


class PowerBIWriter:
    """POST frame rows to a REST dataset endpoint in batches.

    Parity: `PowerBIWriter.scala:25` — rows serialized as a JSON array per
    batch with the advanced retry handler (throttling-aware).
    """

    def __init__(self, url: str, batch_size: int = 100,
                 concurrency: int = 2, timeout: float = 30.0):
        self.url = url
        self.batch_size = int(batch_size)
        self.concurrency = concurrency
        self.timeout = timeout

    def write(self, df: DataFrame) -> List[Dict[str, Any]]:
        """Send all rows; returns a list of per-batch error dicts (empty
        when everything succeeded)."""
        from mmlspark_tpu.core.serialize import _jsonify
        from mmlspark_tpu.io.http import HTTPClient, advanced_handler

        reqs = []
        rows = [_jsonify(row) for row in df.rows()]
        for start in range(0, len(rows), self.batch_size):
            reqs.append(HTTPRequestData.post_json(
                self.url, rows[start:start + self.batch_size]))
        client = HTTPClient(concurrency=self.concurrency,
                            timeout=self.timeout, handler=advanced_handler)
        try:
            resps = client.send(reqs)
        finally:
            client.close()
        errors = []
        for i, r in enumerate(resps):
            if r is None or not (200 <= r.status_code < 300):
                errors.append({"batch": i,
                               "status_code": getattr(r, "status_code", 0),
                               "reason": getattr(r, "reason", "no response")})
        return errors
