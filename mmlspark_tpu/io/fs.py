"""Filesystem abstraction: local paths plus ``gs://``-style URLs.

The reference reads wasb/HDFS everywhere through Hadoop's filesystem
layer (`core/hadoop/src/main/scala/HadoopUtils.scala`; the HDFS model
repo in `ModelDownloader.scala`). The TPU-pod analogue is fsspec: any
``protocol://`` path (``gs://``, ``s3://``, ``memory://``, ...) is
routed through the matching fsspec filesystem, while plain paths keep
using the local OS calls. Callers never touch fsspec directly — these
helpers are the single seam.

fsspec is baked into the image; if it's ever absent, remote URLs raise
with a clear message and local paths keep working.
"""

from __future__ import annotations

import fnmatch
import os
import posixpath
from typing import Iterator, List, Optional, Tuple


def is_remote(path: str) -> bool:
    """True for ``protocol://`` URLs that should go through fsspec."""
    if "://" not in path:
        return False
    proto = path.split("://", 1)[0]
    return proto not in ("file",)


def _strip_file(path: str) -> str:
    return path[len("file://"):] if path.startswith("file://") else path


def get_fs(path: str) -> Tuple["object", str]:
    """(fsspec filesystem, protocol-stripped path) for a remote URL."""
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover - fsspec is in the image
        raise ImportError(
            f"remote path {path!r} needs fsspec, which is unavailable") from e
    return fsspec.core.url_to_fs(path)


def join(base: str, *parts: str) -> str:
    """Path join that keeps URL separators for remote bases."""
    if is_remote(base):
        return posixpath.join(base, *parts)
    return os.path.join(base, *parts)


def isabs(path: str) -> bool:
    return is_remote(path) or os.path.isabs(_strip_file(path))


def exists(path: str) -> bool:
    if is_remote(path):
        fs, p = get_fs(path)
        return fs.exists(p)
    return os.path.exists(_strip_file(path))


def isfile(path: str) -> bool:
    if is_remote(path):
        fs, p = get_fs(path)
        return fs.isfile(p)
    return os.path.isfile(_strip_file(path))


def makedirs(path: str) -> None:
    if is_remote(path):
        fs, p = get_fs(path)
        fs.makedirs(p, exist_ok=True)
    else:
        os.makedirs(_strip_file(path), exist_ok=True)


def open_file(path: str, mode: str = "rb"):
    if is_remote(path):
        fs, p = get_fs(path)
        return fs.open(p, mode)
    return open(_strip_file(path), mode)


def read_bytes(path: str) -> bytes:
    with open_file(path, "rb") as f:
        return f.read()


def write_bytes(path: str, data: bytes) -> None:
    with open_file(path, "wb") as f:
        f.write(data)


def read_text(path: str) -> str:
    # Always read binary + decode UTF-8 so read_text/write_text are
    # symmetric regardless of the host locale.
    return read_bytes(path).decode("utf-8")


def write_text(path: str, text: str) -> None:
    write_bytes(path, text.encode())


def rm_tree(path: str) -> None:
    if is_remote(path):
        fs, p = get_fs(path)
        if fs.exists(p):
            fs.rm(p, recursive=True)
    else:
        import shutil
        shutil.rmtree(_strip_file(path), ignore_errors=True)


def find_files(path: str, recursive: bool = True,
               pattern: Optional[str] = None) -> Iterator[str]:
    """Matching files under ``path`` in global sorted order, as openable
    paths (remote results keep their protocol prefix)."""
    if is_remote(path):
        fs, p = get_fs(path)
        if fs.isfile(p):
            yield path
            return
        out: List[str] = []
        if recursive:
            names = fs.find(p)
        else:
            # one listing with types — per-entry isfile() would cost a
            # metadata round-trip each on object stores
            names = [e["name"] for e in fs.ls(p, detail=True)
                     if e.get("type") == "file"]
        for full in names:
            base = full.rsplit("/", 1)[-1]
            if pattern is None or fnmatch.fnmatch(base, pattern):
                out.append(fs.unstrip_protocol(full))
        yield from sorted(out)
        return

    path = _strip_file(path)
    if os.path.isfile(path):
        yield path
        return
    out = []
    if recursive:
        for root, _, files in os.walk(path):
            for f in files:
                if pattern is None or fnmatch.fnmatch(f, pattern):
                    out.append(os.path.join(root, f))
    else:
        for f in os.listdir(path):
            full = os.path.join(path, f)
            if os.path.isfile(full) and (pattern is None
                                         or fnmatch.fnmatch(f, pattern)):
                out.append(full)
    yield from sorted(out)


def walk_rel_files(path: str) -> Iterator[Tuple[str, str]]:
    """(relative posix path, openable full path) for every file under a
    directory tree, sorted — the traversal order contract used for
    directory hashing."""
    if is_remote(path):
        fs, p = get_fs(path)
        root = p.rstrip("/")
        for full in sorted(fs.find(root)):
            rel = full[len(root):].lstrip("/")
            yield rel, fs.unstrip_protocol(full)
    else:
        path = _strip_file(path)
        entries = []
        for root, _, files in os.walk(path):
            for f in files:
                full = os.path.join(root, f)
                rel = os.path.relpath(full, path).replace(os.sep, "/")
                entries.append((rel, full))
        yield from sorted(entries)


def copy_tree(src: str, dst: str) -> None:
    """Copy a directory tree across any local/remote combination."""
    if not is_remote(src) and not is_remote(dst):
        import shutil
        shutil.copytree(_strip_file(src), _strip_file(dst))
        return
    for rel, full in walk_rel_files(src):
        target = join(dst, rel)
        parent = target.rsplit("/", 1)[0]
        makedirs(parent)
        write_bytes(target, read_bytes(full))
