"""Whole-file binary reader: files -> (path, bytes) rows.

Capability parity with the reference's custom Hadoop FileFormat
(`io/binary/src/main/scala/BinaryFileFormat.scala:114`,
`BinaryRecordReader.scala:34`): read a directory tree as rows of
``(path, bytes)``, with zip-archive inspection (members become rows) and
record-level subsampling — here against the local/NFS filesystem that
backs TPU VMs.
"""

from __future__ import annotations

import fnmatch
import io as _io
import os
import random
import zipfile
from typing import Iterator, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame

PATH_COL = "path"
BYTES_COL = "bytes"


def _iter_files(path: str, recursive: bool, pattern: Optional[str]) -> Iterator[str]:
    """Matching files in global sorted-path order (same as the native reader)."""
    if os.path.isfile(path):
        yield path
        return
    out: List[str] = []
    if recursive:
        for root, _, files in os.walk(path):
            for f in files:
                if pattern is None or fnmatch.fnmatch(f, pattern):
                    out.append(os.path.join(root, f))
    else:
        for f in os.listdir(path):
            full = os.path.join(path, f)
            if os.path.isfile(full) and (pattern is None or fnmatch.fnmatch(f, pattern)):
                out.append(full)
    yield from sorted(out)


def read_binary_files(path: str,
                      recursive: bool = True,
                      pattern: Optional[str] = None,
                      sample_ratio: float = 1.0,
                      inspect_zip: bool = True,
                      seed: int = 0,
                      engine: str = "auto") -> DataFrame:
    """Read files under ``path`` as a frame with ``path``/``bytes`` columns.

    Zip archives are expanded into one row per member, with paths like
    ``archive.zip/member`` (parity: zip inspection + subsampling at the
    record-reader level, `BinaryRecordReader.scala:34`).

    ``engine``: ``native`` uses the C++ prefetching reader
    (``native/binary_reader.cpp``, threads off the GIL), ``python`` the
    in-process fallback, ``auto`` prefers native when it builds. Both
    deliver records in sorted-path file order; the two engines draw
    different RNG streams for ``sample_ratio``, so sampled *subsets*
    (not semantics) differ between them.
    """
    if engine not in ("auto", "native", "python"):
        raise ValueError(f"unknown engine {engine!r}")
    if not os.path.exists(path):
        # both engines would otherwise silently yield an empty frame
        # (os.walk and the native scanner both swallow missing roots)
        raise FileNotFoundError(path)
    use_native = False
    if engine in ("auto", "native"):
        from mmlspark_tpu.native import native_available
        use_native = native_available()
        if engine == "native" and not use_native:
            raise RuntimeError("native reader unavailable (no g++/zlib?)")

    paths: List[str] = []
    blobs: List[bytes] = []
    if use_native:
        from mmlspark_tpu.native import native_read_records
        for p, data in native_read_records(
                path, recursive=recursive, pattern=pattern,
                sample_ratio=sample_ratio, inspect_zip=inspect_zip,
                seed=seed):
            paths.append(p)
            blobs.append(data)
    else:
        rng = random.Random(seed)

        def emit(p: str, data: bytes) -> None:
            if sample_ratio >= 1.0 or rng.random() < sample_ratio:
                paths.append(p)
                blobs.append(data)

        for fp in _iter_files(path, recursive, pattern):
            if inspect_zip and fp.lower().endswith(".zip"):
                with zipfile.ZipFile(fp) as zf:
                    for name in zf.namelist():
                        if name.endswith("/"):
                            continue
                        emit(f"{fp}/{name}", zf.read(name))
            else:
                with open(fp, "rb") as f:
                    emit(fp, f.read())

    return DataFrame({
        PATH_COL: np.array(paths, dtype=object),
        BYTES_COL: np.array(blobs, dtype=object),
    })
