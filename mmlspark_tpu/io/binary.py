"""Whole-file binary reader: files -> (path, bytes) rows.

Capability parity with the reference's custom Hadoop FileFormat
(`io/binary/src/main/scala/BinaryFileFormat.scala:114`,
`BinaryRecordReader.scala:34`): read a directory tree as rows of
``(path, bytes)``, with zip-archive inspection (members become rows) and
record-level subsampling — here against the local/NFS filesystem that
backs TPU VMs.
"""

from __future__ import annotations

import fnmatch
import io as _io
import os
import random
import zipfile
from typing import Iterator, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame

PATH_COL = "path"
BYTES_COL = "bytes"


def _iter_files(path: str, recursive: bool, pattern: Optional[str]) -> Iterator[str]:
    if os.path.isfile(path):
        yield path
        return
    if recursive:
        for root, _, files in os.walk(path):
            for f in sorted(files):
                if pattern is None or fnmatch.fnmatch(f, pattern):
                    yield os.path.join(root, f)
    else:
        for f in sorted(os.listdir(path)):
            full = os.path.join(path, f)
            if os.path.isfile(full) and (pattern is None or fnmatch.fnmatch(f, pattern)):
                yield full


def read_binary_files(path: str,
                      recursive: bool = True,
                      pattern: Optional[str] = None,
                      sample_ratio: float = 1.0,
                      inspect_zip: bool = True,
                      seed: int = 0) -> DataFrame:
    """Read files under ``path`` as a frame with ``path``/``bytes`` columns.

    Zip archives are expanded into one row per member, with paths like
    ``archive.zip/member`` (parity: zip inspection + subsampling at the
    record-reader level, `BinaryRecordReader.scala:34`).
    """
    rng = random.Random(seed)
    paths: List[str] = []
    blobs: List[bytes] = []

    def emit(p: str, data: bytes) -> None:
        if sample_ratio >= 1.0 or rng.random() < sample_ratio:
            paths.append(p)
            blobs.append(data)

    for fp in _iter_files(path, recursive, pattern):
        if inspect_zip and fp.lower().endswith(".zip"):
            with zipfile.ZipFile(fp) as zf:
                for name in zf.namelist():
                    if name.endswith("/"):
                        continue
                    emit(f"{fp}/{name}", zf.read(name))
        else:
            with open(fp, "rb") as f:
                emit(fp, f.read())

    return DataFrame({
        PATH_COL: np.array(paths, dtype=object),
        BYTES_COL: np.array(blobs, dtype=object),
    })
