"""Whole-file binary reader: files -> (path, bytes) rows.

Capability parity with the reference's custom Hadoop FileFormat
(`io/binary/src/main/scala/BinaryFileFormat.scala:114`,
`BinaryRecordReader.scala:34`): read a directory tree as rows of
``(path, bytes)``, with zip-archive inspection (members become rows) and
record-level subsampling — against the local/NFS filesystem that backs
TPU VMs, or any ``gs://``-style remote URL through the fsspec layer
(`io/fs.py`; parity: the reference reads wasb/HDFS via `HadoopUtils`).
"""

from __future__ import annotations

import random
import zipfile
from typing import List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame

PATH_COL = "path"
BYTES_COL = "bytes"


def read_binary_files(path: str,
                      recursive: bool = True,
                      pattern: Optional[str] = None,
                      sample_ratio: float = 1.0,
                      inspect_zip: bool = True,
                      seed: int = 0,
                      engine: str = "auto") -> DataFrame:
    """Read files under ``path`` as a frame with ``path``/``bytes`` columns.

    Zip archives are expanded into one row per member, with paths like
    ``archive.zip/member`` (parity: zip inspection + subsampling at the
    record-reader level, `BinaryRecordReader.scala:34`).

    ``engine``: ``native`` uses the C++ prefetching reader
    (``native/binary_reader.cpp``, threads off the GIL), ``python`` the
    in-process fallback, ``auto`` prefers native when it builds. Both
    deliver records in sorted-path file order; the two engines draw
    different RNG streams for ``sample_ratio``, so sampled *subsets*
    (not semantics) differ between them.
    """
    if engine not in ("auto", "native", "python"):
        raise ValueError(f"unknown engine {engine!r}")
    from mmlspark_tpu.io import fs
    if not fs.exists(path):
        # both engines would otherwise silently yield an empty frame
        # (os.walk and the native scanner both swallow missing roots)
        raise FileNotFoundError(path)
    use_native = False
    if engine in ("auto", "native"):
        if fs.is_remote(path):
            # the C++ reader only scans the local filesystem
            if engine == "native":
                raise ValueError(
                    f"engine='native' cannot read remote path {path!r}")
        else:
            from mmlspark_tpu.native import native_available
            use_native = native_available()
            if engine == "native" and not use_native:
                raise RuntimeError(
                    "native reader unavailable (no g++/zlib?)")

    paths: List[str] = []
    blobs: List[bytes] = []
    if use_native:
        from mmlspark_tpu.native import native_read_records
        for p, data in native_read_records(
                path, recursive=recursive, pattern=pattern,
                sample_ratio=sample_ratio, inspect_zip=inspect_zip,
                seed=seed):
            paths.append(p)
            blobs.append(data)
    else:
        rng = random.Random(seed)

        def emit(p: str, data: bytes) -> None:
            if sample_ratio >= 1.0 or rng.random() < sample_ratio:
                paths.append(p)
                blobs.append(data)

        for fp in fs.find_files(path, recursive, pattern):
            if inspect_zip and fp.lower().endswith(".zip"):
                # both local and fsspec file objects are seekable
                with fs.open_file(fp, "rb") as fh, \
                        zipfile.ZipFile(fh) as zf:
                    for name in zf.namelist():
                        if name.endswith("/"):
                            continue
                        emit(f"{fp}/{name}", zf.read(name))
            else:
                emit(fp, fs.read_bytes(fp))

    return DataFrame({
        PATH_COL: np.array(paths, dtype=object),
        BYTES_COL: np.array(blobs, dtype=object),
    })
