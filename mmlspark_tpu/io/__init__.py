from mmlspark_tpu.io.binary import read_binary_files
from mmlspark_tpu.io.images import read_images, decode_image, encode_image
from mmlspark_tpu.io.streaming import FileStreamSource
from mmlspark_tpu.io.http import (
    HTTPRequestData, HTTPResponseData, HTTPClient, HTTPTransformer,
    SimpleHTTPTransformer, JSONInputParser, JSONOutputParser,
    StringOutputParser, CustomInputParser, CustomOutputParser,
    basic_handler, advanced_handler,
)

__all__ = [
    "FileStreamSource",
    "read_binary_files", "read_images", "decode_image", "encode_image",
    "HTTPRequestData", "HTTPResponseData", "HTTPClient", "HTTPTransformer",
    "SimpleHTTPTransformer", "JSONInputParser", "JSONOutputParser",
    "StringOutputParser", "CustomInputParser", "CustomOutputParser",
    "basic_handler", "advanced_handler",
]
