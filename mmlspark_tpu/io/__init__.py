from mmlspark_tpu.io.binary import read_binary_files
from mmlspark_tpu.io.images import read_images, decode_image, encode_image

__all__ = ["read_binary_files", "read_images", "decode_image", "encode_image"]
