"""Streaming file source: watch a directory, emit new files as frames.

Parity: the reference's binary/image FileFormats are structured-streaming
capable (`BinaryFileFormat.scala:114` is used by ``readStream`` in the
serving docs), with ``checkpointLocation`` giving resumable progress.
Here the same capability over the local/NFS filesystem that backs TPU
VMs: a poller tracks (path, mtime, size) of matching files, yields each
batch of newly-arrived files as a ``(path, bytes)`` DataFrame (through
the native reader when available), and optionally journals processed
paths so a restarted stream resumes where it left off.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zipfile
import zlib
from typing import Callable, Iterator, Optional, Set

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io.binary import read_binary_files


class FileStreamSource:
    """Poll ``path`` for new files; yield them as frames.

    ``checkpoint_location``: optional JSON journal of processed files —
    the ``checkpointLocation`` parity (`docs/mmlspark-serving.md:52`);
    a fresh instance pointed at the same journal skips old files.
    """

    def __init__(self, path: str, pattern: Optional[str] = None,
                 poll_interval: float = 0.5,
                 inspect_zip: bool = True,
                 engine: str = "auto",
                 checkpoint_location: Optional[str] = None):
        self.path = path
        self.pattern = pattern
        self.poll_interval = poll_interval
        self.inspect_zip = inspect_zip
        self.engine = engine
        self.checkpoint_location = checkpoint_location
        self._seen: Set[str] = set()
        self._planned: Set[str] = set()   # engine-mode plan/ack window
        self._read_retry: Set[str] = set()  # transient engine-read fails
        self._fail_counts: dict = {}
        self._quarantined: Set[str] = set()
        self.max_read_failures = 3
        self._stop = threading.Event()
        if checkpoint_location and os.path.exists(checkpoint_location):
            with open(checkpoint_location) as f:
                self._seen = set(json.load(f))
            # dead entries may have accumulated across earlier runs
            # (pre-compaction journals): drop them on the way in
            self._seen = self._compacted(self._seen)

    def stop(self) -> None:
        self._stop.set()

    @staticmethod
    def _key_path(key: str) -> str:
        """The path component of a ``path:mtime_ns:size`` journal key
        (paths may themselves contain colons — split from the right)."""
        return key.rsplit(":", 2)[0]

    @staticmethod
    def _path_gone(path: str) -> bool:
        """True only for GENUINE deletion: a transient stat failure
        (NFS blip, momentary EACCES) must never evict a live file's
        journal key — the next scan would re-offer it as new data."""
        try:
            os.stat(path)
            return False
        except (FileNotFoundError, NotADirectoryError):
            return True
        except OSError:
            return False

    def _compacted(self, keys: Set[str]) -> Set[str]:
        """Drop keys whose file no longer exists on disk: resume
        semantics only need keys a future scan could re-offer, and
        without compaction the set (and its JSON journal) grows by one
        entry per file FOREVER under rolling producers."""
        return {k for k in keys if not self._path_gone(self._key_path(k))}

    #: checkpoints between compaction passes on LARGE journals
    #: (compaction stats every journal key — fine occasionally, or on
    #: small sets, but not per committed batch at thousands of keys)
    _COMPACT_EVERY = 16
    _COMPACT_INLINE_MAX = 256

    def _checkpoint(self) -> None:
        if not self.checkpoint_location:
            return
        self._ckpt_count = getattr(self, "_ckpt_count", 0) + 1
        if len(self._seen) <= self._COMPACT_INLINE_MAX \
                or self._ckpt_count % self._COMPACT_EVERY == 0:
            self._seen = self._compacted(self._seen)
        tmp = f"{self.checkpoint_location}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(sorted(self._seen), f)
        os.replace(tmp, self.checkpoint_location)

    def _scan(self):
        import fnmatch
        out = []
        for root, _, files in os.walk(self.path):
            for name in files:
                if self.pattern and not fnmatch.fnmatch(name, self.pattern):
                    continue
                full = os.path.join(root, name)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                key = f"{full}:{st.st_mtime_ns}:{st.st_size}"
                if key not in self._seen \
                        and key not in self._quarantined \
                        and key not in self._planned:
                    out.append((full, key))
        return out

    def batches(self, max_batches: Optional[int] = None,
                idle_timeout: Optional[float] = None) -> Iterator[DataFrame]:
        """Yield a frame per poll cycle that found new files.

        ``idle_timeout``: stop after this many seconds without new files
        (None = run until :meth:`stop`). ``max_batches`` bounds the
        number of yielded frames.
        """
        yielded = 0
        last_new = time.monotonic()
        while not self._stop.is_set():
            fresh = self._scan()
            frames, keys = [], []
            for full, key in fresh:
                try:
                    frames.append(read_binary_files(
                        full, inspect_zip=self.inspect_zip,
                        engine=self.engine))
                except OSError:
                    # vanished between scan and read (write-then-move
                    # producers) or transient I/O (EACCES/EIO while a
                    # producer settles): not counted, re-examined next
                    # poll — the sleep below keeps this from spinning
                    continue
                except (zipfile.BadZipFile, zlib.error) as exc:
                    # corrupt content. Retried a few polls — a partial
                    # write heals once complete — then quarantined IN
                    # MEMORY so one bad file can't wedge the stream.
                    # Not journaled: a restart retries it.
                    n = self._fail_counts.get(key, 0) + 1
                    self._fail_counts[key] = n
                    if n >= self.max_read_failures:
                        from mmlspark_tpu.core.logs import get_logger
                        get_logger("io.streaming").warning(
                            "quarantining %s after %d failed reads: %s",
                            full, n, exc)
                        self._quarantined.add(key)
                    continue
                # the file may have been mid-write at scan time (stat
                # caught size 0 / an old mtime, the read then saw the
                # settled content): journaling the STALE key would make
                # the next poll re-process the same file under its
                # settled key — a duplicate batch. A file whose stat
                # CHANGED across the read is dropped and re-examined
                # next poll; a file that VANISHED is delivered as read
                # (read-then-archive producers delete immediately, and
                # the gone file can never be re-examined — dropping it
                # would be silent data loss).
                try:
                    st = os.stat(full)
                    settled = f"{full}:{st.st_mtime_ns}:{st.st_size}"
                except OSError:
                    settled = key     # vanished: the read is final
                if settled != key:
                    frames.pop()      # drop the unverified read
                    continue
                keys.append(key)
            # drop stale fail counts (rewritten files get fresh keys every
            # poll; without pruning the dict grows without bound)
            live = {key for _, key in fresh}
            self._fail_counts = {k: v for k, v in self._fail_counts.items()
                                 if k in live and k not in self._quarantined}
            if frames:
                batch = DataFrame.concat(frames) if len(frames) > 1 \
                    else frames[0]
                yield batch
                # journal only AFTER the consumer finished the batch (it
                # asked for the next one): at-least-once on crash, like
                # Spark's checkpointLocation
                self._seen.update(keys)
                self._checkpoint()
                yielded += 1
                last_new = time.monotonic()
                if max_batches is not None and yielded >= max_batches:
                    return
                continue
            # no batch this cycle (nothing new, or every read failed):
            # honor idle_timeout, then wait out the poll interval
            if (idle_timeout is not None
                    and time.monotonic() - last_new > idle_timeout):
                return
            self._stop.wait(self.poll_interval)

    # -- micro-batch engine source protocol ---------------------------------
    # (mmlspark_tpu.streaming.engine.StreamingQuery: plan/read/ack.
    # ``batches()``/``foreach_batch`` above remain the standalone
    # poller surface; the engine drives these instead, with ITS offset
    # log providing crash replay and this source's journal providing
    # the committed cursor.)

    def plan(self, limit_rows: Optional[int] = None) -> Optional[dict]:
        """Claim newly-arrived files as one batch descriptor. This
        source's planning unit is the FILE (row counts are unknowable
        before reading), so the engine's adaptive budget bounds files
        per batch, not rows — its rate adaptation still converges, in
        file units, off the same sink-latency signal. Claimed files
        stay out of later plans until :meth:`ack` journals them (the
        engine replays unacked plans from its own offset log after a
        crash)."""
        fresh = self._scan()
        if limit_rows:
            fresh = fresh[:max(int(limit_rows), 1)]
        if not fresh:
            return None
        self._planned.update(key for _, key in fresh)
        return {"files": [[full, key] for full, key in fresh]}

    def read(self, meta: dict) -> DataFrame:
        """Materialize a planned batch. Deterministic for settled
        files. Failure classes mirror :meth:`batches`: a VANISHED file
        (FileNotFoundError) is skipped for good — its bytes are
        unrecoverable; a TRANSIENT error (NFS blip, EACCES while a
        producer settles) or corrupt content marks the key for
        re-offer — :meth:`ack` will NOT journal it, so a later plan
        retries it, with :attr:`max_read_failures` bounding retries
        before quarantine (one bad file can never wedge the stream OR
        silently lose a healthy one)."""
        from mmlspark_tpu.core.logs import get_logger
        frames = []
        for full, key in meta["files"]:
            try:
                frames.append(read_binary_files(
                    full, inspect_zip=self.inspect_zip,
                    engine=self.engine))
                self._fail_counts.pop(key, None)
            except FileNotFoundError as exc:
                get_logger("io.streaming").warning(
                    "planned file %s vanished before read (%s); its "
                    "rows are lost", full, exc)
            except (OSError, zipfile.BadZipFile, zlib.error) as exc:
                n = self._fail_counts.get(key, 0) + 1
                self._fail_counts[key] = n
                if n >= self.max_read_failures:
                    get_logger("io.streaming").warning(
                        "quarantining %s after %d failed reads: %s",
                        full, n, exc)
                    self._quarantined.add(key)
                    self._fail_counts.pop(key, None)
                else:
                    get_logger("io.streaming").warning(
                        "planned file %s unreadable at read time "
                        "(attempt %d/%d: %s); will re-offer", full, n,
                        self.max_read_failures, exc)
                    self._read_retry.add(key)
        if not frames:
            return DataFrame({})
        return DataFrame.concat(frames) if len(frames) > 1 else frames[0]

    def ack(self, meta: dict) -> None:
        """Journal a committed batch's files (idempotent — the engine
        re-acks committed offsets during recovery). Keys whose read
        failed transiently are released for re-planning instead of
        journaled — journaling an unread file would be silent data
        loss on the first I/O blip."""
        keys = [key for _, key in meta["files"]]
        # quarantined keys stay un-journaled too (in-memory only, like
        # the poller path: a restart retries them)
        self._seen.update(k for k in keys
                          if k not in self._read_retry
                          and k not in self._quarantined)
        self._planned.difference_update(keys)
        self._read_retry.difference_update(keys)
        self._checkpoint()

    def backlog(self) -> int:
        """Unplanned new-file count (the engine's lag gauge)."""
        return len(self._scan())

    def foreach_batch(self, fn: Callable[[DataFrame], None],
                      **kwargs) -> "ForeachBatchHandle":
        """Run :meth:`batches` on a daemon thread, calling ``fn`` per
        frame (the ``writeStream.foreachBatch`` shape).

        An exception from ``fn`` is TERMINAL for the stream, never
        silent: it is logged, counted, and surfaced on the returned
        handle (``handle.state == "failed"``, ``handle.error``) — the
        thread used to die quietly and the stream just stopped with no
        trace. The batch that failed is NOT journaled, so a restarted
        stream re-offers it (at-least-once, like every other batch).
        """
        handle = ForeachBatchHandle(self, fn, kwargs)
        handle.start()
        return handle


class ForeachBatchHandle(threading.Thread):
    """The ``foreach_batch`` daemon thread plus its terminal state
    (still a :class:`threading.Thread`, so existing ``join()`` callers
    keep working). ``state``: ``running`` -> ``terminated`` (source
    stopped / limits reached) | ``failed`` (``fn`` raised — see
    ``error``)."""

    def __init__(self, source: FileStreamSource, fn, kwargs):
        super().__init__(daemon=True, name="file-stream-foreach")
        self._source = source
        self._fn = fn
        self._kwargs = kwargs
        self.state = "running"
        self.error: "Optional[BaseException]" = None
        self.n_batches = 0
        self.n_errors = 0

    def status(self) -> dict:
        return {"state": self.state,
                "error": (f"{type(self.error).__name__}: {self.error}"
                          if self.error is not None else None),
                "n_batches": self.n_batches,
                "n_errors": self.n_errors}

    def run(self) -> None:
        from mmlspark_tpu.core.logs import get_logger
        try:
            for batch in self._source.batches(**self._kwargs):
                try:
                    self._fn(batch)
                except Exception as e:  # noqa: BLE001 — the consumer
                    # failed: count + log + terminal state, never a
                    # silently-dead daemon thread
                    self.n_errors += 1
                    self.error = e
                    self.state = "failed"
                    get_logger("io.streaming").error(
                        "foreach_batch consumer raised on batch %d; "
                        "stream stopped (batch not journaled — a "
                        "restart re-offers it): %s", self.n_batches + 1,
                        e, exc_info=True)
                    return
                self.n_batches += 1
            self.state = "terminated"
        except Exception as e:  # noqa: BLE001 — a source-side failure
            self.n_errors += 1
            self.error = e
            self.state = "failed"
            get_logger("io.streaming").error(
                "file stream poller failed: %s", e, exc_info=True)
