"""Streaming file source: watch a directory, emit new files as frames.

Parity: the reference's binary/image FileFormats are structured-streaming
capable (`BinaryFileFormat.scala:114` is used by ``readStream`` in the
serving docs), with ``checkpointLocation`` giving resumable progress.
Here the same capability over the local/NFS filesystem that backs TPU
VMs: a poller tracks (path, mtime, size) of matching files, yields each
batch of newly-arrived files as a ``(path, bytes)`` DataFrame (through
the native reader when available), and optionally journals processed
paths so a restarted stream resumes where it left off.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zipfile
import zlib
from typing import Callable, Iterator, Optional, Set

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io.binary import read_binary_files


class FileStreamSource:
    """Poll ``path`` for new files; yield them as frames.

    ``checkpoint_location``: optional JSON journal of processed files —
    the ``checkpointLocation`` parity (`docs/mmlspark-serving.md:52`);
    a fresh instance pointed at the same journal skips old files.
    """

    def __init__(self, path: str, pattern: Optional[str] = None,
                 poll_interval: float = 0.5,
                 inspect_zip: bool = True,
                 engine: str = "auto",
                 checkpoint_location: Optional[str] = None):
        self.path = path
        self.pattern = pattern
        self.poll_interval = poll_interval
        self.inspect_zip = inspect_zip
        self.engine = engine
        self.checkpoint_location = checkpoint_location
        self._seen: Set[str] = set()
        self._fail_counts: dict = {}
        self._quarantined: Set[str] = set()
        self.max_read_failures = 3
        self._stop = threading.Event()
        if checkpoint_location and os.path.exists(checkpoint_location):
            with open(checkpoint_location) as f:
                self._seen = set(json.load(f))

    def stop(self) -> None:
        self._stop.set()

    def _checkpoint(self) -> None:
        if not self.checkpoint_location:
            return
        tmp = f"{self.checkpoint_location}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(sorted(self._seen), f)
        os.replace(tmp, self.checkpoint_location)

    def _scan(self):
        import fnmatch
        out = []
        for root, _, files in os.walk(self.path):
            for name in files:
                if self.pattern and not fnmatch.fnmatch(name, self.pattern):
                    continue
                full = os.path.join(root, name)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                key = f"{full}:{st.st_mtime_ns}:{st.st_size}"
                if key not in self._seen and key not in self._quarantined:
                    out.append((full, key))
        return out

    def batches(self, max_batches: Optional[int] = None,
                idle_timeout: Optional[float] = None) -> Iterator[DataFrame]:
        """Yield a frame per poll cycle that found new files.

        ``idle_timeout``: stop after this many seconds without new files
        (None = run until :meth:`stop`). ``max_batches`` bounds the
        number of yielded frames.
        """
        yielded = 0
        last_new = time.monotonic()
        while not self._stop.is_set():
            fresh = self._scan()
            frames, keys = [], []
            for full, key in fresh:
                try:
                    frames.append(read_binary_files(
                        full, inspect_zip=self.inspect_zip,
                        engine=self.engine))
                except OSError:
                    # vanished between scan and read (write-then-move
                    # producers) or transient I/O (EACCES/EIO while a
                    # producer settles): not counted, re-examined next
                    # poll — the sleep below keeps this from spinning
                    continue
                except (zipfile.BadZipFile, zlib.error) as exc:
                    # corrupt content. Retried a few polls — a partial
                    # write heals once complete — then quarantined IN
                    # MEMORY so one bad file can't wedge the stream.
                    # Not journaled: a restart retries it.
                    n = self._fail_counts.get(key, 0) + 1
                    self._fail_counts[key] = n
                    if n >= self.max_read_failures:
                        from mmlspark_tpu.core.logs import get_logger
                        get_logger("io.streaming").warning(
                            "quarantining %s after %d failed reads: %s",
                            full, n, exc)
                        self._quarantined.add(key)
                    continue
                # the file may have been mid-write at scan time (stat
                # caught size 0 / an old mtime, the read then saw the
                # settled content): journaling the STALE key would make
                # the next poll re-process the same file under its
                # settled key — a duplicate batch. A file whose stat
                # CHANGED across the read is dropped and re-examined
                # next poll; a file that VANISHED is delivered as read
                # (read-then-archive producers delete immediately, and
                # the gone file can never be re-examined — dropping it
                # would be silent data loss).
                try:
                    st = os.stat(full)
                    settled = f"{full}:{st.st_mtime_ns}:{st.st_size}"
                except OSError:
                    settled = key     # vanished: the read is final
                if settled != key:
                    frames.pop()      # drop the unverified read
                    continue
                keys.append(key)
            # drop stale fail counts (rewritten files get fresh keys every
            # poll; without pruning the dict grows without bound)
            live = {key for _, key in fresh}
            self._fail_counts = {k: v for k, v in self._fail_counts.items()
                                 if k in live and k not in self._quarantined}
            if frames:
                batch = DataFrame.concat(frames) if len(frames) > 1 \
                    else frames[0]
                yield batch
                # journal only AFTER the consumer finished the batch (it
                # asked for the next one): at-least-once on crash, like
                # Spark's checkpointLocation
                self._seen.update(keys)
                self._checkpoint()
                yielded += 1
                last_new = time.monotonic()
                if max_batches is not None and yielded >= max_batches:
                    return
                continue
            # no batch this cycle (nothing new, or every read failed):
            # honor idle_timeout, then wait out the poll interval
            if (idle_timeout is not None
                    and time.monotonic() - last_new > idle_timeout):
                return
            self._stop.wait(self.poll_interval)

    def foreach_batch(self, fn: Callable[[DataFrame], None],
                      **kwargs) -> threading.Thread:
        """Run :meth:`batches` on a daemon thread, calling ``fn`` per
        frame (the ``writeStream.foreachBatch`` shape)."""
        def run():
            for batch in self.batches(**kwargs):
                fn(batch)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return t
