"""Image reading and host-side codecs.

Capability parity with the reference's image FileFormat + ImageUtils
(`io/image/src/main/scala/PatchedImageFileFormat.scala:23`,
`ImageUtils.scala:25`): read a directory of images into rows, decode to
arrays, with subsampling and zip support inherited from the binary reader.

Decode/encode run host-side (PIL); all subsequent compute happens on
device via :mod:`mmlspark_tpu.ops.image`. Framework convention is RGB HWC
uint8 (the reference stores OpenCV BGR; use ops.image.swap_rb for BGR
models).
"""

from __future__ import annotations

import io as _io
import os
from typing import Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io.binary import read_binary_files, PATH_COL, BYTES_COL

IMAGE_COL = "image"
IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")


def decode_image(data: bytes) -> Optional[np.ndarray]:
    """Decode encoded bytes to RGB HWC uint8; None if undecodable."""
    from PIL import Image
    try:
        with Image.open(_io.BytesIO(data)) as img:
            return np.asarray(img.convert("RGB"), dtype=np.uint8)
    except Exception:
        return None


def encode_image(array: np.ndarray, format: str = "PNG") -> bytes:
    from PIL import Image
    arr = np.asarray(array)
    if arr.dtype != np.uint8:
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    if arr.ndim == 3 and arr.shape[-1] == 1:
        arr = arr[..., 0]
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format=format)
    return buf.getvalue()


def read_images(path: str,
                recursive: bool = True,
                sample_ratio: float = 1.0,
                inspect_zip: bool = True,
                drop_invalid: bool = True,
                seed: int = 0) -> DataFrame:
    """Read images under ``path`` into ``path``/``image`` columns.

    ``image`` is an object column of RGB HWC uint8 arrays (shapes may
    differ per row; ImageTransformer shape-buckets before device work).
    Undecodable files become None rows unless ``drop_invalid``.
    """
    raw = read_binary_files(path, recursive=recursive, sample_ratio=sample_ratio,
                            inspect_zip=inspect_zip, seed=seed)
    keep = [i for i, p in enumerate(raw[PATH_COL])
            if str(p).lower().endswith(IMAGE_EXTENSIONS)] if raw.num_rows else []
    raw = raw.take(keep)
    images = [decode_image(b) for b in raw[BYTES_COL]]
    df = DataFrame({
        PATH_COL: raw[PATH_COL],
        IMAGE_COL: np.array(images, dtype=object),
    })
    if drop_invalid:
        mask = np.array([im is not None for im in images], dtype=bool)
        df = df.filter(mask)
    return df
