"""Runnable serving entrypoints for containers/orchestrators.

``python -m mmlspark_tpu.serving coordinator`` — the driver-side
registry (`serving.ServingCoordinator`); ``python -m
mmlspark_tpu.serving worker`` — load a persisted pipeline/transformer
from ``$MODEL_URI`` (any io.fs path: local dir, gs://...), serve it
(`serving.ServingServer`), and register ``$POD_IP:$PORT`` with
``$COORDINATOR_URL``. These are the commands the k8s manifests under
``tools/k8s/`` run (parity: the reference's spark-serving helm chart,
`/root/reference/tools/helm/`); the readiness probe hits the server's
``GET /readyz`` (drain-aware), liveness ``GET /healthz``, counters
``GET /status``, Prometheus exposition ``GET /metrics`` (point a
scrape_config at the workers, or at the coordinator's
``GET /fleet/metrics`` for the merged fleet — docs/observability.md).
``MMLSPARK_TPU_LOGGING_FORMAT=json`` switches workers to structured
JSON logs with per-request trace ids. SIGTERM triggers the server's
graceful drain (``ServingServer.stop``), so a pod delete finishes its
accepted requests before the listener closes.

Environment:
  PORT             listen port (default 8000)
  MODEL_URI        (worker) persisted stage directory to serve
  COORDINATOR_URL  (worker, optional) http://host:port to register with
  POD_IP           (worker, optional) address advertised to the
                   coordinator; defaults to the local hostname
  MAX_BATCH_SIZE / MAX_LATENCY_MS / JOURNAL_SIZE / JOURNAL_TTL /
  MAX_QUEUE        (worker, optional) ServingServer knobs (MAX_QUEUE
                   bounds the accepted-request backlog: beyond it new
                   requests shed with 429 + Retry-After, see
                   docs/resilience.md)
  PIPELINE / BUCKET_BATCHES / ENCODER_THREADS
                   (worker, optional) data-plane knobs: PIPELINE=0
                   falls back to the serial plane, BUCKET_BATCHES=0
                   disables shape-bucket padding (models then see exact
                   live batch sizes, at the cost of per-size jit
                   retraces), ENCODER_THREADS sizes the reply-encoder
                   pool — see docs/serving.md "The data plane"
  BATCH_POLICY     (worker, optional) "adaptive" decides the batch-
                   mate wait per batch from the live arrival rate +
                   per-bucket dispatch latencies (MAX_LATENCY_MS
                   becomes the hard ceiling); default "fixed" keeps
                   the constant knob — docs/serving.md "Adaptive
                   batching"
  WARMUP_PAYLOAD   (worker, optional) a JSON example payload; when set,
                   the worker dispatches one synthetic batch per shape
                   bucket (ServingServer.warmup) BEFORE registering
                   with the coordinator, so no live request ever pays a
                   jit compile — without it the first request at each
                   bucket size traces on the serving path
  JOURNAL_PATH     (worker, optional) durable replay-journal file (any
                   io.fs path — mount a PVC and point this at it, or
                   gs://...): committed replies survive pod restarts,
                   reported as ``journal_recovered`` in GET /status
  SLOW_TRACE_MS    (worker, optional) tail-capture threshold for this
                   worker's route (default 250): requests slower than
                   this — or that end in error/shed/deadline — retain
                   their span tree at ``GET /trace/<id>`` (Perfetto
                   export via ``?format=perfetto``; 0 captures every
                   request — see docs/observability.md "Tracing")
  ADAPTIVE_SLOW_TRACE
                   (worker, optional) 0 pins the tail-capture
                   threshold at SLOW_TRACE_MS forever; by default
                   (1) the threshold tracks the route's own dispatch-
                   latency p95 (floor/ceiling clamped) once enough
                   samples accumulate — see docs/observability.md
                   "Distributed tracing"
  FRONTEND         (both, optional) the socket edge: "eventloop" (the
                   default — selectors-based keep-alive frontend, see
                   docs/serving.md "The socket edge") or "threaded"
                   (the thread-per-connection http.server baseline)
  ACCEPTORS        (worker, optional) number of SO_REUSEPORT accept/
                   event loops sharing the port (default 1). Raise it
                   when /metrics shows serving_accept_loop_busy_ratio
                   pinned near 1.0; setting it > 1 implies REUSE_PORT=1
                   unless REUSE_PORT=0 is forced (which then fails
                   fast at startup)
  IDLE_TIMEOUT     (worker, optional) seconds a keep-alive connection
                   may sit idle between requests (default 60; also the
                   slow-loris mid-request reap clock; 0 disables)
  MODEL_VERSION    (worker, optional) the version label of the model
                   served at boot (default "v1") — the zero-downtime
                   rollout machinery stages/flips later versions via
                   POST /rollout/{stage,flip,rollback,abort} and
                   GET /version; see docs/serving.md "Zero-downtime
                   rollout"
  VERIFY_CHECKPOINTS
                   (worker, optional) 0 disables the strict digest
                   verification a staged checkpoint must pass before
                   it is flip-eligible (leave on: a truncated or
                   corrupt checkpoint must never go live)
  MAX_CONNS_PER_IP (worker, optional) per-peer-address concurrent
                   connection cap at the socket edge: accepts beyond
                   it get an immediate 429 + close (0 = off; a
                   shedding layer in front of MAX_QUEUE)
  MAX_PIPELINED_PER_ITER
                   (worker, optional) HTTP/1.1 pipelining fairness
                   cap: buffered pipelined requests served per
                   connection per event-loop pass (default 16; one
                   flooding connection cannot monopolize a loop)
  TLS_CERT / TLS_KEY
                   (worker, optional) PEM certificate chain + private
                   key: the event-loop edge terminates TLS itself
                   (non-blocking handshakes in the connection state
                   machine — docs/serving.md "TLS at the edge"), so
                   the worker is internet-facing without a fronting
                   proxy. Both or neither; requires FRONTEND=eventloop
  QUANTIZATION     (worker, optional) a JSON QuantizationConfig for
                   the boot model version, e.g.
                   '{"wire_dtype": "uint8", "scale": 0.0039}': request
                   payloads are cast to the wire dtype at dispatch and
                   dequantized on device — docs/serving.md "The
                   quantized wire". Malformed configs fail startup
  CAPTURE_DIR      (worker, optional) opt-in traffic capture: committed
                   request/reply rows (plus sampled shadow-diff rows
                   during rollouts) journal into rotating JSON-line
                   segments under this directory — the feedstock of
                   the retrain loop (docs/streaming.md). Bounded and
                   non-blocking: a slow disk drops sampled batches,
                   never delays replies
  CAPTURE_SAMPLE_EVERY / CAPTURE_MAX_SEGMENTS / CAPTURE_SEGMENT_BYTES
                   (worker, optional) capture knobs: sample every Nth
                   committed batch (default 1 = all), keep at most N
                   segments (default 64) of at most N bytes each
                   (default 4 MiB)
  PUSH_GATEWAY_URL / PUSH_INTERVAL_S
                   (worker, optional) remote-write: POST the worker's
                   metrics exposition (per-server + process registry)
                   to this URL every PUSH_INTERVAL_S seconds (default
                   30) through the resilient HTTP client, with a
                   final flush on shutdown — telemetry for fleets
                   without a scraping Prometheus
  PROFILER_HZ      (worker, optional) the always-on sampling CPU
                   profiler's rate (default 50; served at
                   ``GET /profile/cpu``, windows/diffs over a bounded
                   in-memory ring — docs/observability.md "The
                   postmortem plane"). ``0`` or ``false`` disables
                   the sampler entirely
  INCIDENTS_DIR    (worker, optional) directory for anomaly-triggered
                   incident bundles: when set, every SLO/anomaly
                   firing transition snapshots alert + series +
                   traces + profile window + logs + stats to
                   ``<dir>/<id>/`` (bounded retention, one bundle per
                   alert per cooldown; ``GET /incidents`` lists them,
                   the coordinator merges the fleet at
                   ``GET /fleet/incidents``). Unset, ``0`` or
                   ``false`` disables capture — nothing is written
  INCIDENT_COOLDOWN_S / INCIDENT_MAX
                   (worker, optional) incident-capture knobs: minimum
                   seconds between bundles for the same alert
                   (default 300) and the on-disk bundle cap (default
                   16, oldest evicted)
"""

import os
import signal
import socket
import sys
import threading
import time


def _env_float(name, default):
    v = os.environ.get(name)
    return default if v in (None, "") else float(v)


def _json_env(name):
    v = os.environ.get(name)
    if v in (None, ""):
        return None
    import json
    return json.loads(v)


def run_coordinator() -> None:
    from mmlspark_tpu.serving.server import ServingCoordinator
    port = int(os.environ.get("PORT", "8000"))
    stale = _env_float("STALE_AFTER", 0.0)   # 0 = never expire
    coord = ServingCoordinator(
        host="0.0.0.0", port=port, stale_after=stale or None,
        frontend=os.environ.get("FRONTEND", "eventloop")).start()
    print(f"[serving] coordinator listening on :{coord.port}", flush=True)
    _wait_forever(coord.stop)


def run_worker() -> None:
    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.serving.server import (
        ServingCoordinator, ServingServer)

    uri = os.environ.get("MODEL_URI")
    if not uri:
        raise SystemExit("worker needs MODEL_URI (a persisted stage dir)")
    model = PipelineStage.load(uri)
    port = int(os.environ.get("PORT", "8000"))
    ttl = _env_float("JOURNAL_TTL", 0.0)
    acceptors = int(_env_float("ACCEPTORS", 1))
    capture = None
    capture_dir = os.environ.get("CAPTURE_DIR")
    if capture_dir:
        from mmlspark_tpu.serving.capture import TrafficCapture
        capture = TrafficCapture(
            capture_dir,
            sample_every=int(_env_float("CAPTURE_SAMPLE_EVERY", 1)),
            max_segments=int(_env_float("CAPTURE_MAX_SEGMENTS", 64)),
            max_segment_bytes=int(
                _env_float("CAPTURE_SEGMENT_BYTES", 4 << 20)))
        print(f"[serving] capturing traffic to {capture_dir}",
              flush=True)
    srv = ServingServer(
        model, host="0.0.0.0", port=port,
        max_batch_size=int(_env_float("MAX_BATCH_SIZE", 64)),
        max_latency_ms=_env_float("MAX_LATENCY_MS", 10.0),
        journal_size=int(_env_float("JOURNAL_SIZE", 4096)),
        journal_ttl=ttl if ttl > 0 else None,
        journal_path=os.environ.get("JOURNAL_PATH") or None,
        max_queue=int(_env_float("MAX_QUEUE", 1024)),
        pipeline=_env_float("PIPELINE", 1) != 0,
        bucket_batches=_env_float("BUCKET_BATCHES", 1) != 0,
        encoder_threads=int(_env_float("ENCODER_THREADS", 2)),
        slow_trace_ms=_env_float("SLOW_TRACE_MS", 250.0),
        adaptive_slow_trace=_env_float("ADAPTIVE_SLOW_TRACE", 1) != 0,
        frontend=os.environ.get("FRONTEND", "eventloop"),
        acceptors=acceptors,
        # ACCEPTORS > 1 needs SO_REUSEPORT (N loops cannot share one
        # listener); default it on so the one knob is enough
        reuse_port=_env_float("REUSE_PORT",
                              1 if acceptors > 1 else 0) != 0,
        idle_timeout=_env_float("IDLE_TIMEOUT", 60.0),
        max_conns_per_ip=int(_env_float("MAX_CONNS_PER_IP", 0)),
        max_pipelined_per_iter=int(
            _env_float("MAX_PIPELINED_PER_ITER", 16)),
        model_version=os.environ.get("MODEL_VERSION", "v1"),
        verify_checkpoints=_env_float("VERIFY_CHECKPOINTS", 1) != 0,
        batch_policy=os.environ.get("BATCH_POLICY", "fixed"),
        capture=capture,
        tls_cert=os.environ.get("TLS_CERT") or None,
        tls_key=os.environ.get("TLS_KEY") or None,
        quantization=(_json_env("QUANTIZATION")),
        # TSDB=0 disables the retrospective plane; a JSON dict
        # overrides its knobs (interval_s, tiers, snapshot_dir,
        # rules, watches, ...); unset = the stock plane
        tsdb=(False if os.environ.get("TSDB") in ("0", "false")
              else _json_env("TSDB")),
        # PROFILER_HZ=0/false disables the always-on sampler; any
        # other value overrides the 50 hz default
        cpu_profiler=(False
                      if os.environ.get("PROFILER_HZ") in ("0", "false")
                      else ({"hz": _env_float("PROFILER_HZ", 50.0)}
                            if os.environ.get("PROFILER_HZ")
                            else None)),
        # INCIDENTS_DIR enables anomaly-triggered incident capture
        incidents=(None
                   if os.environ.get("INCIDENTS_DIR") in (None, "", "0",
                                                          "false")
                   else {"dir": os.environ["INCIDENTS_DIR"],
                         "cooldown_s": _env_float(
                             "INCIDENT_COOLDOWN_S", 300.0),
                         "max_incidents": int(_env_float(
                             "INCIDENT_MAX", 16))}))
    warm = os.environ.get("WARMUP_PAYLOAD")
    if warm:
        # warm BEFORE start(): the socket is already bound (early
        # connects sit in the accept backlog), but no handler/executor
        # thread is live yet, so warmup's model calls can never run
        # concurrently with a real dispatch — and every bucket is
        # compiled before the first request is read
        import json as _json
        sizes = srv.warmup(_json.loads(warm))
        print(f"[serving] warmed buckets {sizes}", flush=True)
    srv.start()
    print(f"[serving] worker serving {uri} on :{srv.port}", flush=True)

    pusher = None
    push_url = os.environ.get("PUSH_GATEWAY_URL")
    if push_url:
        from mmlspark_tpu.core.telemetry import REGISTRY, MetricsPusher
        pusher = MetricsPusher(
            push_url, registries=(srv.registry, REGISTRY),
            interval_s=_env_float("PUSH_INTERVAL_S", 30.0)).start()
        print(f"[serving] pushing metrics to {push_url}", flush=True)

    coord_url = os.environ.get("COORDINATOR_URL")
    if coord_url:
        ip = os.environ.get("POD_IP") or socket.gethostbyname(
            socket.gethostname())
        ServingCoordinator.register_worker(coord_url, ip, srv.port)
        print(f"[serving] registered {ip}:{srv.port} with {coord_url}",
              flush=True)

        # periodic re-register: registration is idempotent, so this is
        # a heartbeat that repopulates a restarted (in-memory-registry)
        # coordinator without operator intervention
        def heartbeat():
            interval = float(os.environ.get("REGISTER_INTERVAL", "10"))
            while True:
                time.sleep(interval)
                try:
                    ServingCoordinator.register_worker(coord_url, ip,
                                                       srv.port)
                except Exception:  # noqa: BLE001 — coordinator down;
                    pass           # keep serving, retry next tick

        threading.Thread(target=heartbeat, daemon=True).start()

    def shutdown():
        # drain first (accepted requests finish), then flush the final
        # metrics push so the gateway sees the worker's terminal counts
        srv.stop()
        if pusher is not None:
            pusher.stop()

    _wait_forever(shutdown)


def _wait_forever(stop) -> None:
    done = threading.Event()

    def handler(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, handler)
    signal.signal(signal.SIGINT, handler)
    done.wait()
    stop()


def main() -> None:
    if os.environ.get("MMLSPARK_TPU_SERVING_CPU") == "1":
        # dev boxes whose sitecustomize pins an accelerator platform:
        # flip before the first device touch (env vars alone cannot)
        from mmlspark_tpu.parallel.topology import use_cpu_devices
        use_cpu_devices(1)
    role = sys.argv[1] if len(sys.argv) > 1 else ""
    if role == "coordinator":
        run_coordinator()
    elif role == "worker":
        run_worker()
    else:
        raise SystemExit(
            "usage: python -m mmlspark_tpu.serving coordinator|worker")


if __name__ == "__main__":
    main()
